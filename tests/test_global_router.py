"""Global router (PR 18): pool classification policy, pool discovery,
closed-loop proxying over real pools, chaos degrade, and the scale-out
snapshot-on-subscribe e2e (a late-started frontend replica inherits the
in-flight slot picture).
"""

import asyncio
import json
import uuid

import aiohttp

from dynamo_tpu import chaos
from dynamo_tpu.disagg.prefill_router import ConditionalDisaggConfig
from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
from dynamo_tpu.frontend.pipeline import _route_attr
from dynamo_tpu.frontend.request_trace import RequestTracker, X_POOL_HEADER
from dynamo_tpu.global_router import (FrontendView, GlobalRouterConfig,
                                      GlobalRouterService, PoolClassifier,
                                      PoolDirectory, PoolView)
from dynamo_tpu.global_router.policy import estimate_isl
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.protocols.model_card import ModelDeploymentCard
from dynamo_tpu.router.kv_router import make_kv_route_factory
from dynamo_tpu.runtime import DistributedRuntime, RouterMode, RuntimeConfig
from dynamo_tpu.runtime.discovery import Instance

MODEL = "gr-model"


# --------------------------- classifier units --------------------------------


def mk_pool(ns, disagg=False, n_fe=1, per_tok=None, flat=None, inflight=0):
    p = PoolView(ns)
    for i in range(n_fe):
        p.frontends[i] = FrontendView(i, f"127.0.0.1:{9000 + i}", ns)
    p.models[MODEL] = {"both", "prefill"} if disagg else {"both"}
    p.ttft_per_token_ewma_s = per_tok
    p.ttft_ewma_s = flat
    p.inflight = inflight
    return p


def test_classifier_prefill_bound_routes_to_disagg_pool():
    c = PoolClassifier(GlobalRouterConfig())
    pools = [mk_pool("agg"), mk_pool("dis", disagg=True)]
    # long prompt + short completion: clears BOTH thresholds
    # (isl >= 2048, ratio 4096/(4096+64) >= 0.7)
    d = c.classify(pools, isl=4096, max_tokens=64)
    assert d.pool == "dis"
    assert d.reason == "disagg"
    assert d.prefill_ratio > 0.9
    # long prompt but LONG completion too: decode-bound, agg wins
    d = c.classify(pools, isl=4096, max_tokens=8192)
    assert d.pool == "agg"
    assert d.reason == "agg"


def test_classifier_decode_bound_routes_to_agg_pool():
    c = PoolClassifier(GlobalRouterConfig())
    pools = [mk_pool("agg"), mk_pool("dis", disagg=True)]
    d = c.classify(pools, isl=100, max_tokens=256)
    assert d.pool == "agg"
    assert d.reason == "agg"
    # both candidate classes are scored, the winner's score present
    assert "agg" in d.scores


def test_classifier_falls_back_across_classes():
    """A preferred class with no live pool must degrade to the other
    class (reason tagged _fallback) rather than 503."""
    c = PoolClassifier(GlobalRouterConfig())
    aggs = [mk_pool("a0"), mk_pool("a1")]
    d = c.classify(aggs, isl=4096, max_tokens=64)  # wants disagg
    assert d.pool in ("a0", "a1")
    assert d.reason == "disagg_fallback"
    diss = [mk_pool("d0", disagg=True), mk_pool("d1", disagg=True)]
    d = c.classify(diss, isl=100, max_tokens=256)  # wants agg
    assert d.reason == "agg_fallback"


def test_classifier_single_pool_and_empty():
    c = PoolClassifier()
    d = c.classify([mk_pool("solo")], isl=4096, max_tokens=64)
    assert d.pool == "solo"
    assert d.reason == "only_pool"
    try:
        c.classify([], isl=10)
        assert False, "empty pool list must raise"
    except ValueError:
        pass


def test_classifier_ttft_then_load_tiebreak():
    cfg = GlobalRouterConfig(load_penalty_s=0.010)
    c = PoolClassifier(cfg)
    fast = mk_pool("fast", per_tok=1e-5)
    slow = mk_pool("slow", per_tok=5e-5)
    assert c.classify([fast, slow], isl=1000, max_tokens=512).pool == "fast"
    # pile enough in-flight load on the fast pool and the ITL-headroom
    # penalty must flip the decision: 10ms/req beats a 40ms TTFT edge
    # at >= 5 queued requests per frontend
    fast.inflight = 8
    assert c.classify([fast, slow], isl=1000, max_tokens=512).pool == "slow"


def test_estimate_isl_shapes():
    assert estimate_isl({"prompt": [1, 2, 3, 4, 5]}) == 5  # exact tokens
    assert estimate_isl({"prompt": "x" * 400}) == 100      # ~4 chars/tok
    assert estimate_isl({"messages": [{"role": "user",
                                       "content": "y" * 80}]}) == 20
    assert estimate_isl({}) == 1  # never zero


def test_request_tracker_pool_attribution():
    """The x-dyn-pool header stamped by the grouter must flow into the
    routed hop and the request_end record."""
    t = RequestTracker.from_headers({X_POOL_HEADER: "pool7"},
                                    request_id="r1", model=MODEL,
                                    sink=None)
    assert t.pool == "pool7"
    t.on_routed(instance_id=3)
    routed = [h for h in t.hops if h["hop"] == "routed"]
    assert routed and routed[0]["pool"] == "pool7"
    rec = t.finish(finish_reason="stop")
    assert rec["request"]["pool"] == "pool7"
    # a direct (un-proxied) request carries no pool at all
    t2 = RequestTracker.from_headers({}, request_id="r2", model=MODEL,
                                     sink=None)
    assert t2.pool is None
    assert "pool" not in t2.finish()["request"]


# --------------------------- pool directory ----------------------------------


async def test_pool_directory_tracks_frontends_and_models():
    rt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem",
                             event_plane="inproc"),
        cluster_id=uuid.uuid4().hex).start()
    d = await PoolDirectory(rt).start()
    try:
        fe = Instance(namespace="pa", component="frontend",
                      endpoint="http", instance_id=11,
                      address="127.0.0.1:8101",
                      metadata={"http_addr": "127.0.0.1:8101",
                                "pool": "pa"})
        await rt.discovery.put(fe.key(), fe.to_dict())
        mdc = ModelDeploymentCard(name=MODEL, namespace="pa",
                                  runtime_config={"role": "both"})
        await rt.discovery.put(mdc.key(instance_id=1), mdc.to_dict())

        async def poll(cond):
            for _ in range(150):
                if cond():
                    return True
                await asyncio.sleep(0.02)
            return cond()

        assert await poll(lambda: d.pools_for_model(MODEL))
        pool = d.pools()["pa"]
        assert pool.frontends[11].http_addr == "127.0.0.1:8101"
        assert not pool.is_disagg
        # a prefill card from a second worker flips the pool's class
        pmdc = ModelDeploymentCard(name=MODEL, namespace="pa",
                                   component="prefill",
                                   runtime_config={"role": "prefill"})
        await rt.discovery.put(pmdc.key(instance_id=2), pmdc.to_dict())
        assert await poll(lambda: d.pools()["pa"].is_disagg)
        # non-frontend instances are ignored
        w = Instance(namespace="pa", component="backend",
                     endpoint="generate", instance_id=12,
                     address="127.0.0.1:9999")
        await rt.discovery.put(w.key(), w.to_dict())
        await asyncio.sleep(0.05)
        assert set(d.pools()["pa"].frontends) == {11}
        # dropping the prefill card reverts the class (the "both" card
        # still claims the model); dropping the frontend empties the
        # pool out of pools_for_model, then GC removes it entirely
        await rt.discovery.delete(pmdc.key(instance_id=2))
        assert await poll(lambda: not d.pools()["pa"].is_disagg)
        assert d.pools_for_model(MODEL)
        await rt.discovery.delete(fe.key())
        assert await poll(lambda: not d.pools_for_model(MODEL))
        await rt.discovery.delete(mdc.key(instance_id=1))
        assert await poll(lambda: "pa" not in d.pools())
    finally:
        await d.close()
        await rt.shutdown()


# --------------------------- closed loop -------------------------------------

# grouter estimates ~4 chars/token; the byte tokenizer counts 1/char.
# Scaled-down thresholds keep the smoke geometry in CPU-milliseconds.
GROUTER_MIN_ISL = 64
FRONTEND_MIN_ISL = 256
LONG_CHARS = 400
SHORT_CHARS = 60


async def start_pool(cluster, ns, *, disagg, frontends=1, engine_kw=None):
    wrt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem",
                             event_plane="inproc", namespace=ns),
        cluster_id=cluster).start()
    common = dict(model_name=MODEL, block_size=16, base_step_s=0.0005,
                  prefill_s_per_token=0.0, decode_s_per_seq=0.0)
    common.update(engine_kw or {})
    workers = [await MockerWorker(wrt, MockEngineArgs(**common),
                                  namespace=ns).start()]
    if disagg:
        workers.append(await MockerWorker(
            wrt, MockEngineArgs(role="prefill", **common),
            namespace=ns, component="prefill").start())
    fes = []
    for _ in range(frontends):
        rt = await DistributedRuntime(
            config=RuntimeConfig(discovery_backend="mem",
                                 event_plane="inproc", namespace=ns),
            cluster_id=cluster).start()
        manager = ModelManager()
        watcher = await ModelWatcher(
            rt, manager, router_mode=RouterMode.KV,
            make_route=make_kv_route_factory(rt),
            disagg_config=ConditionalDisaggConfig(
                min_effective_isl=FRONTEND_MIN_ISL,
                min_effective_ratio=0.7),
            namespaces={ns}).start()
        svc = await HttpService(rt, manager, host="127.0.0.1", port=0,
                                advertise=True).start()
        fes.append({"rt": rt, "manager": manager, "watcher": watcher,
                    "svc": svc,
                    "port": svc._runner.addresses[0][1]})
    return {"ns": ns, "wrt": wrt, "workers": workers, "frontends": fes}


async def stop_pool(pool):
    for fe in pool["frontends"]:
        await fe["svc"].close()
        await fe["watcher"].close()
        await fe["rt"].shutdown()
    for w in pool["workers"]:
        await w.close()
    await pool["wrt"].shutdown()


async def wait_ready(pools, grouter, n_pools):
    for pool in pools:
        for fe in pool["frontends"]:
            for _ in range(200):
                if fe["manager"].get(MODEL):
                    break
                await asyncio.sleep(0.02)
            assert fe["manager"].get(MODEL)
    for _ in range(200):
        if len(grouter.directory.pools_for_model(MODEL)) >= n_pools:
            break
        await asyncio.sleep(0.02)
    assert len(grouter.directory.pools_for_model(MODEL)) >= n_pools


async def sse_text(session, url, body):
    out = []
    async with session.post(f"{url}/v1/completions", json=body) as r:
        assert r.status == 200, (r.status, await r.text())
        async for raw in r.content:
            line = raw.decode().strip()
            if not line.startswith("data:"):
                continue
            data = line[5:].strip()
            if data == "[DONE]":
                break
            for ch in json.loads(data).get("choices", ()):
                if ch.get("text"):
                    out.append(ch["text"])
    return "".join(out)


def trace(n_per_class, max_tokens=8):
    reqs = []
    for i in range(n_per_class):
        reqs.append({"model": MODEL, "prompt": "s" * SHORT_CHARS + str(i),
                     "max_tokens": max_tokens, "stream": True,
                     "seed": 100 + i})
        reqs.append({"model": MODEL, "prompt": "l" * LONG_CHARS + str(i),
                     "max_tokens": max_tokens, "stream": True,
                     "seed": 200 + i})
    return reqs


async def test_grouter_closed_loop_routes_both_classes_byte_identical():
    """2 pools (agg + disagg) x 2 frontends: short prompts land agg,
    long prompts clear the conditional-disagg thresholds and land
    disagg, and every token stream is byte-identical to hitting one
    frontend directly (MockEngine streams are position-addressed by
    seed, so the proxy layer must add zero token-level noise)."""
    cluster = uuid.uuid4().hex
    p0 = await start_pool(cluster, "pool0", disagg=False, frontends=2)
    p1 = await start_pool(cluster, "pool1", disagg=True, frontends=2)
    grt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem",
                             event_plane="inproc", namespace="global"),
        cluster_id=cluster).start()
    grouter = await GlobalRouterService(
        grt, host="127.0.0.1", port=0,
        config=GlobalRouterConfig(disagg_min_isl=GROUTER_MIN_ISL,
                                  disagg_ratio=0.7),
        staleness_scrape_s=30.0).start()
    try:
        await wait_ready([p0, p1], grouter, n_pools=2)
        reqs = trace(4)
        async with aiohttp.ClientSession() as s:
            via_grouter = await asyncio.gather(*(
                sse_text(s, f"http://127.0.0.1:{grouter.port}", b)
                for b in reqs))
            direct = await asyncio.gather(*(
                sse_text(s, f"http://127.0.0.1:{p0['frontends'][0]['port']}",
                         b) for b in reqs))
        assert all(via_grouter), "empty token stream through the grouter"
        assert via_grouter == direct, "proxy layer changed token bytes"
        routed = dict(grouter._routed)
        assert ("pool0", "agg") in routed and routed[("pool0", "agg")] == 4
        assert ("pool1", "disagg") in routed
        assert routed[("pool1", "disagg")] == 4
        # route latency got sampled for every forward
        assert grouter.route_latency_quantiles()["count"] == len(reqs)
        # unknown model 404s instead of hanging
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{grouter.port}/v1/completions",
                json={"model": "nope", "prompt": "x"},
            ) as r:
                assert r.status == 404
            # merged model list across pools
            async with s.get(
                f"http://127.0.0.1:{grouter.port}/v1/models") as r:
                models = [m["id"] for m in (await r.json())["data"]]
                assert models == [MODEL]
    finally:
        await grouter.close()
        await grt.shutdown()
        await stop_pool(p0)
        await stop_pool(p1)


async def test_grouter_classify_chaos_degrades_to_round_robin():
    """Chaos seam grouter.classify: a policy fault must degrade to
    round-robin (reason classify_error_rr) and keep serving — never
    drop the request."""
    cluster = uuid.uuid4().hex
    p0 = await start_pool(cluster, "pool0", disagg=False)
    grt = await DistributedRuntime(
        config=RuntimeConfig(discovery_backend="mem",
                             event_plane="inproc", namespace="global"),
        cluster_id=cluster).start()
    grouter = await GlobalRouterService(
        grt, host="127.0.0.1", port=0,
        staleness_scrape_s=30.0).start()
    plane = chaos.ChaosPlane(seed=7)
    plane.rule("grouter.classify", "fail", times=1)
    try:
        await wait_ready([p0], grouter, n_pools=1)
        body = {"model": MODEL, "prompt": "hello world", "max_tokens": 4,
                "stream": True, "seed": 5}
        with plane:
            async with aiohttp.ClientSession() as s:
                first = await sse_text(
                    s, f"http://127.0.0.1:{grouter.port}", body)
                second = await sse_text(
                    s, f"http://127.0.0.1:{grouter.port}", body)
        assert plane.injections
        assert first and first == second  # degraded path, same bytes
        routed = dict(grouter._routed)
        assert routed.get(("pool0", "classify_error_rr")) == 1
        assert routed.get(("pool0", "only_pool")) == 1
    finally:
        await grouter.close()
        await grt.shutdown()
        await stop_pool(p0)


async def test_late_joining_frontend_inherits_inflight_slots():
    """Frontend scale-out e2e: requests are IN FLIGHT on replica A when
    replica B starts.  B's KvRouter must inherit A's slot view via
    replica-sync snapshot-on-subscribe — within a tick, not after the
    requests finish."""
    cluster = uuid.uuid4().hex
    ns = "poolz"
    # slow decode keeps the requests in flight while B boots
    pool = await start_pool(cluster, ns, disagg=False,
                            engine_kw=dict(base_step_s=0.02))
    fe_a = pool["frontends"][0]
    try:
        for _ in range(200):
            if fe_a["manager"].get(MODEL):
                break
            await asyncio.sleep(0.02)
        assert fe_a["manager"].get(MODEL)
        url = f"http://127.0.0.1:{fe_a['port']}"
        bodies = [{"model": MODEL, "prompt": "p" * 120 + str(i),
                   "max_tokens": 60, "stream": True, "seed": i}
                  for i in range(3)]
        async with aiohttp.ClientSession() as s:
            inflight = [asyncio.create_task(sse_text(s, url, b))
                        for b in bodies]
            try:
                seqs_a = _route_attr(
                    fe_a["manager"].get(MODEL).migration.route,
                    "sequences")
                for _ in range(200):
                    if len(seqs_a._reqs) >= 3:
                        break
                    await asyncio.sleep(0.02)
                assert len(seqs_a._reqs) >= 3, "requests never took slots"

                # replica B joins late: watcher only, no HTTP needed
                rt_b = await DistributedRuntime(
                    config=RuntimeConfig(discovery_backend="mem",
                                         event_plane="inproc",
                                         namespace=ns),
                    cluster_id=cluster).start()
                manager_b = ModelManager()
                watcher_b = await ModelWatcher(
                    rt_b, manager_b, router_mode=RouterMode.KV,
                    make_route=make_kv_route_factory(rt_b),
                    namespaces={ns}).start()
                try:
                    for _ in range(200):
                        if manager_b.get(MODEL):
                            break
                        await asyncio.sleep(0.02)
                    route_b = manager_b.get(MODEL).migration.route
                    seqs_b = _route_attr(route_b, "sequences")
                    sync_b = _route_attr(route_b, "sync")
                    peer_keys = None
                    for _ in range(200):
                        peer_keys = [k for k in seqs_b._reqs if "@" in k]
                        if len(peer_keys) >= 3:
                            break
                        await asyncio.sleep(0.02)
                    assert len(peer_keys) >= 3, (
                        f"late joiner never inherited A's in-flight "
                        f"slots: {list(seqs_b._reqs)}")
                    assert sync_b.stats()["snapshots_applied"] >= 1
                    # B's per-worker load view matches A's for the
                    # in-flight set (A counts them as own, B as peer)
                    wid = pool["workers"][0].served.instance_id
                    assert seqs_b.active_blocks(wid) > 0
                finally:
                    await watcher_b.close()
                    await rt_b.shutdown()
            finally:
                texts = await asyncio.gather(*inflight)
        assert all(texts)
        # ...and the entries drain after the requests finish (frees
        # propagate the same path the adds did)
        for _ in range(200):
            if not seqs_a._reqs:
                break
            await asyncio.sleep(0.02)
        assert not seqs_a._reqs
    finally:
        await stop_pool(pool)
