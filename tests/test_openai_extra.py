"""/v1/responses, /v1/files, /v1/batches against the mocker stack.

Ref behavior model: lib/llm/src/http/service/openai.rs:2297 (responses
family), :3112 (batches/files).
"""

import asyncio
import json
import uuid

import aiohttp

from dynamo_tpu.frontend import HttpService, ModelManager, ModelWatcher
from dynamo_tpu.mocker import MockEngineArgs, MockerWorker
from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig


def fresh_runtime() -> DistributedRuntime:
    cfg = RuntimeConfig(discovery_backend="mem", event_plane="inproc")
    return DistributedRuntime(config=cfg, cluster_id=uuid.uuid4().hex)


async def start_stack(model_name="api-model", **kw):
    rt = await fresh_runtime().start()
    args = MockEngineArgs(model_name=model_name, block_size=4,
                          base_step_s=0.0002, prefill_s_per_token=0.0,
                          decode_s_per_seq=0.0, **kw)
    worker = await MockerWorker(rt, args).start()
    manager = ModelManager()
    watcher = await ModelWatcher(rt, manager).start()
    service = await HttpService(rt, manager, host="127.0.0.1",
                                port=0).start()
    port = service._runner.addresses[0][1]
    for _ in range(100):
        if manager.get(model_name):
            break
        await asyncio.sleep(0.02)
    assert manager.get(model_name)
    return rt, worker, watcher, service, f"http://127.0.0.1:{port}"


async def stop_stack(rt, worker, watcher, service):
    await service.extra.close()
    await service.close()
    await watcher.close()
    await worker.close()
    await rt.shutdown()


async def test_responses_unary_and_chaining():
    stack = await start_stack()
    rt, worker, watcher, service, url = stack
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": "api-model", "input": "hello there",
                    "instructions": "be brief", "max_output_tokens": 8}
            async with s.post(f"{url}/v1/responses", json=body) as r:
                assert r.status == 200, await r.text()
                resp = await r.json()
            assert resp["object"] == "response"
            assert resp["status"] == "completed"
            msg = resp["output"][-1]
            assert msg["type"] == "message" and msg["role"] == "assistant"
            text = msg["content"][0]["text"]
            assert text == resp["output_text"] and text
            assert resp["usage"]["input_tokens"] > 0
            assert resp["usage"]["output_tokens"] > 0

            # retrieve by id
            async with s.get(f"{url}/v1/responses/{resp['id']}") as r:
                assert r.status == 200
                assert (await r.json())["id"] == resp["id"]

            # chain a second turn; the stored transcript grows
            body2 = {"model": "api-model", "input": "and again",
                     "previous_response_id": resp["id"],
                     "max_output_tokens": 8}
            async with s.post(f"{url}/v1/responses", json=body2) as r:
                assert r.status == 200
                resp2 = await r.json()
            msgs = service.extra.responses.messages(resp2["id"])
            roles = [m["role"] for m in msgs]
            assert roles == ["system", "user", "assistant", "user",
                             "assistant"]

            # structured input items are accepted
            body3 = {"model": "api-model", "input": [
                {"type": "message", "role": "user",
                 "content": [{"type": "input_text", "text": "hi"}]}],
                "max_output_tokens": 4}
            async with s.post(f"{url}/v1/responses", json=body3) as r:
                assert r.status == 200

            # delete
            async with s.delete(f"{url}/v1/responses/{resp['id']}") as r:
                assert (await r.json())["deleted"] is True
            async with s.get(f"{url}/v1/responses/{resp['id']}") as r:
                assert r.status == 404

            # chaining a deleted/unknown id 404s
            async with s.post(f"{url}/v1/responses", json={
                    "model": "api-model", "input": "x",
                    "previous_response_id": resp["id"]}) as r:
                assert r.status == 404
    finally:
        await stop_stack(*stack[:4])


async def test_responses_streaming_events():
    stack = await start_stack()
    rt, worker, watcher, service, url = stack
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": "api-model", "input": "stream this",
                    "stream": True, "max_output_tokens": 6}
            events = []
            async with s.post(f"{url}/v1/responses", json=body) as r:
                assert r.status == 200
                async for line in r.content:
                    line = line.decode().strip()
                    if line.startswith("data: "):
                        events.append(json.loads(line[6:]))
        types = [e["type"] for e in events]
        assert types[0] == "response.created"
        assert "response.output_text.delta" in types
        assert types[-2] == "response.output_text.done"
        assert types[-1] == "response.completed"
        deltas = "".join(e["delta"] for e in events
                         if e["type"] == "response.output_text.delta")
        done = next(e for e in events
                    if e["type"] == "response.output_text.done")
        final = events[-1]["response"]
        assert deltas == done["text"] == final["output_text"]
        assert final["status"] == "completed"
        # sequence numbers increase monotonically
        seqs = [e["sequence_number"] for e in events]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        # the streamed response is stored for chaining
        assert service.extra.responses.get(final["id"]) is not None
    finally:
        await stop_stack(*stack[:4])


async def test_files_roundtrip():
    stack = await start_stack()
    rt, worker, watcher, service, url = stack
    try:
        async with aiohttp.ClientSession() as s:
            # multipart upload
            form = aiohttp.FormData()
            form.add_field("purpose", "batch")
            form.add_field("file", b"line1\nline2\n",
                           filename="data.jsonl")
            async with s.post(f"{url}/v1/files", data=form) as r:
                assert r.status == 200, await r.text()
                meta = await r.json()
            assert meta["object"] == "file"
            assert meta["bytes"] == 12
            assert meta["filename"] == "data.jsonl"
            fid = meta["id"]

            async with s.get(f"{url}/v1/files") as r:
                ids = [f["id"] for f in (await r.json())["data"]]
            assert fid in ids
            async with s.get(f"{url}/v1/files/{fid}/content") as r:
                assert await r.read() == b"line1\nline2\n"
            async with s.delete(f"{url}/v1/files/{fid}") as r:
                assert (await r.json())["deleted"] is True
            async with s.get(f"{url}/v1/files/{fid}") as r:
                assert r.status == 404
            # path traversal attempts are 404s, not filesystem reads
            async with s.get(f"{url}/v1/files/..%2F..%2Fetc") as r:
                assert r.status == 404
    finally:
        await stop_stack(*stack[:4])


async def test_upload_size_cap_and_streaming():
    """Multipart uploads stream to disk in bounded chunks with a hard
    size cap: an over-cap body is a 413 (for the JSON shape too), leaves
    no partial file behind, and an under-cap upload still round-trips."""
    import os

    stack = await start_stack()
    rt, worker, watcher, service, url = stack
    try:
        store = service.extra.files
        store.max_upload_bytes = 1024
        async with aiohttp.ClientSession() as s:
            form = aiohttp.FormData()
            form.add_field("purpose", "batch")
            form.add_field("file", b"x" * 4096, filename="big.bin")
            async with s.post(f"{url}/v1/files", data=form) as r:
                assert r.status == 413, await r.text()
                assert (await r.json())["error"]["type"] == \
                    "request_too_large"
            # the JSON convenience shape honors the same cap
            async with s.post(f"{url}/v1/files", json={
                    "purpose": "batch", "content": "y" * 4096}) as r:
                assert r.status == 413
            # no partial payloads or staging temp files leaked
            async with s.get(f"{url}/v1/files") as r:
                assert (await r.json())["data"] == []
            assert not [n for n in os.listdir(store.root)
                        if n.endswith(".tmp")]
            # under the cap: streamed upload still lands intact
            form = aiohttp.FormData()
            form.add_field("purpose", "batch")
            form.add_field("file", b"z" * 600, filename="ok.bin")
            async with s.post(f"{url}/v1/files", data=form) as r:
                assert r.status == 200, await r.text()
                meta = await r.json()
            assert meta["bytes"] == 600
            async with s.get(
                    f"{url}/v1/files/{meta['id']}/content") as r:
                assert await r.read() == b"z" * 600
    finally:
        await stop_stack(*stack[:4])


async def test_batches_end_to_end():
    stack = await start_stack()
    rt, worker, watcher, service, url = stack
    try:
        lines = [
            json.dumps({
                "custom_id": f"req-{i}",
                "method": "POST", "url": "/v1/chat/completions",
                "body": {"model": "api-model",
                         "messages": [{"role": "user",
                                       "content": f"item {i}"}],
                         "max_tokens": 4},
            }) for i in range(5)
        ]
        # one bad line: unknown model -> lands in request_counts.failed
        lines.append(json.dumps({
            "custom_id": "req-bad",
            "method": "POST", "url": "/v1/chat/completions",
            "body": {"model": "nope", "messages": [
                {"role": "user", "content": "x"}]},
        }))
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{url}/v1/files", json={
                    "purpose": "batch", "filename": "in.jsonl",
                    "content": "\n".join(lines) + "\n"}) as r:
                assert r.status == 200, await r.text()
                fid = (await r.json())["id"]
            async with s.post(f"{url}/v1/batches", json={
                    "input_file_id": fid,
                    "endpoint": "/v1/chat/completions",
                    "completion_window": "24h"}) as r:
                assert r.status == 200, await r.text()
                batch = await r.json()
            assert batch["status"] in ("validating", "in_progress")
            bid = batch["id"]
            for _ in range(200):
                async with s.get(f"{url}/v1/batches/{bid}") as r:
                    batch = await r.json()
                if batch["status"] == "completed":
                    break
                await asyncio.sleep(0.05)
            assert batch["status"] == "completed"
            assert batch["request_counts"] == {
                "total": 6, "completed": 5, "failed": 1}
            out_id = batch["output_file_id"]
            async with s.get(f"{url}/v1/files/{out_id}/content") as r:
                out_lines = [json.loads(x) for x in
                             (await r.read()).decode().splitlines()]
        by_cid = {o["custom_id"]: o for o in out_lines}
        assert set(by_cid) == {f"req-{i}" for i in range(5)} | {"req-bad"}
        ok = by_cid["req-0"]["response"]
        assert ok["status_code"] == 200
        assert ok["body"]["choices"][0]["message"]["content"]
        assert by_cid["req-bad"]["response"]["status_code"] == 404
        # batch listing sees it
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{url}/v1/batches") as r:
                assert bid in [b["id"] for b in (await r.json())["data"]]
    finally:
        await stop_stack(*stack[:4])


async def test_batch_cancel_and_validation():
    stack = await start_stack()
    rt, worker, watcher, service, url = stack
    try:
        async with aiohttp.ClientSession() as s:
            # bad endpoint rejected
            async with s.post(f"{url}/v1/batches", json={
                    "input_file_id": "file-x",
                    "endpoint": "/v1/nope"}) as r:
                assert r.status == 400
            # missing file rejected
            async with s.post(f"{url}/v1/batches", json={
                    "input_file_id": "file-missing",
                    "endpoint": "/v1/chat/completions"}) as r:
                assert r.status == 404
            # cancel a running batch
            many = "\n".join(json.dumps({
                "custom_id": f"c{i}", "url": "/v1/chat/completions",
                "body": {"model": "api-model",
                         "messages": [{"role": "user", "content": "x"}],
                         "max_tokens": 64}}) for i in range(50))
            async with s.post(f"{url}/v1/files", json={
                    "purpose": "batch", "filename": "big.jsonl",
                    "content": many}) as r:
                fid = (await r.json())["id"]
            async with s.post(f"{url}/v1/batches", json={
                    "input_file_id": fid,
                    "endpoint": "/v1/chat/completions"}) as r:
                bid = (await r.json())["id"]
            async with s.post(f"{url}/v1/batches/{bid}/cancel") as r:
                assert r.status == 200
            for _ in range(100):
                async with s.get(f"{url}/v1/batches/{bid}") as r:
                    b = await r.json()
                if b["status"] in ("cancelled", "completed"):
                    break
                await asyncio.sleep(0.05)
            assert b["status"] in ("cancelled", "completed")
    finally:
        await stop_stack(*stack[:4])
