"""Fleet aggregator: one merged view of every live instance's state.

PRs 6-7 gave each *process* deep observability; this module is the
fleet-level half the reference's control plane implies (PAPER.md L0
system-status/metrics plane): it discovers every live instance through
the existing discovery backend (each instance advertises its
system-status address in its discovery metadata —
runtime/component.py), scrapes `/metrics` and the token-gated
`/debug/state` concurrently with bounded retries (runtime/retry.py),
tolerates partial failure by marking individual workers ``stale`` /
``unreachable`` instead of failing the snapshot, and reduces the
result to the signals ROADMAP items 2 and 4 block on:

  * per-worker KV occupancy + fleet-minimum KV headroom (the KV-aware
    router's capacity term),
  * load imbalance (max/mean tokens-in-flight) and goodput spread,
  * straggler detection (per-worker decode ITL p95 vs fleet median),
  * serving-recompile hotspots and drain states.

Exported three ways: ``dynamo_fleet_*`` gauges (`export_fleet_gauges`),
the planner's per-tick diag (`FleetObserver` → planner/planner.py
``fleet_imbalance`` / ``fleet_straggler`` / ``fleet_kv_headroom``), and
the operator CLI::

    python -m dynamo_tpu.obs.fleet [--json] [--watch] [--namespace ns]

which resolves the discovery backend from the same ``DYN_*`` env the
fleet itself runs on and reads the admin token from ``DYN_ADMIN_TOKEN``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..runtime.discovery import INSTANCE_PREFIX, QUARANTINE_PREFIX, Instance
from ..runtime.metrics import percentile
from ..runtime.retry import RetryPolicy, call_with_retry

logger = logging.getLogger(__name__)

# two quick tries per surface: a scrape rides incident paths, so it must
# give up fast and mark the worker rather than hang the snapshot
SCRAPE_POLICY = RetryPolicy(max_attempts=2, base_s=0.05, cap_s=0.25)

# a worker whose decode ITL p95 exceeds this multiple of the fleet
# median is flagged a straggler
STRAGGLER_RATIO = 2.0

WORKER_ENDPOINTS = ("generate", "http")


@dataclass
class WorkerView:
    """One instance's slice of the fleet snapshot."""

    worker_id: int
    kind: str                 # engine | mocker | frontend | unknown
    namespace: str
    component: str
    endpoint: str
    address: str
    system_addr: str
    state: str                # live | stale | unreachable
    debug: Optional[dict] = None    # this worker's /debug/state source
    metrics: Dict[str, float] = field(default_factory=dict)
    # this frontend's /debug/requests forensics dump (tail exemplars;
    # obs/forensics.py) — best-effort, never affects `state`
    tail: Optional[dict] = None
    # this worker's /debug/kv kv-ledger dump (obs/kv_ledger.py:
    # attributed occupancy + audit) — best-effort, never affects `state`
    kv_ledger: Optional[dict] = None
    error: str = ""

    def to_dict(self) -> dict:
        return {
            "worker_id": self.worker_id, "kind": self.kind,
            "namespace": self.namespace, "component": self.component,
            "endpoint": self.endpoint, "address": self.address,
            "system_addr": self.system_addr, "state": self.state,
            "debug": self.debug, "metrics": self.metrics,
            **({"tail": self.tail} if self.tail is not None else {}),
            **({"kv_ledger": self.kv_ledger}
               if self.kv_ledger is not None else {}),
            **({"error": self.error} if self.error else {}),
        }


@dataclass
class FleetSnapshot:
    ts_unix: float
    workers: List[WorkerView]
    frontends: List[WorkerView]
    summary: dict

    def to_dict(self) -> dict:
        return {
            "ts_unix": self.ts_unix,
            "summary": self.summary,
            "workers": [w.to_dict() for w in self.workers],
            "frontends": [f.to_dict() for f in self.frontends],
        }


# ---------------------------------------------------------------------------
# scraping
# ---------------------------------------------------------------------------


class PermanentScrapeError(Exception):
    """A 4xx scrape response (bad/missing admin token, unknown route):
    deterministic, so retrying it only doubles the load and latency of
    every snapshot — fail the surface immediately."""


async def _fetch(session, url: str, headers: dict,
                 timeout_s: float) -> str:
    import aiohttp

    async def once() -> str:
        async with session.get(
            url, headers=headers,
            timeout=aiohttp.ClientTimeout(total=timeout_s),
        ) as r:
            if 400 <= r.status < 500:
                raise PermanentScrapeError(f"HTTP {r.status} from {url}")
            r.raise_for_status()
            return await r.text()

    # retry transport + 5xx failures only; PermanentScrapeError is not
    # in retry_on, so it propagates on the first attempt
    return await call_with_retry(
        once, SCRAPE_POLICY,
        retry_on=(aiohttp.ClientError, asyncio.TimeoutError, OSError))


def _parse_headline_metrics(text: str) -> Dict[str, float]:
    """A small, stable extract of a scrape: per-phase roofline and the
    frontend goodput gauge — enough for the merged view without
    shipping whole scrape bodies around."""
    from prometheus_client.parser import text_string_to_metric_families

    out: Dict[str, float] = {}
    for fam in text_string_to_metric_families(text):
        if fam.name in ("dynamo_engine_mfu", "dynamo_engine_mbu"):
            for s in fam.samples:
                out[f"{fam.name}:{s.labels.get('phase', '')}"] = s.value
        elif fam.name in ("dynamo_frontend_slo_goodput",
                          "dynamo_engine_itl_ema_seconds",
                          # router decision attribution (kv_router.py):
                          # index-staleness + realized reuse, scraped
                          # into the merged view so a stale indexer is
                          # visible fleet-wide
                          "dynamo_router_overlap_staleness_ratio",
                          "dynamo_frontend_realized_overlap_ratio"):
            for s in fam.samples:
                out[fam.name] = s.value
    return out


async def _scrape_addr(session, addr: str, token: str,
                       timeout_s: float,
                       want_requests: bool = False,
                       want_kv: bool = False
                       ) -> Tuple[Optional[dict],
                                  Optional[Dict[str, float]],
                                  Optional[dict], Optional[dict], str]:
    """(debug_state, headline_metrics, forensics, kv, error) for one
    process; each surface fails independently (partial data beats
    none).  The forensics surface (/debug/requests, obs/forensics.py)
    is scraped only for frontend-bearing addresses, the KV-accounting
    surface (/debug/kv, obs/kv_ledger.py) only for worker-bearing
    ones, and NEITHER affects the live/stale classification — tail
    exemplars and ledger audits are incident context, not a health
    signal."""
    headers = {"X-Dyn-Admin-Token": token} if token else {}
    debug: Optional[dict] = None
    metrics: Optional[Dict[str, float]] = None
    forensics: Optional[dict] = None
    kv: Optional[dict] = None
    errs = []
    try:
        body = await _fetch(session, f"http://{addr}/debug/state", headers,
                            timeout_s)
        debug = json.loads(body)
    except Exception as e:
        errs.append(f"debug/state: {type(e).__name__}: {e}")
    try:
        text = await _fetch(session, f"http://{addr}/metrics", {},
                            timeout_s)
        metrics = _parse_headline_metrics(text)
    except Exception as e:
        errs.append(f"metrics: {type(e).__name__}: {e}")
    if want_requests:
        try:
            body = await _fetch(session, f"http://{addr}/debug/requests",
                                headers, timeout_s)
            forensics = json.loads(body)
        except Exception:
            logger.debug("forensics scrape of %s failed", addr,
                         exc_info=True)
    if want_kv:
        try:
            body = await _fetch(session, f"http://{addr}/debug/kv",
                                headers, timeout_s)
            kv = json.loads(body)
        except Exception:
            logger.debug("kv-ledger scrape of %s failed", addr,
                         exc_info=True)
    return debug, metrics, forensics, kv, "; ".join(errs)


async def snapshot(discovery, namespace: Optional[str] = None,
                   token: Optional[str] = None,
                   timeout_s: float = 2.0) -> FleetSnapshot:
    """Discover + scrape + merge.  Never raises on a sick worker: each
    worker degrades to ``stale``/``unreachable`` individually, so one
    SIGSTOP'd process cannot blind the operator to the rest."""
    if token is None:
        token = os.environ.get("DYN_ADMIN_TOKEN", "")
    snap = await discovery.get_prefix(INSTANCE_PREFIX + "/")
    instances: List[Instance] = []
    for v in snap.values():
        try:
            inst = Instance.from_dict(v)
        except (KeyError, TypeError, ValueError):
            continue  # foreign/corrupt entry must not kill the snapshot
        if namespace and inst.namespace != namespace:
            continue
        instances.append(inst)
    # one view per instance_id (a worker registers generate + aux
    # endpoints under one id); prefer its primary endpoint's entry
    instances.sort(key=lambda i: (i.endpoint not in WORKER_ENDPOINTS,
                                  i.endpoint, i.key()))
    primary: Dict[int, Instance] = {}
    for inst in instances:
        primary.setdefault(inst.instance_id, inst)

    # quarantine markers (runtime/discovery.py QUARANTINE_PREFIX): a
    # held worker's routing keys are withdrawn, so without the marker it
    # would silently vanish from this snapshot — the marker keeps it on
    # the board as state="quarantined", and its system_addr keeps it
    # scrapeable (the process is alive by design: lease-withdrawal mark,
    # not a kill)
    qsnap = await discovery.get_prefix(QUARANTINE_PREFIX + "/")
    qrecs: List[dict] = []
    for v in qsnap.values():
        try:
            iid = int(v["instance_id"])
        except (KeyError, TypeError, ValueError):
            continue  # corrupt marker must not kill the snapshot
        if namespace and v.get("namespace") \
                and v["namespace"] != namespace:
            continue
        if iid in primary:
            continue  # readmission race: the restored live view wins
        qrecs.append(v)

    by_addr: Dict[str, List[Instance]] = {}
    for inst in primary.values():
        addr = str(inst.metadata.get("system_addr", ""))
        if addr:
            by_addr.setdefault(addr, []).append(inst)

    def _frontendish(insts: List[Instance]) -> bool:
        return any(i.endpoint == "http"
                   or i.metadata.get("kind") == "frontend"
                   for i in insts)

    def _workerish(insts: List[Instance]) -> bool:
        # any non-frontend instance at the address can carry a KV
        # ledger (co-located frontend+worker addresses scrape both)
        return any(i.endpoint != "http"
                   and i.metadata.get("kind") != "frontend"
                   for i in insts)

    # (addr -> (want_requests, want_kv)); quarantined workers scrape as
    # worker-bearing addresses
    plan: Dict[str, Tuple[bool, bool]] = {
        addr: (_frontendish(insts), _workerish(insts))
        for addr, insts in by_addr.items()}
    for rec in qrecs:
        addr = str(rec.get("system_addr", ""))
        if addr and addr not in plan:
            plan[addr] = (False, True)

    scraped: Dict[str, tuple] = {}
    if plan:
        import aiohttp

        async with aiohttp.ClientSession() as session:
            results = await asyncio.gather(
                *(_scrape_addr(session, addr, token, timeout_s,
                               want_requests=fr, want_kv=wk)
                  for addr, (fr, wk) in plan.items()))
        scraped = dict(zip(plan, results))

    workers: List[WorkerView] = []
    frontends: List[WorkerView] = []
    for inst in primary.values():
        addr = str(inst.metadata.get("system_addr", ""))
        view = WorkerView(
            worker_id=inst.instance_id, kind="unknown",
            namespace=inst.namespace, component=inst.component,
            endpoint=inst.endpoint, address=inst.address,
            system_addr=addr, state="unreachable",
        )
        if not addr:
            view.error = "no system_addr advertised (DYN_SYSTEM_PORT off?)"
        else:
            debug, metrics, forensics, kv, err = scraped[addr]
            view.error = err
            view.metrics = metrics or {}
            if forensics is not None:
                # ONLY this instance's forensics source (keyed
                # "frontend:<instance_id>" by the HttpService) — a
                # strict match, because co-located workers share the
                # same system_addr and must not have the frontend's
                # whole tail dump misattributed onto their views
                srcs = forensics.get("sources") or {}
                view.tail = next(
                    (v for k, v in srcs.items()
                     if k.endswith(f":{inst.instance_id}")), None)
            if kv is not None:
                # strict instance match, the same co-location rule:
                # workers key their kv source "kv:<instance_id>"
                srcs = kv.get("sources") or {}
                view.kv_ledger = next(
                    (v for k, v in srcs.items()
                     if k.endswith(f":{inst.instance_id}")), None)
            if debug is not None:
                sources = debug.get("sources", {})
                mine = next(
                    (s for s in sources.values() if isinstance(s, dict)
                     and s.get("instance_id") == inst.instance_id), None)
                view.debug = mine
                if mine is None:
                    # the process answered but doesn't claim this
                    # instance (restart race / half-registered worker)
                    view.state = "stale"
                    view.error = (view.error or
                                  "instance missing from /debug/state")
                else:
                    view.kind = str(mine.get("kind", "unknown"))
                    view.state = "live" if metrics is not None else "stale"
            elif metrics is not None:
                view.state = "stale"
        if view.endpoint == "http" or view.kind == "frontend" \
                or inst.metadata.get("kind") == "frontend":
            view.kind = view.kind if view.kind != "unknown" else "frontend"
            frontends.append(view)
        else:
            workers.append(view)

    for rec in qrecs:
        iid = int(rec["instance_id"])
        addr = str(rec.get("system_addr", ""))
        view = WorkerView(
            worker_id=iid, kind="unknown",
            namespace=str(rec.get("namespace", "")),
            component=str(rec.get("component", "")),
            endpoint="", address="", system_addr=addr,
            state="quarantined")
        if not addr:
            view.error = "no system_addr in quarantine marker"
        else:
            debug, metrics, _forensics, kv, err = scraped[addr]
            view.error = err
            view.metrics = metrics or {}
            if kv is not None:
                srcs = kv.get("sources") or {}
                view.kv_ledger = next(
                    (v for k, v in srcs.items()
                     if k.endswith(f":{iid}")), None)
            if debug is not None:
                mine = next(
                    (s for s in (debug.get("sources") or {}).values()
                     if isinstance(s, dict)
                     and s.get("instance_id") == iid), None)
                view.debug = mine
                if mine is not None:
                    view.kind = str(mine.get("kind", "unknown"))
        workers.append(view)

    # quarantined workers are ON the board but OUT of the reductions:
    # their ITL/load must not re-list them as stragglers (the planner's
    # hold owns them) nor skew imbalance for the in-rotation fleet
    summary = summarize_states(
        [w.debug for w in workers if w.debug is not None
         and w.state == "live"],
        frontend_states=[f.debug for f in frontends
                         if f.debug is not None],
        stale=sum(w.state == "stale" for w in workers),
        stale_states=[w.debug for w in workers if w.debug is not None
                      and w.state == "stale"],
        unreachable=sum(w.state == "unreachable" for w in workers),
        kv_states=[w.kv_ledger for w in workers
                   if w.kv_ledger is not None],
        quarantined=sum(w.state == "quarantined" for w in workers),
    )
    return FleetSnapshot(ts_unix=time.time(), workers=workers,
                         frontends=frontends, summary=summary)


# ---------------------------------------------------------------------------
# reduction (pure: also fed directly from in-proc worker.debug_state()
# dicts by bench_serving.py)
# ---------------------------------------------------------------------------


def _g1_headroom(state: dict) -> Optional[float]:
    g1 = (state.get("kv") or {}).get("g1") or {}
    cap = g1.get("capacity", 0)
    if not cap:
        return None
    return g1.get("free", 0) / cap


def reduce_kv_ledgers(kv_states: List[dict]) -> Optional[dict]:
    """Fleet rollup of per-worker kv-ledger dumps (obs/kv_ledger.py
    /debug/kv sources): total violations by kind, per-tier occupancy
    attributed by lifecycle state, and how many workers reported.
    Pure — benches feed it worker dumps directly."""
    kv_states = [s for s in kv_states
                 if isinstance(s, dict) and s.get("enabled", True)
                 and s.get("schema") == "dynamo.kv_ledger.v1"]
    if not kv_states:
        return None
    violations: Dict[str, int] = {}
    occupancy: Dict[str, Dict[str, int]] = {}
    onboards: Dict[str, int] = {}
    g4_residency: Dict[str, int] = {}
    g4_workers = 0
    # degraded-mode fold: tier -> breaker-state -> worker count, plus
    # total integrity failures ((tier, action) quarantine/timeout rows)
    tier_states: Dict[str, Dict[str, int]] = {}
    integrity: Dict[str, int] = {}
    for s in kv_states:
        for kind, tiers in (s.get("violations_total") or {}).items():
            violations[kind] = violations.get(kind, 0) \
                + sum(int(n) for n in tiers.values())
        for tier, st in (s.get("tier_state") or {}).items():
            by_state = tier_states.setdefault(tier, {})
            by_state[st] = by_state.get(st, 0) + 1
        for key, n in (s.get("integrity") or {}).items():
            integrity[key] = integrity.get(key, 0) + int(n)
        for tier, states_ in (s.get("attribution") or {}).items():
            dst = occupancy.setdefault(tier, {})
            for state in ("active", "prefix_cached",
                          "pinned_by_transfer", "partial"):
                if state in states_:
                    dst[state] = dst.get(state, 0) + int(states_[state])
        # fleet prefix cache: onboard totals by source tier + the G4
        # lineage-residency verdicts (each worker samples its own view
        # of the shared store; the fold is a fleet-health histogram,
        # not a dedup — overlapping samples are fine for a headline)
        for tier, n in (s.get("onboards_by_tier") or {}).items():
            onboards[tier] = onboards.get(tier, 0) + int(n)
        g4 = s.get("g4")
        if isinstance(g4, dict):
            g4_workers += 1
            for verdict, n in (g4.get("residency") or {}).items():
                g4_residency[verdict] = g4_residency.get(verdict, 0) \
                    + int(n)
    out = {
        "workers_reporting": len(kv_states),
        "violations": violations,
        "violations_total": sum(violations.values()),
        "occupancy": occupancy,
    }
    if onboards:
        out["onboards_by_tier"] = onboards
    if g4_workers:
        out["g4"] = {"workers_reporting": g4_workers,
                     "residency": g4_residency}
    if tier_states:
        out["tier_state"] = tier_states
    if integrity:
        out["integrity_failures"] = integrity
    return out


def summarize_states(states: List[dict], frontend_states: List[dict] = (),
                     stale: int = 0, unreachable: int = 0,
                     stale_states: List[dict] = (),
                     kv_states: List[dict] = (),
                     quarantined: int = 0) -> dict:
    """Reduce per-worker /debug/state dicts to the fleet headline:
    imbalance, stragglers, KV headroom, recompile hotspots, drain
    states, goodput spread.  Pure — no I/O — so benches and tests feed
    it worker states directly.

    `states` are the LIVE workers (fully scraped); `stale_states` are
    dumps from partially-scraped workers — their load/KV/straggler data
    still folds into the reduction (real signal beats a blind spot) but
    they count under `stale`, not `live`, so worker counts stay disjoint
    (live + stale + unreachable + quarantined = workers).  `quarantined`
    workers are counted but NEVER folded into the load/straggler/KV
    reductions: they are out of rotation — the planner's hold owns
    them, and their outlier ITL must not re-list them as stragglers."""
    live = len(states)
    states = list(states) + list(stale_states)
    toks = [int(s.get("tokens_in_flight", 0)) for s in states]
    mean_t = sum(toks) / len(toks) if toks else 0.0
    imbalance = (max(toks) / mean_t) if mean_t > 0 else 1.0
    itls = [float(s.get("itl_p95_s", 0.0)) for s in states
            if float(s.get("itl_p95_s", 0.0)) > 0.0]
    itl_median = percentile(itls, 50.0)
    stragglers = sorted(
        s.get("instance_id") for s in states
        if itl_median > 0.0
        and float(s.get("itl_p95_s", 0.0)) > STRAGGLER_RATIO * itl_median)
    headrooms = {s.get("instance_id"): _g1_headroom(s) for s in states
                 if _g1_headroom(s) is not None}
    hotspots: Dict[str, int] = {}
    for s in states:
        for fam, st in ((s.get("compile") or {}).get("families")
                        or {}).items():
            if st.get("serving"):
                hotspots[fam] = hotspots.get(fam, 0) + int(st["serving"])
    goodputs = [float(f["slo"]["goodput"]) for f in frontend_states
                if isinstance(f.get("slo"), dict)
                and f["slo"].get("goodput") is not None]
    # router decision attribution (kv_router.py overlap_stats via the
    # frontend's debug dump): the WORST per-model staleness across all
    # frontends — the ROADMAP-item-2 indexer-accuracy headline
    stalenesses = [
        float(st["staleness_ratio"])
        for f in frontend_states
        for st in (f.get("router") or {}).values()
        if isinstance(st, dict) and st.get("staleness_ratio") is not None]
    # tail-forensics headline (obs/forensics.py counts via debug dump)
    tails = [f["tail"] for f in frontend_states
             if isinstance(f.get("tail"), dict)]
    return {
        "workers": live + stale + unreachable + quarantined,
        "live": live,
        "stale": stale,
        "unreachable": unreachable,
        # held out of rotation by the planner's straggler quarantine
        # (discovery quarantine markers) — counted separately so the
        # fleet does not appear to SHRINK while a worker is held
        "quarantined": quarantined,
        "draining": sum(bool(s.get("draining")) for s in states),
        "active_seqs_total": sum(int(s.get("active_seqs", 0))
                                 for s in states),
        "tokens_in_flight": {
            "total": sum(toks), "max": max(toks) if toks else 0,
            "mean": round(mean_t, 3),
        },
        "imbalance": round(imbalance, 4),
        "itl_p95_median_s": round(itl_median, 6),
        "stragglers": stragglers,
        "straggler_count": len(stragglers),
        "kv_headroom_min": (round(min(headrooms.values()), 4)
                            if headrooms else 1.0),
        "serving_compile_hotspots": hotspots,
        "frontends": len(frontend_states),
        "goodput": ({"min": round(min(goodputs), 4),
                     "max": round(max(goodputs), 4),
                     "spread": round(max(goodputs) - min(goodputs), 4)}
                    if goodputs else None),
        "router_staleness_max": (round(max(stalenesses), 4)
                                 if stalenesses else None),
        "tail": ({"exemplars": sum(int(t.get("exemplars", 0))
                                   for t in tails),
                  "breaches": sum(int(t.get("breaches", 0))
                                  for t in tails)}
                 if tails else None),
        # KV-accounting rollup (obs/kv_ledger.py /debug/kv dumps):
        # per-tier occupancy attributed by state + total audit
        # violations — a nonzero violation count means kv_headroom_min
        # above cannot be trusted
        "kv_ledger": reduce_kv_ledgers(list(kv_states)),
    }


# ---------------------------------------------------------------------------
# prometheus export
# ---------------------------------------------------------------------------

# families carrying a per-instance `worker` label (the scrape-contract
# test pins this set; removal on worker departure iterates it)
PER_WORKER_FAMILIES = (
    "dynamo_fleet_up",
    "dynamo_fleet_kv_usage",
    "dynamo_fleet_kv_headroom",
    "dynamo_fleet_kv_free_blocks",
    "dynamo_fleet_active_seqs",
    "dynamo_fleet_tokens_in_flight",
    "dynamo_fleet_itl_p95_seconds",
    "dynamo_fleet_serving_compiles",
    "dynamo_fleet_draining",
)


def export_fleet_gauges(metrics, snap: FleetSnapshot,
                        prev_workers: Optional[Set[str]] = None
                        ) -> Set[str]:
    """Export a snapshot as ``dynamo_fleet_*`` gauges on a
    MetricsHierarchy.  Per-instance families carry a ``worker`` label;
    labels from workers that left the fleet are removed (a scaled-away
    worker must not freeze its last value into every future scrape).
    Returns the current worker-label set for the next call's
    `prev_workers`."""
    current: Set[str] = set()
    for w in snap.workers:
        lbl = str(w.worker_id)
        current.add(lbl)
        metrics.set("dynamo_fleet_up",
                    1.0 if w.state == "live" else 0.0,
                    "1 = worker scraped fully this snapshot",
                    worker=lbl)
        d = w.debug
        if d is None:
            continue
        metrics.set("dynamo_fleet_kv_usage",
                    float(d.get("kv_usage", 0.0)), worker=lbl)
        hr = _g1_headroom(d)
        if hr is not None:
            metrics.set("dynamo_fleet_kv_headroom", hr, worker=lbl)
            metrics.set("dynamo_fleet_kv_free_blocks",
                        float((d["kv"]["g1"]).get("free", 0)), worker=lbl)
        metrics.set("dynamo_fleet_active_seqs",
                    float(d.get("active_seqs", 0)), worker=lbl)
        metrics.set("dynamo_fleet_tokens_in_flight",
                    float(d.get("tokens_in_flight", 0)), worker=lbl)
        metrics.set("dynamo_fleet_itl_p95_seconds",
                    float(d.get("itl_p95_s", 0.0)), worker=lbl)
        metrics.set("dynamo_fleet_serving_compiles",
                    float((d.get("compile") or {}).get("serving", 0)),
                    worker=lbl)
        metrics.set("dynamo_fleet_draining",
                    1.0 if d.get("draining") else 0.0, worker=lbl)
    s = snap.summary
    for state in ("live", "stale", "unreachable", "draining",
                  "quarantined"):
        metrics.set("dynamo_fleet_workers", float(s.get(state, 0)),
                    "worker count by scrape/drain state", state=state)
    metrics.set("dynamo_fleet_load_imbalance", float(s["imbalance"]))
    metrics.set("dynamo_fleet_straggler_workers",
                float(s["straggler_count"]))
    metrics.set("dynamo_fleet_kv_headroom_min",
                float(s["kv_headroom_min"]))
    metrics.set("dynamo_fleet_frontends", float(s["frontends"]))
    if s.get("router_staleness_max") is not None:
        metrics.set("dynamo_fleet_router_staleness_max",
                    float(s["router_staleness_max"]),
                    "worst per-model router overlap-staleness ratio "
                    "across frontends (kv_router.py overlap_stats)")
    else:
        metrics.remove("dynamo_fleet_router_staleness_max")
    if s.get("tail") is not None:
        metrics.set("dynamo_fleet_tail_breaches",
                    float(s["tail"]["breaches"]),
                    "SLO-breach exemplars retained across frontends "
                    "(obs/forensics.py)")
    else:
        metrics.remove("dynamo_fleet_tail_breaches")
    if s.get("kv_ledger") is not None:
        metrics.set("dynamo_fleet_kv_violations",
                    float(s["kv_ledger"]["violations_total"]),
                    "total kv-ledger audit violations across the fleet "
                    "(obs/kv_ledger.py; nonzero = the KV headroom "
                    "signals are built on corrupted books)")
    else:
        metrics.remove("dynamo_fleet_kv_violations")
    if s.get("goodput") is not None:
        metrics.set("dynamo_fleet_goodput_spread",
                    float(s["goodput"]["spread"]))
        metrics.set("dynamo_fleet_goodput_min",
                    float(s["goodput"]["min"]))
    else:
        # all frontends gone/unscraped: drop the samples rather than
        # freeze the last spread into every future scrape (0.0 would
        # read "no spread" and a frozen min would read as live data)
        metrics.remove("dynamo_fleet_goodput_spread")
        metrics.remove("dynamo_fleet_goodput_min")
    # drop labels of departed workers
    for gone in (prev_workers or set()) - current:
        for name in PER_WORKER_FAMILIES:
            metrics.remove(name, worker=gone)
    return current


# ---------------------------------------------------------------------------
# periodic observer (planner + long-running exporters)
# ---------------------------------------------------------------------------


class FleetObserver:
    """Background snapshot refresher: planners read `.summary()` per
    tick, exporters get the gauges updated on the given hierarchy.
    Scrape failures degrade the snapshot, never the loop."""

    def __init__(self, runtime=None, discovery=None,
                 namespace: Optional[str] = None, interval_s: float = 2.0,
                 timeout_s: float = 2.0, token: Optional[str] = None,
                 metrics=None):
        if discovery is None:
            if runtime is None:
                raise ValueError("FleetObserver needs runtime= or "
                                 "discovery=")
            discovery = runtime.discovery
        self.discovery = discovery
        self.namespace = namespace
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.token = token
        self.metrics = metrics if metrics is not None else (
            runtime.metrics.scoped(component="fleet")
            if runtime is not None else None)
        self.snapshot: Optional[FleetSnapshot] = None
        self._prev_workers: Set[str] = set()
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> "FleetObserver":
        if self._task is None:
            self._task = asyncio.create_task(self._loop())
        return self

    async def _loop(self) -> None:
        try:
            while True:
                try:
                    await self.refresh()
                except Exception:
                    logger.warning("fleet snapshot failed; retrying",
                                   exc_info=True)
                await asyncio.sleep(self.interval_s)
        except asyncio.CancelledError:
            pass

    async def refresh(self) -> FleetSnapshot:
        snap = await snapshot(self.discovery, namespace=self.namespace,
                              token=self.token, timeout_s=self.timeout_s)
        self.snapshot = snap
        if self.metrics is not None:
            self._prev_workers = export_fleet_gauges(
                self.metrics, snap, self._prev_workers)
        return snap

    def summary(self, max_age_s: Optional[float] = None) -> Optional[dict]:
        """The latest snapshot's summary, or None when there is none OR
        it has gone stale (default: 5 refresh intervals old).  A
        discovery outage must not keep feeding the planner a frozen
        half-hour-old imbalance as if it were live."""
        if self.snapshot is None:
            return None
        if max_age_s is None:
            max_age_s = 5.0 * max(self.interval_s, self.timeout_s)
        if time.time() - self.snapshot.ts_unix > max_age_s:
            return None
        return self.snapshot.summary

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _human(snap: FleetSnapshot) -> str:
    s = snap.summary
    lines = [
        f"fleet @ {time.strftime('%H:%M:%S', time.localtime(snap.ts_unix))}"
        f"  workers={s['workers']} (live={s['live']} stale={s['stale']} "
        f"unreachable={s['unreachable']} draining={s['draining']} "
        f"quarantined={s.get('quarantined', 0)})  "
        f"frontends={s['frontends']}",
        f"  imbalance={s['imbalance']:.2f}  "
        f"stragglers={s['straggler_count']}  "
        f"kv_headroom_min={s['kv_headroom_min']:.2%}  "
        f"active_seqs={s['active_seqs_total']}",
    ]
    if s["serving_compile_hotspots"]:
        lines.append(f"  RECOMPILE HOTSPOTS: "
                     f"{s['serving_compile_hotspots']}")
    kvl = s.get("kv_ledger")
    if kvl and kvl["violations_total"]:
        lines.append(f"  KV LEDGER VIOLATIONS: {kvl['violations']}")
    hdr = (f"  {'worker':>20} {'component':>12} {'state':>12} "
           f"{'act':>5} {'kv_used':>16} {'itl_p95_ms':>10} flags")
    lines.append(hdr)
    for w in snap.workers:
        d = w.debug or {}
        g1 = (d.get("kv") or {}).get("g1") or {}
        flags = []
        if d.get("draining"):
            flags.append("draining")
        if w.worker_id in s["stragglers"]:
            flags.append("STRAGGLER")
        if w.error and w.state != "live":
            flags.append(w.error.split(";")[0][:48])
        lines.append(
            f"  {w.worker_id:>20} {w.component:>12} {w.state:>12} "
            f"{d.get('active_seqs', '-'):>5} "
            f"{g1.get('used', '-'):>7}/{g1.get('capacity', '-'):<8} "
            f"{1e3 * float(d.get('itl_p95_s', 0.0)):>10.2f} "
            f"{' '.join(flags)}")
    for f in snap.frontends:
        d = f.debug or {}
        slo = d.get("slo") or {}
        lines.append(
            f"  {f.worker_id:>20} {'frontend':>12} {f.state:>12} "
            f"{d.get('inflight', '-'):>5} "
            f"goodput={slo.get('goodput', '-')} "
            f"models={','.join(d.get('models', []))}")
    return "\n".join(lines)


async def _amain(args: argparse.Namespace) -> int:
    from ..runtime.config import RuntimeConfig
    from ..runtime.discovery import make_discovery

    cfg = RuntimeConfig.from_env()
    # read_only: the CLI observes, it must never reap lease files —
    # run it with the FLEET'S DYN_LEASE_TTL (a shorter TTL here hides
    # workers whose heartbeat period exceeds it)
    disco = make_discovery(
        cfg.discovery_backend, path=cfg.discovery_path,
        ttl_s=cfg.lease_ttl_s,
        cluster_id=os.environ.get("DYN_CLUSTER_ID", "default"),
        etcd_endpoint=cfg.etcd_endpoint, read_only=True)
    await disco.start()
    try:
        while True:
            snap = await snapshot(disco, namespace=args.namespace or None,
                                  timeout_s=args.timeout_s)
            if args.json:
                print(json.dumps(snap.to_dict(), default=repr), flush=True)
            else:
                print(_human(snap), flush=True)
            if not args.watch:
                break
            await asyncio.sleep(args.interval)
    finally:
        await disco.close()
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        "dynamo_tpu.obs.fleet",
        description="one-shot or watching fleet snapshot: discovery-"
                    "driven scrape of every instance's /metrics + "
                    "/debug/state (DYN_ADMIN_TOKEN), merged into per-"
                    "worker KV/load/health plus imbalance, straggler, "
                    "and headroom signals")
    p.add_argument("--json", action="store_true",
                   help="machine output: one JSON snapshot per line")
    p.add_argument("--watch", action="store_true",
                   help="keep snapshotting every --interval seconds")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--namespace", default="",
                   help="restrict to one namespace (default: all)")
    p.add_argument("--timeout-s", type=float, default=2.0,
                   help="per-surface scrape timeout before a worker is "
                        "marked stale/unreachable")
    args = p.parse_args(argv)
    try:
        return asyncio.run(_amain(args))
    except BrokenPipeError:
        # stdout consumer (head, a closed pager) went away mid-print —
        # normal CLI lifecycle, not an error
        import sys

        try:
            sys.stdout.close()
        except OSError:
            pass
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
