"""KV ledger: block-lifecycle accounting + leak/double-free auditing.

The reference's KVBM tracks every block through an explicit lifecycle
(Reset→Partial→Complete→Registered, docs/design-docs/kvbm-design.md) and
its router is fed by worker block stored/evicted events.  Our engine has
the tiers and a refcounting :class:`~dynamo_tpu.engine.block_allocator.
BlockAllocator` — but until this plane, nothing WATCHED the accounting:
a leaked or double-freed block is silent capacity loss at fleet scale,
and ``dynamo_fleet_kv_headroom`` (the planner's scale signal) is only
as trustworthy as the allocator's unaudited books.

This module is a second, independent set of books:

  * **The ledger** records every G1 block transition at its definition
    site (the allocator calls in, one ``if ledger is None`` pointer
    compare when off — the obs-plane zero-cost-off contract, gated by
    ``DYN_KV_LEDGER=0``), every KVBM G2–G4 stage/evict (via the
    engine's per-tier event batches), and disagg park/unpark handoffs —
    each op stamped with seq_id, tier, lineage hash, and the request's
    trace_id where one was propagated, onto a bounded event tape.

  * **The invariant auditor** reconciles the ledger's mirror against
    the allocator's ``_block_ref``/free-list, the scheduler's live
    slot view, and the KVBM pool manifests — on request finish, on an
    idle-tick cadence, and on demand (``/debug/kv``).  Violations are
    classified::

        leak            a block the allocator holds that no live owner
                        accounts for (capacity silently lost), or a
                        tier pool holding an unledgered block
        double-free     a block id on the free list twice, or freed
                        while a live sequence still owns it
        orphan          the ledger references a block the allocator
                        already freed (books point at a ghost), or a
                        tier entry whose pool copy is gone
        refcount-drift  ledger refcount != allocator refcount — the
                        precursor state every other class grows from

    counted into ``dynamo_kv_ledger_violations_total{kind,tier}`` and
    snapshotting the flight recorder on each kind's first occurrence.

  * **Attribution**: per-tier occupancy broken down by state (active /
    prefix-cached / pinned-by-transfer / orphaned) plus lineage
    fragmentation — cached blocks whose parent block is gone can never
    be prefix-hit again (prefix matching walks leading runs only), so
    they are dead capacity the plain used/free split cannot see.

The ledger's accuracy contract is that EVERY mutation of the
allocator's refcount/free-list state goes through the defining module —
dynlint DYN013 enforces it statically.  The mocker's
:class:`~dynamo_tpu.mocker.kv_cache_sim.KvCacheSim` feeds the same
ledger (hash-keyed instead of block-id-keyed), so the whole plane is
tier-1 testable CPU-only and ``/debug/kv`` reads identically off both
worker types.

The canonical cache-event stream (``kv_events.{ns}``) stays owned by
:class:`~dynamo_tpu.router.events.KvEventPublisher`; this plane audits
it and the publisher gained the snapshot-on-subscribe replay (a late
subscriber receives the warm resident set — the PR 13 staleness fix and
ROADMAP item 2's ingestion contract).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

logger = logging.getLogger(__name__)

# THE canonical ledger-op taxonomy (the DYN006/SPAN_KINDS registry
# pattern): every record the ledger tapes names one of these; extend the
# set and the docstring table together when adding an op.
#
#   alloc       a free/evicted block pinned to a sequence (rc=1)
#   pin         prefix-cache hit: an owner added to a resident block
#   unpin       an owner released while others remain (rc stays > 0)
#   cache       last owner released; block retained prefix-cached (rc=0)
#   commit      a full block's lineage hash registered (with its parent)
#   evict       a cached block's registration destroyed (reuse/clear)
#   release     a block returned to the free list
#   park        a sequence's blocks pinned-by-transfer (disagg prefill
#               awaiting pull)
#   unpark      the parked handoff completed/expired
#   partial     mocker parity: anonymous (unhashed) block count delta
#   stage       a block stored into a KVBM tier (g2/g3/g4)
#   tier_evict  a block dropped from a KVBM tier
#   onboard     a block's payload served back INTO G1 from a lower tier
#               (tape/counter only — the allocator's commit and the
#               fetch promotion's stage already move the membership
#               books; this mark is what lets the auditor and the
#               fleet-prefix-cache bench attribute reuse to its source
#               tier)
#   clear       whole-cache clear (clear_kv_blocks)
LEDGER_OPS = frozenset({
    "alloc", "pin", "unpin", "cache", "commit", "evict", "release",
    "park", "unpark", "partial", "stage", "tier_evict", "onboard",
    "clear", "quarantine",
})

# `corrupt` differs from the reconciliation kinds: it is recorded at
# the consume site the moment a checksum fails (corruption()), not
# derived by an audit sweep — an audit can't see a flipped bit, only a
# read can
VIOLATION_KINDS = ("leak", "double-free", "orphan", "refcount-drift",
                   "corrupt")

DEFAULT_RING = 4096

# bounded lineage-parent / recent-touch maps feeding the G4 residency
# policy (kvbm/residency.py): oldest entries age out FIFO, which only
# degrades a verdict to the TTL fallback, never to a wrong "dead"
LINEAGE_CAP = 65536


def ledger_enabled(override: Optional[bool] = None) -> bool:
    """The plane's on/off switch: an explicit config override wins,
    else ``DYN_KV_LEDGER`` (always-on by default, ``0`` disables)."""
    if override is not None:
        return bool(override)
    return os.environ.get("DYN_KV_LEDGER", "1").lower() not in (
        "0", "false", "no", "off")


class _Entry:
    """One tracked G1 block: refcount, lineage hash + parent, owners."""

    __slots__ = ("rc", "h", "parent", "owners")

    def __init__(self) -> None:
        self.rc = 0
        self.h: Optional[int] = None
        self.parent: Optional[int] = None
        self.owners: Dict[str, int] = {}


class KvLedger:
    """Independent block-lifecycle books + the reconciliation auditor.

    Keys are physical block ids for the JAX engine and PLHs for the
    mocker sim (whose blocks have no physical identity) — the audit
    entry points differ, everything else is shared.  Thread-safe: the
    engine records from the scheduler thread while ``/debug/kv`` reads
    from the event loop."""

    def __init__(self, ring: Optional[int] = None):
        if ring is None:
            try:
                ring = int(os.environ.get("DYN_KV_LEDGER_RING",
                                          str(DEFAULT_RING)))
            except ValueError:
                ring = DEFAULT_RING
        self._lock = threading.Lock()
        self._blk: Dict[int, _Entry] = {}
        self._tiers: Dict[str, Set[int]] = {}
        self._partials: Dict[str, int] = {}      # mocker: seq -> count
        self._parked_seqs: Set[str] = set()
        self._seq_trace: Dict[str, str] = {}
        # lineage + liveness surfaces for the G4 residency policy
        # (kvbm/residency.py): hash -> parent hash (from commit), and
        # hash -> last touch time (pin/commit/stage/onboard).  Both
        # FIFO-bounded at LINEAGE_CAP.
        from collections import OrderedDict

        self._lineage: "OrderedDict[int, Optional[int]]" = OrderedDict()
        self._touch: "OrderedDict[int, float]" = OrderedDict()
        self._onboards: Dict[str, int] = {}  # tier -> blocks onboarded
        # the event tape: (t, op, tier, key, h, seq, trace_id)
        self.events: "deque[tuple]" = deque(maxlen=max(64, ring))
        self.counts: Dict[str, int] = {}
        # (kind, tier) -> total, monotonic across audits
        self.violations_total: Dict[Tuple[str, str], int] = {}
        self.last_audit: Optional[dict] = None
        self._audit_t = 0.0
        self._finish_dirty = False

    # -- recording --------------------------------------------------------
    def _note(self, op: str, tier: str, key: Optional[int],
              h: Optional[int], seq: Optional[str]) -> None:
        # callers hold self._lock
        self.counts[op] = self.counts.get(op, 0) + 1
        self.events.append((time.monotonic(), op, tier, key, h, seq,
                            self._seq_trace.get(seq) if seq else None))

    def _touch_h(self, h: Optional[int]) -> None:
        # callers hold self._lock
        if h is None:
            return
        self._touch[h] = time.monotonic()
        self._touch.move_to_end(h)
        while len(self._touch) > LINEAGE_CAP:
            self._touch.popitem(last=False)

    def bind_seq(self, seq: str, trace_id: Optional[str]) -> None:
        """Associate a request's propagated trace_id with its seq_id so
        the tape's entries for that sequence are trace-joinable."""
        if trace_id is None:
            return
        with self._lock:
            self._seq_trace[seq] = trace_id

    def alloc(self, key: int, seq: str, h: Optional[int] = None) -> None:
        with self._lock:
            ent = self._blk.get(key)
            if ent is None:
                ent = self._blk[key] = _Entry()
            ent.rc += 1
            ent.owners[seq] = ent.owners.get(seq, 0) + 1
            if h is not None:
                ent.h = h
            self._note("alloc", "g1", key, ent.h, seq)

    def pin(self, key: int, seq: str) -> None:
        with self._lock:
            ent = self._blk.get(key)
            if ent is None:
                ent = self._blk[key] = _Entry()
            ent.rc += 1
            ent.owners[seq] = ent.owners.get(seq, 0) + 1
            self._touch_h(ent.h)
            self._note("pin", "g1", key, ent.h, seq)

    def unpin(self, key: int, seq: str) -> None:
        with self._lock:
            ent = self._blk.get(key)
            if ent is None:
                # recorded so the audit (not a crash) reports the drift
                self._note("unpin", "g1", key, None, seq)
                return
            ent.rc = max(0, ent.rc - 1)
            n = ent.owners.get(seq, 0) - 1
            if n > 0:
                ent.owners[seq] = n
            else:
                ent.owners.pop(seq, None)
            self._note("unpin", "g1", key, ent.h, seq)

    def cache(self, key: int, seq: Optional[str] = None) -> None:
        """Last owner released; the block stays resident prefix-cached."""
        with self._lock:
            ent = self._blk.get(key)
            if ent is not None:
                ent.rc = 0
                ent.owners.clear()
            self._note("cache", "g1", key,
                       ent.h if ent is not None else None, seq)

    def commit(self, key: int, h: int,
               parent: Optional[int] = None,
               seq: Optional[str] = None) -> None:
        with self._lock:
            ent = self._blk.get(key)
            if ent is not None:
                ent.h = h
                ent.parent = parent
            self._lineage[h] = parent
            self._lineage.move_to_end(h)
            while len(self._lineage) > LINEAGE_CAP:
                self._lineage.popitem(last=False)
            self._touch_h(h)
            self._note("commit", "g1", key, h, seq)

    def evict(self, key: int, h: Optional[int] = None) -> None:
        """A cached block's registration destroyed (the block is about
        to be reused or freed — an `alloc`/`release` follows)."""
        with self._lock:
            ent = self._blk.pop(key, None)
            self._note("evict", "g1", key,
                       h if h is not None
                       else (ent.h if ent is not None else None), None)

    def release(self, key: int, seq: Optional[str] = None) -> None:
        with self._lock:
            ent = self._blk.pop(key, None)
            self._note("release", "g1", key,
                       ent.h if ent is not None else None, seq)

    def seq_freed(self, seq: str) -> None:
        """A sequence fully released its holdings: arms the
        finish-cadence audit and drops the trace binding."""
        with self._lock:
            self._seq_trace.pop(seq, None)
            self._partials.pop(seq, None)
            self._finish_dirty = True

    def park(self, seq: str) -> None:
        with self._lock:
            self._parked_seqs.add(seq)
            self._note("park", "g1", None, None, seq)

    def unpark(self, seq: str) -> None:
        with self._lock:
            self._parked_seqs.discard(seq)
            self._note("unpark", "g1", None, None, seq)

    def partial(self, seq: str, delta: int) -> None:
        """Mocker parity: unhashed (partial) blocks have no identity —
        tracked as per-sequence counts."""
        with self._lock:
            n = self._partials.get(seq, 0) + delta
            if n > 0:
                self._partials[seq] = n
            else:
                self._partials.pop(seq, None)
            self._note("partial", "g1", None, None, seq)

    def tier_batch(self, stored: Sequence[int], removed: Sequence[int],
                   tier: str) -> None:
        """One KVBM tier's mutation batch (the engine's pre-consolidator
        per-tier events): membership sets the audit reconciles against
        the pool manifests.  G4 records onto the tape/counters only —
        the shared object store is swept by OTHER workers' TTL passes
        which fire no local events, so a per-worker membership set
        would grow monotonically forever (and the auditor deliberately
        excludes G4 for the same reason, see audit_kvbm)."""
        with self._lock:
            s = (self._tiers.setdefault(tier, set())
                 if tier != "g4" else None)
            for h in removed:
                if s is not None:
                    s.discard(h)
                self._note("tier_evict", tier, None, h, None)
            for h in stored:
                if s is not None:
                    s.add(h)
                self._touch_h(h)
                self._note("stage", tier, None, h, None)

    def onboard(self, h: int, tier: str, seq: Optional[str] = None) -> None:
        """One block served back into G1 from `tier` (tape/counter only;
        the membership books move via commit + the fetch promotion's
        stage).  Touches the hash — onboarded lineages are live by
        definition, which is what keeps them G4-resident."""
        with self._lock:
            self._onboards[tier] = self._onboards.get(tier, 0) + 1
            self._touch_h(h)
            self._note("onboard", tier, None, h, seq)

    def onboard_counts(self) -> Dict[str, int]:
        """Per-tier onboard totals (exported as
        dynamo_engine_kv_onboard_total{tier})."""
        with self._lock:
            return dict(self._onboards)

    def corruption(self, tier: str, h: Optional[int] = None,
                   detail: str = "") -> None:
        """One checksum-failed consume, attributed at the read site
        (kind=corrupt — see VIOLATION_KINDS).  The blob/frame is already
        quarantined by the caller; this is the forensic record: the
        monotonic (corrupt, tier) counter, a `quarantine` tape entry,
        and a flight-recorder snapshot on each tier's FIRST corruption
        (the context that poisoned a tier is exactly what post-incident
        forensics needs and exactly what a counter loses)."""
        from .. import obs

        with self._lock:
            key = ("corrupt", tier)
            first = key not in self.violations_total
            self.violations_total[key] = \
                self.violations_total.get(key, 0) + 1
            self._note("quarantine", tier, None, h, None)
        logger.error(
            "KV integrity: corrupt block %s in tier %s quarantined%s",
            f"{h:x}" if h is not None else "?", tier,
            f" ({detail})" if detail else "")
        if first:
            obs.flight_dump(f"kv_ledger.corrupt.{tier}")

    def clear(self) -> None:
        with self._lock:
            self._blk.clear()
            self._tiers.clear()
            self._partials.clear()
            self._note("clear", "g1", None, None, None)

    # -- residency surfaces (kvbm/residency.py reads these) ---------------
    def lineage_parent(self, h: int):
        """(known, parent): known=False when the commit that would have
        recorded the parent aged out of the bounded map (or never ran on
        this worker) — the residency policy must fall back to TTL, not
        guess."""
        with self._lock:
            if h in self._lineage:
                return True, self._lineage[h]
            return False, None

    def touched_within(self, h: int, window_s: float,
                       now: Optional[float] = None) -> bool:
        now = now if now is not None else time.monotonic()
        with self._lock:
            t = self._touch.get(h)
        return t is not None and (now - t) <= window_s

    def resident_hashes(self) -> Set[int]:
        """Every hash this worker's books currently account for, across
        G1 and the KVBM tiers — the liveness set lineage verdicts check
        parents against."""
        with self._lock:
            out = {e.h for e in self._blk.values() if e.h is not None}
            for s in self._tiers.values():
                out |= s
            return out

    # -- audit cadence ----------------------------------------------------
    def audit_due(self, idle_interval_s: Optional[float] = None) -> bool:
        """True when the reconciliation sweep should run: a request
        finished since the last audit (the step-end cadence), or —
        when the caller passes the idle-tick interval — that much time
        elapsed since the last sweep.  The interval applies on IDLE
        engines only; a busy engine audits per finish, so the
        O(num_blocks) scan never interleaves a steady decode stretch."""
        with self._lock:
            if self._finish_dirty:
                return True
        if idle_interval_s is None:
            return False
        return time.monotonic() - self._audit_t > idle_interval_s

    # -- auditor ----------------------------------------------------------
    @staticmethod
    def _v(kind: str, tier: str, detail: str, key=None, h=None,
           seq=None) -> dict:
        out = {"kind": kind, "tier": tier, "detail": detail}
        if key is not None:
            out["block"] = key
        if h is not None:
            out["hash"] = f"{int(h):x}"
        if seq is not None:
            out["seq_id"] = seq
        return out

    def audit_allocator(self, allocator, live_seqs: Iterable[str],
                        parked_seqs: Iterable[str] = ()) -> List[dict]:
        """Reconcile against a BlockAllocator: its free list and
        ``_block_ref`` are the ground truth the ledger's mirror must
        agree with, and every owner the ledger records must still exist
        in the scheduler's slot view (``live_seqs``) or the parked-
        transfer set."""
        live = set(live_seqs) | set(parked_seqs)
        viol: List[dict] = []
        # reads only — DYN013 forbids MUTATION outside the allocator
        free_list = list(allocator._free)
        block_ref = dict(allocator._block_ref)
        seq_blocks = {s: list(b) for s, b in allocator._seq_blocks.items()}
        with self._lock:
            mirror = {k: (e.rc, dict(e.owners), e.h)
                      for k, e in self._blk.items()}
        free_set = set(free_list)
        if len(free_list) != len(free_set):
            seen: Set[int] = set()
            for bid in free_list:
                if bid in seen:
                    viol.append(self._v(
                        "double-free", "g1",
                        "block id appears on the free list more than "
                        "once", key=bid))
                seen.add(bid)
        owned = {bid for bids in seq_blocks.values() for bid in bids}
        for bid in owned & free_set:
            seq = next((s for s, bids in seq_blocks.items()
                        if bid in bids), None)
            viol.append(self._v(
                "double-free", "g1",
                "block freed while a sequence still holds it",
                key=bid, seq=seq))
        # unsorted iteration throughout: the sweep runs on the finish
        # cadence with the engine's step lock held, and the clean case
        # (the overwhelmingly common one) must not pay O(n log n) for
        # deterministic ordering of violations that don't exist —
        # finish_audit sorts the (rare, small) findings instead
        in_use = {bid for bid in range(1, allocator.num_blocks)
                  if bid not in free_set}
        for bid in in_use:
            ent = mirror.get(bid)
            if ent is None:
                viol.append(self._v(
                    "leak", "g1",
                    "allocated block has no ledger owner (capacity "
                    "silently lost)", key=bid))
                continue
            rc, owners, h = ent
            alloc_rc = block_ref.get(bid, 0)
            if rc != alloc_rc:
                viol.append(self._v(
                    "refcount-drift", "g1",
                    f"ledger rc={rc} but allocator rc={alloc_rc}",
                    key=bid, h=h))
            dead = [s for s in owners if s not in live]
            for seq in dead:
                viol.append(self._v(
                    "leak", "g1",
                    "owner sequence no longer exists (block never "
                    "freed)", key=bid, h=h, seq=seq))
        for bid in set(mirror) - in_use:
            rc, owners, h = mirror[bid]
            seq = next(iter(owners), None)
            viol.append(self._v(
                "orphan", "g1",
                "ledger references a block the allocator freed",
                key=bid, h=h, seq=seq))
        return viol

    def audit_kvbm(self, kvbm) -> List[dict]:
        """Reconcile the ledger's tier membership against the KVBM pool
        manifests (G2 host / G3 disk; G4 is the shared object store —
        listed by other workers' sweeps, so it is deliberately out of
        per-worker audit scope)."""
        if kvbm is None:
            return []
        viol: List[dict] = []
        manifest = kvbm.manifest()
        with self._lock:
            mine = {t: set(s) for t, s in self._tiers.items()}
        for tier, pool in manifest.items():
            led = mine.get(tier, set())
            for h in pool - led:
                viol.append(self._v(
                    "leak", tier,
                    "pool holds a block the ledger never saw staged",
                    h=h))
            for h in led - pool:
                viol.append(self._v(
                    "orphan", tier,
                    "ledger says staged but the pool no longer holds "
                    "it", h=h))
        return viol

    def audit_sim(self, sim, live_seqs: Iterable[str]) -> List[dict]:
        """Reconcile against the mocker's KvCacheSim (hash-keyed; the
        free-block COUNTER stands in for a free list, so double-free
        surfaces as the counter running ahead of the books)."""
        live = set(live_seqs)
        viol: List[dict] = []
        ref = dict(sim._ref)
        with self._lock:
            mirror = {k: (e.rc, dict(e.owners)) for k, e in
                      self._blk.items()}
            partial_total = sum(self._partials.values())
        for h in set(ref) - set(mirror):
            viol.append(self._v(
                "leak", "g1",
                "sim caches a block the ledger never saw", h=h))
        for h in set(mirror) - set(ref):
            rc, owners = mirror[h]
            viol.append(self._v(
                "orphan", "g1",
                "ledger references a block the sim dropped", h=h,
                seq=next(iter(owners), None)))
        for h in set(ref) & set(mirror):
            rc, owners = mirror[h]
            if rc != ref[h]:
                viol.append(self._v(
                    "refcount-drift", "g1",
                    f"ledger rc={rc} but sim rc={ref[h]}", h=h))
            for seq in owners:
                if seq not in live:
                    viol.append(self._v(
                        "leak", "g1",
                        "owner sequence no longer exists", h=h,
                        seq=seq))
        expected_used = len(mirror) + partial_total
        if sim.used_blocks < expected_used:
            viol.append(self._v(
                "double-free", "g1",
                f"sim counts {sim.used_blocks} used but the books hold "
                f"{expected_used} (free counter ran ahead)"))
        elif sim.used_blocks > expected_used:
            viol.append(self._v(
                "leak", "g1",
                f"sim counts {sim.used_blocks} used but the books hold "
                f"only {expected_used}"))
        return viol

    def finish_audit(self, violations: List[dict],
                     where: str = "") -> dict:
        """Fold one sweep's findings into the monotonic counters, the
        flight recorder (first occurrence per kind), and `last_audit`
        (what /debug/kv serves).  Returns the audit report."""
        from .. import obs

        # deterministic report order, paid only when something is wrong
        violations = sorted(
            violations,
            key=lambda v: (v["kind"], v["tier"], v.get("block", -1),
                           v.get("hash", "")))
        new_kinds = []
        with self._lock:
            prior = {k for (k, _t) in self.violations_total}
            for v in violations:
                key = (v["kind"], v["tier"])
                self.violations_total[key] = \
                    self.violations_total.get(key, 0) + 1
                if v["kind"] not in prior:
                    prior.add(v["kind"])
                    new_kinds.append(v["kind"])
            report = {
                "ts_unix": time.time(),
                "where": where,
                "clean": not violations,
                "violations": violations[:32],
                "violation_count": len(violations),
            }
            self.last_audit = report
            self._finish_dirty = False
        self._audit_t = time.monotonic()
        for kind in new_kinds:
            # first occurrence of this class in the process's lifetime:
            # the timeline that led here is the post-mortem
            obs.flight_dump(f"kv_ledger.{kind}")
        if violations:
            logger.error(
                "kv ledger audit (%s): %d violation(s), first: %r",
                where or "sweep", len(violations), violations[0])
        return report

    # -- attribution ------------------------------------------------------
    def attribution(self) -> dict:
        """Per-tier occupancy broken down by state, plus lineage
        fragmentation: a prefix-cached block whose parent block is no
        longer resident can never be prefix-hit again (matching walks
        leading runs), so it is dead capacity `used/free` cannot see."""
        with self._lock:
            active = cached = parked = 0
            dead_cached = 0
            resident_hashes = {e.h for e in self._blk.values()
                               if e.h is not None}
            for ent in self._blk.values():
                if ent.owners and any(s in self._parked_seqs
                                      for s in ent.owners):
                    parked += 1
                elif ent.rc > 0:
                    active += 1
                else:
                    cached += 1
                    if ent.parent is not None \
                            and ent.parent not in resident_hashes:
                        dead_cached += 1
            partial = sum(self._partials.values())
            out = {"g1": {
                "active": active,
                "prefix_cached": cached,
                "pinned_by_transfer": parked,
                "partial": partial,
                "tracked": len(self._blk) + partial,
                "orphaned": sum(
                    1 for v in (self.last_audit or {}).get(
                        "violations", ())
                    if v["kind"] == "orphan" and v["tier"] == "g1"),
                "fragmentation": {
                    "dead_cached": dead_cached,
                    "dead_frac": (round(dead_cached / cached, 4)
                                  if cached else 0.0),
                },
            }}
            for tier, s in self._tiers.items():
                out[tier] = {"blocks": len(s)}
            return out

    def violations_by_kind(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            out: Dict[str, Dict[str, int]] = {}
            for (kind, tier), n in self.violations_total.items():
                out.setdefault(kind, {})[tier] = n
            return out

    # -- export -----------------------------------------------------------
    def dump(self, tail: int = 64) -> dict:
        """The /debug/kv payload (and the obs.report KV-accounting
        input): attribution, op counts, violation totals, the last
        audit report, and the event tape's tail."""
        with self._lock:
            events = list(self.events)[-max(0, tail):]
            counts = dict(self.counts)
            parked = sorted(self._parked_seqs)
            last = self.last_audit
        now = time.monotonic()
        return {
            "schema": "dynamo.kv_ledger.v1",
            "enabled": True,
            "counts": counts,
            "onboards_by_tier": self.onboard_counts(),
            "attribution": self.attribution(),
            "violations_total": self.violations_by_kind(),
            "last_audit": last,
            "parked_seqs": parked,
            "events_tail": [
                {"age_s": round(now - t, 4), "op": op, "tier": tier,
                 **({"block": key} if key is not None else {}),
                 **({"hash": f"{int(h):x}"} if h is not None else {}),
                 **({"seq_id": seq} if seq else {}),
                 **({"trace_id": tid} if tid else {})}
                for t, op, tier, key, h, seq, tid in events
            ],
        }


class MergedLedgers:
    """Gauge-surface adapter summing several ledgers (a dp>1 mocker
    worker runs one independent engine+ledger per rank, but exports ONE
    /metrics surface — the same summing its load gauges already do)."""

    def __init__(self, ledgers: Iterable[Optional[KvLedger]]):
        self.ledgers = [led for led in ledgers if led is not None]

    def __bool__(self) -> bool:
        return bool(self.ledgers)

    def violations_by_kind(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for led in self.ledgers:
            for kind, tiers in led.violations_by_kind().items():
                dst = out.setdefault(kind, {})
                for tier, n in tiers.items():
                    dst[tier] = dst.get(tier, 0) + n
        return out

    def attribution(self) -> dict:
        out: Dict[str, Dict[str, int]] = {}
        for led in self.ledgers:
            for tier, states in led.attribution().items():
                dst = out.setdefault(tier, {})
                for state, v in states.items():
                    if isinstance(v, (int, float)):
                        dst[state] = dst.get(state, 0) + v
        return out

    def onboard_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for led in self.ledgers:
            for tier, n in led.onboard_counts().items():
                out[tier] = out.get(tier, 0) + n
        return out


__all__ = [
    "DEFAULT_RING",
    "KvLedger",
    "LEDGER_OPS",
    "MergedLedgers",
    "VIOLATION_KINDS",
    "ledger_enabled",
]
