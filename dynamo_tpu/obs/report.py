"""Gap-attribution report: reduce a Chrome trace dump to the numbers
ROADMAP item 3 (overlapped scheduling) is scored on.

    python -m dynamo_tpu.obs.report trace.json [more-dumps.json ...]
        [--peak-tflops N] [--peak-hbm-gbps N]

"Served is 0.40 of raw" is a symptom; this report turns a recorded
timeline into the ranked culprits: what fraction of engine wall time is
host scheduling vs device wait vs dispatch build vs idle, how often
decode ran as a device-resident continuation burst, and the p50/p95 of
every phase.  Multiple dumps (frontend + each worker) merge; engine
tracks are recognized by their ``sched:`` prefix (obs/__init__.py pins
step spans there).

The report also prints a **per-phase roofline table**: the compile
watchdog (obs/compile_watch.py) stamps every ``compile`` span with the
program's XLA cost-analysis FLOPs/bytes, and prefill/decode dispatch
spans carry their program's costs + the dispatch gap — so the table
shows, per phase, measured FLOP/s and bytes/s (MFU/MBU when the peaks
are given), the cost-analysis MFU next to the engine's hand-estimated
one (``est_mfu``, the pre-roofline `_flops_per_token` path — the two
should agree within tens of percent; a large gap means one of them is
lying), and every compile with its family, duration, and whether it
landed mid-serving.

Attribution is **innermost-span self time**: on one track, every
instant belongs to the deepest span covering it, so nesting (``step``
wraps ``sched`` wraps nothing; ``decode_dispatch`` wraps
``device_wait``) never double-counts and the partition sums to wall
time exactly — ``step_other`` is the step loop's unattributed host
overhead, ``idle`` the time outside any span (scheduler parked, or the
device running ahead of a host with nothing to do).  The acceptance
bar "phases sum to ≥95% of wall" is therefore a property of the
recording, checked here, not an accounting trick.

**Overlapped-scheduler semantics** (engine ``overlap_scheduling``):
host scheduling performed while the device still has in-flight work is
recorded as ``enqueue_ahead`` rather than ``sched`` — the device never
waited on it, so it is EXCLUDED from ``sched_overhead_frac`` (which
thereby means exactly "host time the device idled for") and surfaced
separately as ``enqueue_ahead_frac``.  The partition stays exact: both
kinds are named slices of ``wall_fractions``.  A healthy overlapped
run shows sched_overhead ≤ ~0.02, enqueue_ahead absorbing the host
work, device_wait carrying only the deliberate deferred readbacks, and
``cont_burst_frac`` near 1 in decode-dominated stretches; see the
README "Overlapped scheduling" section for the regression-reading
guide.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Any, Dict, Iterable, List, Tuple

from ..runtime.metrics import percentile

ENGINE_TRACK_PREFIX = "sched:"


def events_of_doc(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The X-phase events of ONE Chrome-trace document, each event's
    track resolved to "<service>:<pid>/<thread-name>" — the in-memory
    half of load_events, so a benchmark can reduce a Tracer's
    chrome_trace() without a filesystem round trip."""
    out: List[Dict[str, Any]] = []
    other = doc.get("otherData", {})
    proc = f"{other.get('service', 'proc')}:{other.get('pid', 0)}"
    names: Dict[int, str] = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            names[ev["tid"]] = ev["args"]["name"]
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        out.append({
            "name": ev["name"],
            "track": f"{proc}/{names.get(ev['tid'], ev['tid'])}",
            "ts": float(ev["ts"]),
            "dur": float(ev.get("dur", 0.0)),
            "args": ev.get("args", {}) or {},
        })
    return out


def load_events(paths: Iterable[str]) -> List[Dict[str, Any]]:
    """Merge the X-phase events of several dumps; same-named tracks from
    different processes stay distinct (see events_of_doc)."""
    out: List[Dict[str, Any]] = []
    for path in paths:
        with open(path) as f:
            out.extend(events_of_doc(json.load(f)))
    return out


def _self_times(events: List[Dict[str, Any]]) -> Dict[str, float]:
    """Innermost-covering-span self time per kind on ONE track, in µs.

    Events must be well nested per track (they are: each track is one
    serialized timeline).  Sweep the start/end boundaries with a stack;
    each elapsed segment is charged to the span open on top."""
    bounds: List[Tuple[float, int, int]] = []  # (t, +1 open | -1 close, idx)
    for i, ev in enumerate(events):
        if ev["dur"] <= 0.0:
            # a zero-width span has zero self time by definition; in the
            # sweep its close would sort before its own open and the
            # ghost entry would swallow the track's unattributed time
            continue
        bounds.append((ev["ts"], 1, i))
        bounds.append((ev["ts"] + ev["dur"], -1, i))
    # at equal t, close before open EXCEPT a parent opening at the same
    # instant as its child: opens sort by (t, kind=1) after closes —
    # and among same-t opens, longer spans (parents) first
    bounds.sort(key=lambda b: (b[0], b[1] == 1,
                               -events[b[2]]["dur"] if b[1] == 1
                               else events[b[2]]["dur"]))
    self_us: Dict[str, float] = defaultdict(float)
    stack: List[int] = []
    last_t = None
    for t, kind, idx in bounds:
        if last_t is not None and stack and t > last_t:
            self_us[events[stack[-1]]["name"]] += t - last_t
        last_t = t
        if kind == 1:
            stack.append(idx)
        else:
            if idx in stack:  # tolerate slight overlap from clock jitter
                stack.remove(idx)
    return dict(self_us)


def roofline(events: List[Dict[str, Any]], peak_tflops: float = 0.0,
             peak_hbm_gbps: float = 0.0) -> Dict[str, Any]:
    """Per-phase roofline from compile spans + dispatch-span attrs.

    Phase rates use the same gates as the live gauges
    (planner/metrics.py FpmWindow): plausible dispatch gaps only, and
    prefill only where a device sync landed in the gap (``synced``) —
    an async enqueue gap measures host time and would inflate MFU."""
    compiles: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        if ev["name"] != "compile":
            continue
        a = ev["args"]
        fam = str(a.get("family", ""))
        c = compiles.setdefault(fam, {
            "count": 0, "seconds": 0.0, "serving": 0, "variants": set(),
        })
        c["count"] += 1
        c["seconds"] = round(c["seconds"] + float(a.get("seconds", 0.0)), 6)
        c["serving"] += int(bool(a.get("serving")))
        tokens = int(a.get("tokens", 0))
        c["variants"].add(tokens)
        if a.get("flops") and tokens >= c.get("_cost_tokens", -1):
            # deterministic representative: the LARGEST token variant's
            # costs (dump merge order is not chronological, so
            # last-seen-wins would flip the intensity verdict run to
            # run); `variants` says how many shapes the family compiled
            c["_cost_tokens"] = tokens
            c["flops"] = float(a["flops"])
            c["bytes"] = float(a.get("bytes", 0.0))
            if c["bytes"]:
                c["intensity"] = round(c["flops"] / c["bytes"], 3)
    for c in compiles.values():
        c["variants"] = len(c.pop("variants"))
        c.pop("_cost_tokens", None)

    phases: Dict[str, Dict[str, Any]] = {}
    for phase, span_name, need_sync in (("prefill", "prefill_dispatch",
                                         True),
                                        ("decode", "decode_dispatch",
                                         False)):
        flops = byts = gaps = 0.0
        est_mfu_w = est_gaps = 0.0
        n_all = n_used = 0
        for ev in events:
            if ev["name"] != span_name:
                continue
            n_all += 1
            a = ev["args"]
            gap = float(a.get("gap_s", 0.0))
            if "xla_flops" not in a or not 0.0 < gap < 1.0:
                continue
            if need_sync and not a.get("synced"):
                continue
            n_used += 1
            flops += float(a["xla_flops"])
            byts += float(a.get("xla_bytes", 0.0))
            gaps += gap
            if "est_mfu" in a:
                # gap-weighted: a per-record mfu is flops_i/gap_i, so
                # weighting by gap recovers Σflops/Σgap — the same
                # aggregation as the cost-analysis rate above, making
                # mfu vs est_mfu a pure FLOP-count comparison instead
                # of a mean-of-ratios artifact
                est_mfu_w += float(a["est_mfu"]) * gap
                est_gaps += gap
        if not n_all:
            continue
        # 4 significant digits, not 4 decimals: a CPU test run's MFU at
        # a TPU peak is ~1e-7 and must not round to a vacuous 0.0
        sig4 = lambda x: float(f"{x:.4g}")  # noqa: E731
        entry: Dict[str, Any] = {"dispatches": n_all,
                                 "costed_dispatches": n_used}
        if gaps > 0.0:
            entry["xla_flops_per_s"] = round(flops / gaps, 1)
            entry["xla_bytes_per_s"] = round(byts / gaps, 1)
            if peak_tflops > 0.0:
                entry["mfu"] = sig4(
                    min(flops / gaps / (peak_tflops * 1e12), 1.0))
            if peak_hbm_gbps > 0.0:
                entry["mbu"] = sig4(
                    min(byts / gaps / (peak_hbm_gbps * 1e9), 1.0))
        if est_gaps > 0.0:
            # the engine's own hand-estimated MFU (pre-roofline path),
            # printed next to the cost-analysis number so divergence is
            # visible at a glance
            entry["est_mfu"] = sig4(est_mfu_w / est_gaps)
        phases[phase] = entry
    return {"compiles": compiles, "phases": phases}


def report(events: List[Dict[str, Any]], peak_tflops: float = 0.0,
           peak_hbm_gbps: float = 0.0) -> Dict[str, Any]:
    by_track: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
    for ev in events:
        by_track[ev["track"]].append(ev)

    # -- engine-track wall partition --------------------------------------
    engine_tracks = [t for t, evs in by_track.items()
                     if ENGINE_TRACK_PREFIX in t
                     or any(e["name"] == "step" for e in evs)]
    wall_us = 0.0
    phase_us: Dict[str, float] = defaultdict(float)
    for t in engine_tracks:
        evs = sorted(by_track[t], key=lambda e: e["ts"])
        if not evs:
            continue
        t0 = min(e["ts"] for e in evs)
        t1 = max(e["ts"] + e["dur"] for e in evs)
        wall_us += t1 - t0
        for kind, us in _self_times(evs).items():
            key = "step_other" if kind == "step" else kind
            phase_us[key] += us
    idle_us = max(0.0, wall_us - sum(phase_us.values()))

    # -- per-kind latency stats (all tracks) ------------------------------
    durs: Dict[str, List[float]] = defaultdict(list)
    for ev in events:
        durs[ev["name"]].append(ev["dur"])
    kinds = {
        k: {
            "count": len(v),
            "total_s": round(sum(v) / 1e6, 6),
            "p50_ms": round(percentile(v, 50) / 1e3, 4),
            "p95_ms": round(percentile(v, 95) / 1e3, 4),
        }
        for k, v in sorted(durs.items())
    }

    # -- headline gap numbers ---------------------------------------------
    decode = [ev for ev in events if ev["name"] == "decode_dispatch"]
    cont = sum(1 for ev in decode if ev["args"].get("cont"))
    steps = [ev for ev in events if ev["name"] == "step"]
    gap: Dict[str, Any] = {}
    if wall_us > 0:
        frac = {k: round(us / wall_us, 4)
                for k, us in sorted(phase_us.items(),
                                    key=lambda kv: -kv[1])}
        frac["idle"] = round(idle_us / wall_us, 4)
        gap = {
            "engine_wall_s": round(wall_us / 1e6, 6),
            # what the overlapped scheduler must drive to ~0: host time
            # spent deciding WHILE THE DEVICE WAITED.  Host scheduling
            # that ran with device work still in flight reports as
            # `enqueue_ahead` (overlap_scheduling) and is deliberately
            # excluded here — the device never waited on it; it still
            # appears in wall_fractions/enqueue_ahead_frac so the
            # partition stays exact
            "sched_overhead_frac": round(
                (phase_us.get("sched", 0.0)
                 + phase_us.get("step_other", 0.0)) / wall_us, 4),
            "enqueue_ahead_frac": round(
                phase_us.get("enqueue_ahead", 0.0) / wall_us, 4),
            "device_wait_frac": round(
                phase_us.get("device_wait", 0.0) / wall_us, 4),
            # time the scheduler wasn't even stepping: with work queued
            # this is device-idle the host never filled
            "idle_frac": round(idle_us / wall_us, 4),
            "device_idle_per_step_ms": round(
                (idle_us + phase_us.get("sched", 0.0)
                 + phase_us.get("step_other", 0.0))
                / max(len(steps), 1) / 1e3, 4),
            "wall_fractions": frac,
        }
        if decode:
            gap["cont_burst_frac"] = round(cont / len(decode), 4)
    trace_ids = {ev["args"]["trace_id"] for ev in events
                 if "trace_id" in ev["args"]}
    fpc = fleet_prefix_cache(events)
    return {
        "spans": len(events),
        "tracks": len(by_track),
        "engine_tracks": len(engine_tracks),
        "distinct_trace_ids": len(trace_ids),
        "gap": gap,
        "kinds": kinds,
        "roofline": roofline(events, peak_tflops, peak_hbm_gbps),
        **({"fleet_prefix_cache": fpc} if fpc else {}),
    }


def fleet_prefix_cache(events: List[Dict[str, Any]]):
    """TTFT attributed to tier hits: every block a ``kvbm_onboard`` span
    served back into G1 skipped its share of prefill recompute and paid
    the tier transfer instead.  Saved time per tier = onboarded tokens ×
    the SAME trace's measured prefill seconds/token; the net headline
    subtracts the transfer time actually spent inside the onboard spans.
    None when the trace has no onboard spans (section omitted)."""
    onboards = [ev for ev in events if ev["name"] == "kvbm_onboard"]
    if not onboards:
        return None
    prefill = [ev for ev in events if ev["name"] == "prefill_dispatch"
               and ev["args"].get("tokens")]
    tok = sum(float(e["args"]["tokens"]) for e in prefill)
    s_per_tok = (sum(e["dur"] for e in prefill) / 1e6 / tok) \
        if tok > 0 else 0.0
    by_tier: Dict[str, Dict[str, float]] = {}
    onboard_s = 0.0
    for ev in onboards:
        a = ev["args"]
        onboard_s += ev["dur"] / 1e6
        blocks = float(a.get("blocks") or 0)
        toks_per_block = (float(a.get("tokens") or 0) / blocks
                          if blocks else 0.0)
        for k, v in a.items():
            if k.startswith("from_"):
                d = by_tier.setdefault(k[5:], {"blocks": 0,
                                               "tokens": 0.0})
                d["blocks"] += int(v)
                d["tokens"] += float(v) * toks_per_block
    total_saved = 0.0
    tiers: Dict[str, Any] = {}
    for t, d in sorted(by_tier.items()):
        saved = d["tokens"] * s_per_tok
        total_saved += saved
        tiers[t] = {"blocks": int(d["blocks"]),
                    "recompute_saved_s": round(saved, 6)}
    return {
        "onboard_spans": len(onboards),
        "onboard_s": round(onboard_s, 6),
        "prefill_s_per_token": round(s_per_tok, 9),
        "by_tier": tiers,
        "ttft_saved_s": round(total_saved - onboard_s, 6),
    }


# ---------------------------------------------------------------------------
# tail autopsy (forensics dumps — obs/forensics.py dynamo.forensics.v1)
# ---------------------------------------------------------------------------


def forensics_docs(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The forensics dumps inside one JSON document: a raw
    ForensicsPlane.dump(), or a /debug/requests response wrapping one
    dump per registered source."""
    out = []
    if doc.get("schema") == "dynamo.forensics.v1":
        out.append(doc)
    for v in (doc.get("sources") or {}).values():
        if isinstance(v, dict) and v.get("schema") == "dynamo.forensics.v1":
            out.append(v)
    return out


def tail_autopsy(dumps: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Reduce forensics dumps to the tail-autopsy section: per model,
    the worst exemplar by TTFT and by mean ITL with their EXACT
    queue/route/prefill/transfer/decode/stall partitions, the mean
    phase mix across every retained exemplar, breach counts by reason,
    and the partition-exactness check (max |Σphases − e2e| / e2e — a
    property of the recording, verified here on every exemplar, not an
    accounting trick)."""
    per_model: Dict[str, Dict[str, Any]] = {}
    realized = {"realized_tokens": 0, "input_tokens": 0}
    for dump in dumps:
        ro = dump.get("realized_overlap") or {}
        realized["realized_tokens"] += int(ro.get("realized_tokens") or 0)
        realized["input_tokens"] += int(ro.get("input_tokens") or 0)
        for model, windows in (dump.get("models") or {}).items():
            m = per_model.setdefault(model, {
                "seen": {}, "breach_reasons": {}, "breaches": 0,
            })
            for w in windows:
                for kind in ("ttft", "itl", "breach"):
                    for ex in w.get(kind) or ():
                        # the same exemplar can sit in several ranked
                        # lists; dedupe by request id
                        m["seen"][ex.get("request_id", id(ex))] = ex
                for ex in w.get("breach") or ():
                    m["breaches"] += 1
                    r = ex.get("breach", "unknown")
                    m["breach_reasons"][r] = \
                        m["breach_reasons"].get(r, 0) + 1
    models: Dict[str, Any] = {}
    n_total = 0
    worst_err = 0.0
    for model, m in per_model.items():
        exemplars = list(m["seen"].values())
        n_total += len(exemplars)
        phase_sum: Dict[str, float] = {}
        e2e_sum = 0.0
        for ex in exemplars:
            part = ex.get("partition") or {}
            e2e = float(ex.get("e2e_ms") or 0.0)
            e2e_sum += e2e
            for p, v in part.items():
                phase_sum[p] = phase_sum.get(p, 0.0) + float(v)
            if e2e > 0.0:
                worst_err = max(worst_err, abs(
                    sum(float(v) for v in part.values()) - e2e) / e2e)

        def _brief(ex):
            if ex is None:
                return None
            return {k: ex.get(k) for k in
                    ("request_id", "ttft_ms", "avg_itl_ms", "e2e_ms",
                     "outcome", "breach", "partition") if k in ex}

        models[model] = {
            "exemplars": len(exemplars),
            "breaches": m["breaches"],
            "breach_reasons": m["breach_reasons"],
            # mean phase mix over the retained tail (fractions of the
            # summed e2e, so phases with rounding dust stay comparable)
            "phase_mix": ({p: round(v / e2e_sum, 4)
                           for p, v in sorted(phase_sum.items(),
                                              key=lambda kv: -kv[1])}
                          if e2e_sum > 0.0 else {}),
            "worst_ttft": _brief(max(
                (e for e in exemplars if e.get("ttft_ms") is not None),
                key=lambda e: e["ttft_ms"], default=None)),
            "worst_itl": _brief(max(
                (e for e in exemplars if e.get("avg_itl_ms") is not None),
                key=lambda e: e["avg_itl_ms"], default=None)),
        }
    return {
        "exemplars": n_total,
        "partition_err_max": round(worst_err, 6),
        "realized_overlap_ratio": (
            round(realized["realized_tokens"] / realized["input_tokens"], 4)
            if realized["input_tokens"] else None),
        "models": models,
    }


# ---------------------------------------------------------------------------
# KV accounting (kv-ledger dumps — obs/kv_ledger.py dynamo.kv_ledger.v1)
# ---------------------------------------------------------------------------


def kv_ledger_docs(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The kv-ledger dumps inside one JSON document: a raw
    KvLedger.dump(), or a /debug/kv response wrapping one dump per
    registered worker source (and the fleet CLI's --json snapshot,
    whose worker views carry `kv_ledger` blocks)."""
    out = []
    if doc.get("schema") == "dynamo.kv_ledger.v1":
        out.append(doc)
    for v in (doc.get("sources") or {}).values():
        if isinstance(v, dict) and v.get("schema") == "dynamo.kv_ledger.v1":
            out.append(v)
    for w in doc.get("workers") or ():
        v = w.get("kv_ledger") if isinstance(w, dict) else None
        if isinstance(v, dict) and v.get("schema") == "dynamo.kv_ledger.v1":
            out.append(v)
    return out


def kv_accounting(dumps: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Reduce kv-ledger dumps to the KV-accounting section: total audit
    violations by kind+tier (with the first few violation details kept
    verbatim — block id, hash, seq_id are the leak report's lead), the
    fleet-summed per-tier occupancy attribution, worst fragmentation,
    and whether every reporting worker's LAST audit reconciled clean."""
    from .fleet import reduce_kv_ledgers

    dumps = [d for d in dumps if d.get("enabled", True)]
    rollup = reduce_kv_ledgers(dumps) or {
        "workers_reporting": 0, "violations": {}, "violations_total": 0,
        "occupancy": {},
    }
    examples: List[Dict[str, Any]] = []
    clean = True
    worst_frag = 0.0
    ops: Dict[str, int] = {}
    for d in dumps:
        audit = d.get("audit") or d.get("last_audit") or {}
        if audit and not audit.get("clean", True):
            clean = False
            examples.extend(audit.get("violations", ())[:4])
        frag = ((d.get("attribution") or {}).get("g1") or {}).get(
            "fragmentation") or {}
        worst_frag = max(worst_frag, float(frag.get("dead_frac", 0.0)))
        for op, n in (d.get("counts") or {}).items():
            ops[op] = ops.get(op, 0) + int(n)
    return {
        **rollup,
        "reconciled_clean": clean,
        "violation_examples": examples[:8],
        "dead_cached_frac_max": round(worst_frag, 4),
        "ops": ops,
    }


# ---------------------------------------------------------------------------
# Planner actuation (planner/planner.py Planner.debug_state() dumps)
# ---------------------------------------------------------------------------


def planner_docs(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The planner control-plane states inside one JSON document: a raw
    ``Planner.debug_state()`` dump, or a /debug/state response wrapping
    a ``planner:{component}`` source."""
    def _is_planner(v) -> bool:
        return (isinstance(v, dict) and v.get("kind") == "planner"
                and "decisions" in v)

    out = [doc] if _is_planner(doc) else []
    out.extend(v for v in (doc.get("sources") or {}).values()
               if _is_planner(v))
    return out


def actuation_report(dumps: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Reduce planner debug-state dumps to the actuation section: scale
    decisions by direction, burn-forced scale-ups, quarantine
    holds/strikes/event counts, spawn-governor failure and breaker
    totals, and drain escalations — 'what did the control plane DO' as
    one rollup next to the report's 'where did the time go'."""
    planners = []
    ups = downs = burn_ups = 0
    q_events: Dict[str, int] = {}
    held = 0
    strikes = 0
    spawn = {"failures_total": 0, "breaker_opens_total": 0,
             "breaker_open": False}
    drain_escalations = 0
    for d in dumps:
        decisions = [x for x in (d.get("decisions") or ())
                     if isinstance(x, dict)]
        for dec in decisions:
            applied = dec.get("applied")
            current = dec.get("current")
            if applied is None or current is None:
                continue
            if applied > current:
                ups += 1
            elif applied < current:
                downs += 1
            if dec.get("burn_actuation"):
                burn_ups += 1
        q = d.get("quarantine") or {}
        held += len(q.get("held") or {})
        strikes += sum(int(n) for n in (q.get("strikes") or {}).values())
        for ev in q.get("events") or ():
            kind = str(ev.get("kind", "unknown"))
            q_events[kind] = q_events.get(kind, 0) + 1
        sp = d.get("spawn") or {}
        spawn["failures_total"] += int(sp.get("failures_total", 0))
        spawn["breaker_opens_total"] += \
            int(sp.get("breaker_opens_total", 0))
        spawn["breaker_open"] |= bool(sp.get("breaker_open"))
        drain_escalations += int(d.get("drain_escalations", 0))
        planners.append({
            "component": d.get("component"),
            "mode": d.get("mode"),
            "phase": d.get("phase") or "any",
            "decisions": len(decisions),
        })
    return {
        "planners": planners,
        "scale_ups": ups,
        "scale_downs": downs,
        "burn_actuations": burn_ups,
        "quarantine": {"held": held, "strikes": strikes,
                       "events": q_events},
        "spawn": spawn,
        "drain_escalations": drain_escalations,
    }


def report_paths(paths: Iterable[str], peak_tflops: float = 0.0,
                 peak_hbm_gbps: float = 0.0) -> Dict[str, Any]:
    """Reduce a mixed set of dumps: Chrome traces feed the gap/roofline
    sections, forensics dumps (/debug/requests or ForensicsPlane.dump
    files) feed the tail-autopsy section, kv-ledger dumps (/debug/kv or
    fleet --json snapshots) feed the KV-accounting section, and planner
    debug-state dumps feed the actuation section — pass any mix and the
    report carries what it finds."""
    events: List[Dict[str, Any]] = []
    tails: List[Dict[str, Any]] = []
    ledgers: List[Dict[str, Any]] = []
    planners: List[Dict[str, Any]] = []
    for path in paths:
        with open(path) as f:
            doc = json.load(f)
        found = forensics_docs(doc)
        led = kv_ledger_docs(doc)
        plans = planner_docs(doc)
        ledgers.extend(led)
        planners.extend(plans)
        if found:
            tails.extend(found)
        elif not led and not plans:
            events.extend(events_of_doc(doc))
    rep = report(events, peak_tflops, peak_hbm_gbps)
    if tails:
        rep["tail"] = tail_autopsy(tails)
    if ledgers:
        rep["kv"] = kv_accounting(ledgers)
    if planners:
        rep["actuation"] = actuation_report(planners)
    return rep


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "dynamo_tpu.obs.report",
        description="Gap-attribution report over Chrome trace dumps "
                    "(DYN_TRACE_OUT / bench_serving.py --trace-out); "
                    "forensics dumps (/debug/requests JSON or "
                    "ForensicsPlane.dump files) additionally render "
                    "the tail-autopsy section, kv-ledger dumps "
                    "(/debug/kv JSON or fleet --json snapshots) the "
                    "KV-accounting section, and planner debug-state "
                    "dumps the actuation section.")
    p.add_argument("paths", nargs="+",
                   help="Chrome trace JSON dump(s), dynamo.forensics.v1 "
                        "dumps, and/or dynamo.kv_ledger.v1 dumps")
    p.add_argument("--indent", type=int, default=2,
                   help="JSON indent (0 = one line)")
    p.add_argument("--peak-tflops", type=float, default=0.0,
                   help="accelerator peak TFLOP/s: the roofline table "
                        "reports per-phase MFU (0 = rates only)")
    p.add_argument("--peak-hbm-gbps", type=float, default=0.0,
                   help="accelerator peak HBM GB/s: the roofline table "
                        "reports per-phase MBU (0 = rates only)")
    args = p.parse_args(argv)
    rep = report_paths(args.paths, args.peak_tflops, args.peak_hbm_gbps)
    json.dump(rep, sys.stdout, indent=args.indent or None)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
