"""Gap-attribution report: reduce a Chrome trace dump to the numbers
ROADMAP item 3 (overlapped scheduling) is scored on.

    python -m dynamo_tpu.obs.report trace.json [more-dumps.json ...]

"Served is 0.40 of raw" is a symptom; this report turns a recorded
timeline into the ranked culprits: what fraction of engine wall time is
host scheduling vs device wait vs dispatch build vs idle, how often
decode ran as a device-resident continuation burst, and the p50/p95 of
every phase.  Multiple dumps (frontend + each worker) merge; engine
tracks are recognized by their ``sched:`` prefix (obs/__init__.py pins
step spans there).

Attribution is **innermost-span self time**: on one track, every
instant belongs to the deepest span covering it, so nesting (``step``
wraps ``sched`` wraps nothing; ``decode_dispatch`` wraps
``device_wait``) never double-counts and the partition sums to wall
time exactly — ``step_other`` is the step loop's unattributed host
overhead, ``idle`` the time outside any span (scheduler parked, or the
device running ahead of a host with nothing to do).  The acceptance
bar "phases sum to ≥95% of wall" is therefore a property of the
recording, checked here, not an accounting trick.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Any, Dict, Iterable, List, Tuple

from ..runtime.metrics import percentile

ENGINE_TRACK_PREFIX = "sched:"


def load_events(paths: Iterable[str]) -> List[Dict[str, Any]]:
    """Merge the X-phase events of several dumps, resolving each event's
    track to "<service>:<pid>/<thread-name>" so same-named tracks from
    different processes stay distinct."""
    out: List[Dict[str, Any]] = []
    for path in paths:
        with open(path) as f:
            doc = json.load(f)
        other = doc.get("otherData", {})
        proc = f"{other.get('service', 'proc')}:{other.get('pid', 0)}"
        names: Dict[int, str] = {}
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") == "M" and ev.get("name") == "thread_name":
                names[ev["tid"]] = ev["args"]["name"]
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            out.append({
                "name": ev["name"],
                "track": f"{proc}/{names.get(ev['tid'], ev['tid'])}",
                "ts": float(ev["ts"]),
                "dur": float(ev.get("dur", 0.0)),
                "args": ev.get("args", {}) or {},
            })
    return out


def _self_times(events: List[Dict[str, Any]]) -> Dict[str, float]:
    """Innermost-covering-span self time per kind on ONE track, in µs.

    Events must be well nested per track (they are: each track is one
    serialized timeline).  Sweep the start/end boundaries with a stack;
    each elapsed segment is charged to the span open on top."""
    bounds: List[Tuple[float, int, int]] = []  # (t, +1 open | -1 close, idx)
    for i, ev in enumerate(events):
        if ev["dur"] <= 0.0:
            # a zero-width span has zero self time by definition; in the
            # sweep its close would sort before its own open and the
            # ghost entry would swallow the track's unattributed time
            continue
        bounds.append((ev["ts"], 1, i))
        bounds.append((ev["ts"] + ev["dur"], -1, i))
    # at equal t, close before open EXCEPT a parent opening at the same
    # instant as its child: opens sort by (t, kind=1) after closes —
    # and among same-t opens, longer spans (parents) first
    bounds.sort(key=lambda b: (b[0], b[1] == 1,
                               -events[b[2]]["dur"] if b[1] == 1
                               else events[b[2]]["dur"]))
    self_us: Dict[str, float] = defaultdict(float)
    stack: List[int] = []
    last_t = None
    for t, kind, idx in bounds:
        if last_t is not None and stack and t > last_t:
            self_us[events[stack[-1]]["name"]] += t - last_t
        last_t = t
        if kind == 1:
            stack.append(idx)
        else:
            if idx in stack:  # tolerate slight overlap from clock jitter
                stack.remove(idx)
    return dict(self_us)


def report(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    by_track: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
    for ev in events:
        by_track[ev["track"]].append(ev)

    # -- engine-track wall partition --------------------------------------
    engine_tracks = [t for t, evs in by_track.items()
                     if ENGINE_TRACK_PREFIX in t
                     or any(e["name"] == "step" for e in evs)]
    wall_us = 0.0
    phase_us: Dict[str, float] = defaultdict(float)
    for t in engine_tracks:
        evs = sorted(by_track[t], key=lambda e: e["ts"])
        if not evs:
            continue
        t0 = min(e["ts"] for e in evs)
        t1 = max(e["ts"] + e["dur"] for e in evs)
        wall_us += t1 - t0
        for kind, us in _self_times(evs).items():
            key = "step_other" if kind == "step" else kind
            phase_us[key] += us
    idle_us = max(0.0, wall_us - sum(phase_us.values()))

    # -- per-kind latency stats (all tracks) ------------------------------
    durs: Dict[str, List[float]] = defaultdict(list)
    for ev in events:
        durs[ev["name"]].append(ev["dur"])
    kinds = {
        k: {
            "count": len(v),
            "total_s": round(sum(v) / 1e6, 6),
            "p50_ms": round(percentile(v, 50) / 1e3, 4),
            "p95_ms": round(percentile(v, 95) / 1e3, 4),
        }
        for k, v in sorted(durs.items())
    }

    # -- headline gap numbers ---------------------------------------------
    decode = [ev for ev in events if ev["name"] == "decode_dispatch"]
    cont = sum(1 for ev in decode if ev["args"].get("cont"))
    steps = [ev for ev in events if ev["name"] == "step"]
    gap: Dict[str, Any] = {}
    if wall_us > 0:
        frac = {k: round(us / wall_us, 4)
                for k, us in sorted(phase_us.items(),
                                    key=lambda kv: -kv[1])}
        frac["idle"] = round(idle_us / wall_us, 4)
        gap = {
            "engine_wall_s": round(wall_us / 1e6, 6),
            # what the overlapped scheduler must drive to ~0: host time
            # spent deciding instead of keeping the device fed
            "sched_overhead_frac": round(
                (phase_us.get("sched", 0.0)
                 + phase_us.get("step_other", 0.0)) / wall_us, 4),
            "device_wait_frac": round(
                phase_us.get("device_wait", 0.0) / wall_us, 4),
            # time the scheduler wasn't even stepping: with work queued
            # this is device-idle the host never filled
            "idle_frac": round(idle_us / wall_us, 4),
            "device_idle_per_step_ms": round(
                (idle_us + phase_us.get("sched", 0.0)
                 + phase_us.get("step_other", 0.0))
                / max(len(steps), 1) / 1e3, 4),
            "wall_fractions": frac,
        }
        if decode:
            gap["cont_burst_frac"] = round(cont / len(decode), 4)
    trace_ids = {ev["args"]["trace_id"] for ev in events
                 if "trace_id" in ev["args"]}
    return {
        "spans": len(events),
        "tracks": len(by_track),
        "engine_tracks": len(engine_tracks),
        "distinct_trace_ids": len(trace_ids),
        "gap": gap,
        "kinds": kinds,
    }


def report_paths(paths: Iterable[str]) -> Dict[str, Any]:
    return report(load_events(paths))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "dynamo_tpu.obs.report",
        description="Gap-attribution report over Chrome trace dumps "
                    "(DYN_TRACE_OUT / bench_serving.py --trace-out).")
    p.add_argument("paths", nargs="+", help="Chrome trace JSON dump(s)")
    p.add_argument("--indent", type=int, default=2,
                   help="JSON indent (0 = one line)")
    args = p.parse_args(argv)
    rep = report_paths(args.paths)
    json.dump(rep, sys.stdout, indent=args.indent or None)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
