"""Request SLO plane: per-request latency histograms, goodput, burn rate.

PR 6's span tracer decomposes *where* time goes; this module answers
*whether the users got what they were promised*.  TTFT/ITL existed only
as per-request JSONL ``request_end`` records (frontend/request_trace.py)
— nothing aggregated them onto ``/metrics``, so "p95 TTFT halved"
(ROADMAP item 3) and the SLA planner loop (item 4) had no live
observation surface.  The SloPlane is that surface:

  * **Per-request histograms**, fed from ``RequestTracker.finish`` (the
    one funnel every terminal path already goes through — clean finish,
    client abort, drain-abort, dispatch failure):
    ``dynamo_frontend_ttft_seconds``, ``dynamo_frontend_e2e_seconds``,
    ``dynamo_frontend_queue_seconds`` (received → first worker
    dispatch: preprocessing + routing + admission wait).  Per-token ITL
    stays on the richer delta-stream probe
    (``dynamo_frontend_itl_seconds``, frontend/service.py).

  * **Terminal outcomes.**  Every request ends exactly once as
    ``ok`` | ``error`` | ``no_first_token`` (errored before ANY token:
    dispatch fail, drain reject, preprocess/encode failure).  The e2e
    histogram and the finished counter are labeled by outcome, so
    no-first-token requests count in every denominator WITHOUT
    polluting the TTFT histogram — a dispatch-failed request has no
    TTFT, but pretending it didn't happen would inflate goodput
    exactly when the fleet is dropping load.

  * **Goodput + multi-window burn rate**, driven by the configured
    targets (``--slo-ttft-ms`` / ``--slo-itl-ms``): a request is *good*
    iff it finished ok AND met every configured target (per-request avg
    ITL; a request with ≤1 token has no ITL and passes that check).
    ``dynamo_frontend_slo_goodput`` is the good fraction over the
    shortest window; ``dynamo_frontend_slo_burn_rate{window}`` is the
    SRE burn rate per window — bad-fraction over the error budget
    ``1 - objective`` — so 1.0 means "burning budget exactly at the
    allowed rate", >>1 means a fast burn (page), and the multi-window
    pattern separates a blip from a sustained breach.

  * **Planner feed.**  ``publish()`` pushes the rolling summary onto
    the event plane (``slo_metrics.{namespace}``); the planner's
    SloObserver folds it into every SLA tick diag (planner/metrics.py)
    — the breach signal item 4's controller actuates on, measured at
    the client edge where SLOs are actually defined.

Model-agnostic by construction: the mocker fleet behind the same
frontend exports identical metric names, so the whole plane is tier-1
testable CPU-only.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

SLO_SUBJECT_PREFIX = "slo_metrics"

# terminal outcomes (request_trace.py stamps them on the record too)
OUTCOME_OK = "ok"
OUTCOME_ERROR = "error"                    # errored after ≥1 token
OUTCOME_NO_FIRST_TOKEN = "no_first_token"  # errored before any token

def breach_reason(config, record: dict) -> Optional[str]:
    """Why one request_end record breached, or None when it was good.

    THE shared breach predicate: SloPlane's per-reason counters and the
    forensics plane's breach retention (obs/forensics.py) must agree on
    what a breach is, so both call this.  A non-ok outcome is always a
    breach reason (even with no latency targets configured — an errored
    request is a tail event worth pinning); with targets set, a missed
    TTFT/ITL target breaches with that target's name.  A request with
    ≤1 token has no ITL and passes that check (the goodput convention
    above)."""
    req = record.get("request", {})
    outcome = req.get("outcome", OUTCOME_OK)
    if outcome != OUTCOME_OK:
        return outcome
    if config is None or not config.targets_set:
        return None
    ttft_ms = req.get("ttft_ms")
    if config.ttft_ms is not None and (ttft_ms is None
                                       or ttft_ms > config.ttft_ms):
        return "ttft"
    itl_ms = req.get("avg_itl_ms")
    if config.itl_ms is not None and itl_ms is not None \
            and itl_ms > config.itl_ms:
        return "itl"
    return None


_E2E_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                10.0, 30.0, 60.0, 120.0, 300.0)
_QUEUE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                  0.1, 0.25, 0.5, 1.0, 2.5, 5.0)
_TTFT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


@dataclass
class SloConfig:
    """Targets + windows.  Both targets None = histograms/outcomes only
    (always on); any target set = goodput/burn gauges light up."""

    ttft_ms: Optional[float] = None
    itl_ms: Optional[float] = None
    # SLO objective: the promised good-request fraction the error
    # budget derives from (burn rate 1.0 = burning exactly the budget)
    objective: float = 0.99
    # rolling windows, seconds, shortest first: goodput reads over the
    # shortest; burn rate is exported per window (multi-window burn —
    # short catches a fast burn, long confirms it is sustained)
    windows_s: Tuple[float, ...] = (60.0, 300.0, 1800.0)
    publish_interval_s: float = 1.0

    @property
    def targets_set(self) -> bool:
        return self.ttft_ms is not None or self.itl_ms is not None


class SloPlane:
    """Owns the frontend's request-level latency/SLO metric surface."""

    def __init__(self, metrics, config: Optional[SloConfig] = None,
                 frontend_id: Optional[int] = None):
        self.m = metrics
        self.config = config or SloConfig()
        import secrets

        self.frontend_id = frontend_id or secrets.randbits(48)
        # (finish_t, breach_reason-or-None) per finished request, pruned
        # to the longest window; bounded hard so a breach storm can't
        # grow unchecked.  Carrying the REASON (not just good/bad) is
        # what lets burn attribute by phase: a TTFT burn means the
        # prefill side is behind, an ITL burn the decode side — the
        # planner's burn actuation scales the matching pool
        # (planner/planner.py, the disagg P/D-ratio control input)
        self._finished: Deque[Tuple[float, Optional[str]]] = \
            deque(maxlen=65536)
        self._last_refresh_t = 0.0
        # one window scan serves refresh()+summary()+scrapes within its
        # TTL: the deque can hold 65536 entries and goodput()/
        # burn_rates() would otherwise each rescan it per caller
        self._counts_cache: Tuple[float, Optional[dict]] = (0.0, None)
        m = metrics
        m.histogram("dynamo_frontend_ttft_seconds",
                    "time to first streamed token", ("model",),
                    buckets=_TTFT_BUCKETS)
        m.histogram("dynamo_frontend_e2e_seconds",
                    "request end-to-end latency by terminal outcome",
                    ("model", "outcome"), buckets=_E2E_BUCKETS)
        m.histogram("dynamo_frontend_queue_seconds",
                    "request received to first worker dispatch "
                    "(preprocessing + routing + admission wait)",
                    ("model",), buckets=_QUEUE_BUCKETS)
        if self.config.targets_set:
            m.gauge("dynamo_frontend_slo_goodput",
                    "fraction of requests meeting every configured SLO "
                    "target over the shortest window")
            m.gauge("dynamo_frontend_slo_burn_rate",
                    "error-budget burn rate per rolling window "
                    "(1.0 = burning exactly the allowed budget)",
                    ("window",))

    # -- per-request ingestion (RequestTracker.finish calls this) ---------
    def observe_finish(self, tracker, record: dict) -> None:
        """Fold one finished request in.  Exceptions are swallowed with
        a log line — the SLO plane must never take down serving."""
        try:
            self._observe(tracker, record)
        except Exception:
            logger.warning("slo observation failed", exc_info=True)

    def _observe(self, tracker, record: dict) -> None:
        c = self.config
        req = record.get("request", {})
        model = tracker.model
        outcome = req.get("outcome", OUTCOME_OK)
        total_ms = float(req.get("total_time_ms", 0.0))
        ttft_ms = req.get("ttft_ms")
        itl_ms = req.get("avg_itl_ms")
        self.m.observe("dynamo_frontend_e2e_seconds", total_ms / 1000.0,
                       model=model, outcome=outcome)
        self.m.inc("dynamo_frontend_requests_finished_total",
                   model=model, outcome=outcome)
        if ttft_ms is not None:
            # only requests that produced a first token: dispatch-fail /
            # drain-reject requests have no TTFT and must not smuggle a
            # 0 or a sentinel into the latency distribution
            self.m.observe("dynamo_frontend_ttft_seconds",
                           ttft_ms / 1000.0, model=model)
        if req.get("queue_ms") is not None:
            self.m.observe("dynamo_frontend_queue_seconds",
                           float(req["queue_ms"]) / 1000.0, model=model)
        if not c.targets_set:
            return
        reason = breach_reason(c, record)
        good = reason is None
        if not good:
            self.m.inc("dynamo_frontend_slo_breach_total",
                       model=model, reason=reason)
        now = time.monotonic()
        self._finished.append((now, reason))
        self._counts_cache = (0.0, None)  # new data: cached scan stale
        # gauge refresh walks the rolling deque (up to its 65536 cap):
        # throttle the per-finish path so a busy frontend doesn't pay an
        # O(window) scan per completed request — scrapes and the publish
        # loop still refresh unconditionally
        if now - self._last_refresh_t >= 0.25:
            self.refresh()

    # -- rolling windows --------------------------------------------------
    _COUNTS_TTL_S = 0.2

    def _window_counts(
            self, now: float) -> Dict[float, Tuple[int, int, Dict[str, int]]]:
        """{window_s: (total, good, breaches-by-reason)} over the
        rolling deque — one full scan, cached briefly so
        refresh/summary/scrape callers within the same beat share it
        instead of each rescanning up to 65536 entries on the event
        loop."""
        cached_t, cached = self._counts_cache
        if cached is not None and 0.0 <= now - cached_t < self._COUNTS_TTL_S:
            return cached
        c = self.config
        longest = max(c.windows_s)
        while self._finished and now - self._finished[0][0] > longest:
            self._finished.popleft()
        out = {w: [0, 0, {}] for w in c.windows_s}
        for t, reason in self._finished:
            age = now - t
            for w in c.windows_s:
                if age <= w:
                    out[w][0] += 1
                    if reason is None:
                        out[w][1] += 1
                    else:
                        out[w][2][reason] = out[w][2].get(reason, 0) + 1
        counts = {w: (tot, good, dict(reasons))
                  for w, (tot, good, reasons) in out.items()}
        self._counts_cache = (now, counts)
        return counts

    def goodput(self, now: Optional[float] = None) -> Optional[float]:
        """Good fraction over the shortest window; None when idle."""
        if not self.config.targets_set:
            return None
        counts = self._window_counts(now or time.monotonic())
        tot, good, _ = counts[min(self.config.windows_s)]
        return good / tot if tot else None

    def burn_rates(self, now: Optional[float] = None) -> Dict[float, float]:
        """{window_s: burn rate} — bad fraction over the error budget."""
        c = self.config
        budget = max(1.0 - c.objective, 1e-6)
        out: Dict[float, float] = {}
        for w, (tot, good, _) in self._window_counts(
                now or time.monotonic()).items():
            if tot:
                out[w] = ((tot - good) / tot) / budget
        return out

    def burn_by_phase(self, now: Optional[float] = None) -> Dict[str, float]:
        """{breach reason: worst burn rate across windows} — the burn
        split the planner's phase-attributed actuation consumes: a
        ``ttft`` burn says the prefill pool is behind, an ``itl`` burn
        the decode pool (``error``/``no_first_token`` count too — an
        errored request burns budget regardless of phase).  Empty when
        nothing breached in any window."""
        c = self.config
        budget = max(1.0 - c.objective, 1e-6)
        out: Dict[str, float] = {}
        for _w, (tot, _good, reasons) in self._window_counts(
                now or time.monotonic()).items():
            if not tot:
                continue
            for reason, n in reasons.items():
                burn = (n / tot) / budget
                if burn > out.get(reason, 0.0):
                    out[reason] = burn
        return out

    def refresh(self) -> None:
        """Recompute the goodput/burn gauges from the rolling windows —
        called after finishes (throttled) AND on each /metrics scrape,
        so an idle frontend's gauges age out breaches instead of
        freezing on the last bad minute.  Empty windows report the
        no-breach values (goodput 1.0, burn 0.0): a breach that aged
        out must stop alerting, and `requests_finished_total` already
        distinguishes idle from healthy."""
        if not self.config.targets_set:
            return
        now = time.monotonic()
        self._last_refresh_t = now
        g = self.goodput(now)
        self.m.set("dynamo_frontend_slo_goodput",
                   1.0 if g is None else g)
        burns = self.burn_rates(now)
        for w in self.config.windows_s:
            self.m.set("dynamo_frontend_slo_burn_rate",
                       burns.get(w, 0.0), window=f"{int(w)}s")

    # -- planner feed -----------------------------------------------------
    def summary(self) -> dict:
        now = time.monotonic()
        counts = self._window_counts(now)
        tot, _good, _reasons = counts[min(self.config.windows_s)]
        g = self.goodput(now)
        return {
            "frontend_id": self.frontend_id,
            "goodput": 1.0 if g is None else g,
            "burn": {f"{int(w)}s": round(r, 4)
                     for w, r in self.burn_rates(now).items()},
            "burn_by_phase": {k: round(v, 4)
                              for k, v in self.burn_by_phase(now).items()},
            "requests": tot,
            "ttft_ms": self.config.ttft_ms,
            "itl_ms": self.config.itl_ms,
            "objective": self.config.objective,
        }

    async def publish(self, runtime, namespaces) -> None:
        """One summary push per served namespace onto the event plane —
        what the planner's SloObserver aggregates into tick diag."""
        payload = self.summary()
        for ns in namespaces:
            try:
                await runtime.event_plane.publish(
                    f"{SLO_SUBJECT_PREFIX}.{ns}", payload)
            except Exception:
                logger.warning("slo publish to %r failed", ns,
                               exc_info=True)
