"""Compile watchdog + XLA cost-analysis roofline for the engine's jit
dispatch sites.

The engine's own comments record *measured* 8-14s guided-fork compiles
landing mid-serving with zero telemetry — an invisible latency cliff
that no span, metric, or FPM record could attribute.  This module makes
every XLA compile an observed event, and harvests each compiled
program's FLOPs / bytes-accessed so decode, spec-verify, and packed
prefill all get live MFU *and* memory-bandwidth-utilization instead of
the hand-counted prefill-only estimate.

Mechanism (no second compile, no steady-state cost):

  * ``WatchedProgram`` wraps a ``jax.jit`` callable.  Per call it reads
    the pjit C++ cache size before and after — a growth means THIS call
    traced+compiled a new executable, and the call's wall time is the
    compile time (jit dispatch is async; only a compiling call blocks).
    Steady-state overhead is two cache-size reads and two clock reads
    per dispatch — nanoseconds next to the descriptor uploads the
    dispatch already does.  Unlike the span tracer there is no off
    switch: an unobserved mid-serving compile is exactly the blind spot
    this exists to close, and the steady-state cost is negligible.

  * On a compile event the watchdog re-lowers the traced call on
    ``jax.ShapeDtypeStruct`` avals (tracing is cached; donated buffers
    are already consumed but their aval metadata survives) and runs
    ``Lowered.cost_analysis()`` — XLA's HLO cost analysis, **without**
    compiling again.  FLOPs and bytes-accessed are stored per
    (program, token-bucket) so dispatch sites can stamp them onto FPM
    records with one dict lookup.

  * Every compile emits: a ``compile`` span on the engine's logical
    track (Perfetto shows the cliff in the timeline), a ``compile`` FPM
    record (``family``, ``seconds``, ``tokens``, ``flops``, ``bytes``,
    ``serving``) the worker turns into
    ``dynamo_engine_compile_seconds{family}`` and the planner's
    recompile-storm diag, and — when the compile landed **mid-serving**
    (active sequences exist; warmup compiles don't) — a flight-recorder
    snapshot plus a warning, because a steady-state recompile means a
    shape leaked past warmup.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

# one place defines the compile FPM record's kind string; engine, mocker,
# workers, FpmWindow and the report all join on it
COMPILE_KIND = "compile"


def _sds_of(x):
    """Aval stand-in for one call argument: lowering needs shapes/dtypes
    only, and a donated (already-deleted) jax.Array keeps its metadata."""
    import jax

    if x is None or isinstance(x, (bool, int, float)):
        return x
    return jax.ShapeDtypeStruct(x.shape, x.dtype)


def xla_costs(fn, args) -> Optional[Dict[str, float]]:
    """FLOPs / bytes-accessed of the program ``fn(*args)`` compiled, via
    ``Lowered.cost_analysis()`` on aval stand-ins — re-traces (cached)
    but does NOT re-compile.  None when the backend has no cost model
    for this program (the roofline is best-effort by design)."""
    import jax

    try:
        sds = jax.tree_util.tree_map(_sds_of, args)
        ca = fn.lower(*sds).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        byts = float(ca.get("bytes accessed", 0.0))
        if flops <= 0.0 and byts <= 0.0:
            return None
        return {"flops": flops, "bytes": byts}
    except Exception:  # observability must never take down serving
        logger.debug("xla cost analysis unavailable", exc_info=True)
        return None


class WatchedProgram:
    """One jit callable under the watchdog.  Call syntax is unchanged;
    ``cost(key)`` returns the XLA cost entry for the token-bucket key
    the dispatch site computes (0 for fixed-shape programs)."""

    __slots__ = ("fn", "family", "watch", "tokens_of", "costs")

    def __init__(self, fn, family: str, watch: "CompileWatch",
                 tokens_of: Optional[Callable] = None):
        self.fn = fn
        self.family = family
        self.watch = watch
        # tokens_of(args) -> int key grouping compiled variants (e.g. the
        # prefill bucket = the token array's padded length); None = one
        # fixed shape per program (decode: always [max_num_seqs])
        self.tokens_of = tokens_of
        self.costs: Dict[int, Dict[str, float]] = {}

    def __call__(self, *args):
        fn = self.fn
        try:
            n0 = fn._cache_size()
        except AttributeError:
            # not a pjit function (test stand-in): pass through unwatched
            return fn(*args)
        t0 = time.monotonic()
        out = fn(*args)
        if fn._cache_size() > n0:
            self.watch.on_compile(self, time.monotonic() - t0, args)
        return out

    def cost(self, tokens: int = 0) -> Optional[Dict[str, float]]:
        return self.costs.get(int(tokens))

    def lower(self, *args, **kw):
        return self.fn.lower(*args, **kw)


class CompileWatch:
    """Per-engine compile observer: counts/times every compile per
    program family and owns the roofline cost registry."""

    def __init__(self, sink: Optional[Callable[[dict], None]] = None,
                 track: Optional[str] = None,
                 serving: Optional[Callable[[], bool]] = None,
                 cost_analysis: bool = True):
        self.sink = sink          # fpm ring append (engine.fpm.append)
        self.track = track        # obs logical track for compile spans
        self._serving = serving or (lambda: False)
        self.cost_analysis = cost_analysis
        self.counts: Dict[str, int] = {}
        self.seconds: Dict[str, float] = {}
        self.serving_compiles = 0
        self.events: deque = deque(maxlen=256)

    def wrap(self, fn, family: str,
             tokens_of: Optional[Callable] = None):
        """Wrap one jit callable; None passes through (families gated off
        for this worker keep their `is None` checks working)."""
        if fn is None:
            return None
        return WatchedProgram(fn, family, self, tokens_of)

    def on_compile(self, wp: WatchedProgram, seconds: float,
                   args: Tuple[Any, ...]) -> None:
        t1 = time.monotonic()
        family = wp.family
        serving = bool(self._serving())
        key = 0
        if wp.tokens_of is not None:
            try:
                key = int(wp.tokens_of(args))
            except Exception:
                key = 0
        costs = xla_costs(wp.fn, args) if self.cost_analysis else None
        if costs is not None:
            wp.costs[key] = costs
        self.counts[family] = self.counts.get(family, 0) + 1
        self.seconds[family] = self.seconds.get(family, 0.0) + seconds
        if serving:
            self.serving_compiles += 1
        ev = {
            "t": t1, "kind": COMPILE_KIND, "family": family,
            "seconds": round(seconds, 6), "tokens": key,
            "serving": serving,
        }
        if costs is not None:
            ev["flops"] = costs["flops"]
            ev["bytes"] = costs["bytes"]
        self.events.append(ev)
        if self.sink is not None:
            self.sink(dict(ev))
        from . import flight_dump, tracer

        tr = tracer()
        if tr is not None:
            # the span covers the compiling call itself (cost analysis
            # above ran after it and is not part of the compile)
            tr.record(COMPILE_KIND, t1 - seconds, t1,
                      {k: v for k, v in ev.items()
                       if k not in ("t", "kind")},
                      None, self.track)
        if serving:
            # a compile the warmup didn't cover landed while requests
            # were in flight: every active stream just stalled behind it
            logger.warning(
                "XLA compile of %r (%d tokens) landed mid-serving: "
                "%.2fs stall", family, key, seconds)
            flight_dump(f"compile-{family}")


# compiles range from ms (CPU test programs) to 8-14s (measured TPU
# guided forks); the default prometheus buckets top out at 10s
COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   20.0, 60.0)


def observe_compile_records(metrics, records) -> None:
    """Fold a drained FPM batch's compile records onto a worker's
    /metrics: the dynamo_engine_compile_seconds{family} histogram and
    compile counters.  Shared by the JAX and mocker workers so both
    export the same families (the plane stays tier-1 testable
    CPU-only)."""
    hist = None
    for rec in records:
        if rec.get("kind") != COMPILE_KIND:
            continue
        if hist is None:
            hist = metrics.histogram(
                "dynamo_engine_compile_seconds",
                "XLA compile wall time per program family", ("family",),
                buckets=COMPILE_BUCKETS)
        family = str(rec.get("family", ""))
        hist.labels(**metrics.labels, family=family).observe(
            float(rec.get("seconds", 0.0)))
        metrics.inc("dynamo_engine_compiles_total", 1.0,
                    "XLA compiles per program family", family=family)
        if rec.get("serving"):
            metrics.inc("dynamo_engine_serving_compiles_total", 1.0,
                        "compiles that landed while requests were "
                        "in flight (each one is a serving stall)",
                        family=family)
