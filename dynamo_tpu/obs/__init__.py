"""Timeline tracing plane: span-attributed engine steps + flight recorder.

BENCH_r05 measured the served path at 0.40 of its own raw decode loop,
and nothing in the process could say *where* the other 60% goes — the
FPM deque records per-dispatch aggregates, but no record decomposes a
scheduler step into host-schedule / device-wait / sample / detokenize /
frame-egress time, and nothing stitches a request's journey across
frontend → router → prefill worker → disagg pull → decode worker.
This module is that decomposition: named spans on every engine phase,
exported three ways, reduced to ROADMAP item-3's scoreboard by
:mod:`dynamo_tpu.obs.report`.

Design (mirrors the chaos plane's zero-cost-off contract):

  * **Module-global None check when disabled.**  Every hot-path helper
    (`begin()`, `end()`, `span()`) starts with ``if _TRACER is None``
    and allocates NOTHING on that branch: `begin()` returns the shared
    float ``0.0``, `span()` returns one process-wide no-op context
    manager.  The engine scheduler loop pays one pointer compare per
    phase when tracing is off.

  * **Thread-safe ring.**  Spans append to a bounded deque from both
    the scheduler thread and the event loop; the ring IS the flight
    recorder — `flight_dump()` snapshots the last N spans when a chaos
    seam fires or a drain/abort/migration triggers, so a post-mortem
    always has the timeline that led up to the fault.

  * **Logical tracks.**  A span records the current thread name unless
    the caller pins a `track`.  Engine steps pin ``sched:<engine-id>``
    (the step runs on whichever pool thread `asyncio.to_thread` picked,
    but it is ONE logical timeline — the step lock serializes it), so
    the report's innermost-span attribution sees a well-nested track.

  * **Cross-process stitching.**  Request-scoped spans carry the
    `trace_id` the frontend minted (or received via W3C `traceparent`)
    and propagated through request annotations
    (frontend/request_trace.py) — one trace_id joins the frontend's
    `request_end` record, its `request` span, and every worker's
    `worker_request` / pull spans for that request.

Span taxonomy (kind — where — what the time is):

  step             engine _sched_step / mocker _step: one scheduler
                   iteration end to end
  sched            host scheduling: cancellations, KVBM offload sweep,
                   admission (allocation + prefix match) — emitted only
                   when the device had nothing in flight (the host time
                   the device actually waited on)
  enqueue_ahead    the same host scheduling/dispatch-build work when it
                   runs WHILE the device is still executing in-flight
                   work (overlap_scheduling): the overlapped scheduler's
                   step-N+1 build during step N.  Counted as its own
                   phase so the wall partition stays exact, and excluded
                   from the report's sched_overhead_frac — the device
                   never waited on it
  prefill_dispatch building + dispatching one prefill program (packed /
                   batched / B=1 / ring), including its FPM accounting
  decode_dispatch  building + dispatching one decode burst; attrs carry
                   ``cont`` (device-resident continuation vs full
                   upload), ``k``, ``lanes``
  device_wait      host blocked on a device fetch (burst readback,
                   prefill first-token sync, KVBM gather); on the
                   mocker, the simulated device step sleep
  sample           host-side token acceptance: spec-decode rejection
                   sampling, guided-decoding candidate selection
  detok            incremental detokenization of one engine output
  frame_egress     writing one SSE frame to the client socket
  request          frontend: one HTTP request end to end (trace_id)
  worker_request   worker: serving one generate() stream (trace_id)
  kv_pull          decode engine: one whole disagg KV pull
  disagg_open/disagg_chunk
                   receiver-paced pull ops on the wire (tier 3)
  kvbm_offload     one batched G1→G2 offload sweep
  kvbm_onboard     one G2/G3/G4→G1 onboard scatter

Env vocabulary (the request-trace config style):

    DYN_TRACE=1            install a process tracer at main() startup
    DYN_TRACE_OUT=path     Chrome trace JSON dump target; ``{pid}``
                           expands so multi-process fleets don't
                           clobber each other; dumped at exit and by
                           the flight recorder (sibling files)
    DYN_TRACE_RING=N       ring capacity in spans (default 16384)

Load a dump in Perfetto (https://ui.perfetto.dev) or chrome://tracing;
`python -m dynamo_tpu.obs.report <dump...>` reduces it to the
gap-attribution numbers.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

DEFAULT_RING = 16384

# span kinds the engine-step partition is scored on (report.py groups
# everything else under its own name); kept here so engine, mocker and
# report agree on the taxonomy
STEP_PHASES = ("sched", "enqueue_ahead", "prefill_dispatch",
               "decode_dispatch", "device_wait", "sample")

# THE canonical span taxonomy (the docstring table above, plus the
# compile watchdog's span): every obs.span()/obs.end() call site names
# one of these, and the DYN006 lint (lint/rules.py) checks the literals
# statically — a typo'd kind would otherwise produce an orphan span the
# report buckets under its own name and no dashboard ever joins on.
# Extend this set and the docstring table together when adding a kind.
SPAN_KINDS = frozenset(STEP_PHASES) | frozenset({
    "step",
    "detok",
    "frame_egress",
    "request",
    "worker_request",
    "kv_pull",
    "disagg_open",
    "disagg_chunk",
    "kvbm_offload",
    "kvbm_onboard",
    "compile",  # obs/compile_watch.py COMPILE_KIND
})

# ---------------------------------------------------------------------------
# span record: a plain tuple, cheapest thing that can ride a deque
#   (kind, t0, t1, track, attrs|None, trace_id|None)
SpanTuple = Tuple[str, float, float, str, Optional[dict], Optional[str]]


class Tracer:
    """A bounded in-process span ring with Chrome-trace export.

    Install process-globally with ``with tracer:`` (or
    install()/uninstall()); the module helpers are no-ops while no
    tracer is installed."""

    def __init__(self, service: str = "dynamo", ring: int = DEFAULT_RING,
                 out_path: Optional[str] = None):
        self.service = service
        self.spans: "deque[SpanTuple]" = deque(maxlen=max(16, ring))
        self.out_path = out_path
        # monotonic epoch for ts=0, plus the unix time it corresponds to
        # so dumps from different processes can be coarsely aligned
        self._t0 = time.monotonic()
        self._epoch_unix_ms = time.time() * 1000.0
        self._lock = threading.Lock()
        self._metrics = None
        # flight-recorder rate limit: one dump per reason per cooldown
        self._flight_last: Dict[str, float] = {}
        self.flight_cooldown_s = 1.0
        self.flight_dumps: List[str] = []  # paths written (post-mortems)

    # -- recording --------------------------------------------------------
    def record(self, kind: str, t0: float, t1: float,
               attrs: Optional[dict] = None, trace_id: Optional[str] = None,
               track: Optional[str] = None) -> None:
        span = (kind, t0, t1,
                track or threading.current_thread().name, attrs, trace_id)
        with self._lock:
            self.spans.append(span)
        m = self._metrics
        if m is not None:
            try:
                m.observe("dynamo_trace_span_seconds", t1 - t0, kind=kind)
            except Exception:  # observability must never take down serving
                logger.warning("trace span metric failed", exc_info=True)
                self._metrics = None

    def bind_metrics(self, metrics) -> "Tracer":
        """Register the per-span-kind duration histogram on a
        MetricsHierarchy so `/metrics` on the system status server
        exposes phase latencies next to the engine gauges."""
        metrics.histogram(
            "dynamo_trace_span_seconds",
            "duration of timeline-tracer spans by kind", ("kind",),
            buckets=(1e-5, 1e-4, 2.5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2,
                     2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 5.0))
        self._metrics = metrics
        return self

    # -- chrome trace export ----------------------------------------------
    def chrome_trace(self, spans=None) -> Dict[str, Any]:
        """Chrome trace-format JSON (Perfetto/chrome://tracing loadable):
        one "X" complete event per span, one metadata event per track,
        events sorted by start ts."""
        with self._lock:
            spans = list(self.spans) if spans is None else list(spans)
        pid = os.getpid()
        tids: Dict[str, int] = {}
        events: List[Dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"{self.service}:{pid}"},
        }]
        rows: List[Dict[str, Any]] = []
        for kind, t0, t1, track, attrs, trace_id in spans:
            tid = tids.get(track)
            if tid is None:
                tid = tids[track] = len(tids) + 1
                events.append({
                    "name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": track},
                })
            args: Dict[str, Any] = dict(attrs) if attrs else {}
            if trace_id is not None:
                args["trace_id"] = trace_id
            rows.append({
                "name": kind, "cat": "dynamo", "ph": "X", "pid": pid,
                "tid": tid,
                "ts": round((t0 - self._t0) * 1e6, 3),
                "dur": round(max(t1 - t0, 0.0) * 1e6, 3),
                "args": args,
            })
        # sorted by start time: nested spans were appended at their END,
        # so ring order is t1 order — viewers and the report both want
        # per-track monotonic start ts
        rows.sort(key=lambda e: e["ts"])
        return {
            "traceEvents": events + rows,
            "displayTimeUnit": "ms",
            "otherData": {
                "service": self.service,
                "pid": pid,
                "epoch_unix_ms": round(self._epoch_unix_ms, 3),
            },
        }

    def resolve_out_path(self) -> Optional[str]:
        if not self.out_path:
            return None
        return self.out_path.replace("{pid}", str(os.getpid()))

    def dump(self, path: Optional[str] = None) -> Optional[str]:
        """Write the ring as Chrome trace JSON; returns the path (None
        when no target is configured)."""
        path = path or self.resolve_out_path()
        if path is None:
            return None
        try:
            with open(path, "w") as f:
                json.dump(self.chrome_trace(), f)
        except OSError:
            logger.warning("trace dump to %r failed", path, exc_info=True)
            return None
        return path

    def flight_dump(self, reason: str) -> Optional[str]:
        """Flight recorder: dump the last-N-spans ring next to the
        configured trace output (or the cwd) when a fault fires.
        Rate-limited per reason so a storm of injected frame drops
        doesn't grind serving into file I/O."""
        now = time.monotonic()
        last = self._flight_last.get(reason, 0.0)
        if now - last < self.flight_cooldown_s:
            return None
        self._flight_last[reason] = now
        safe = "".join(c if (c.isalnum() or c in "._-") else "-"
                       for c in reason)
        base = self.resolve_out_path()
        d = os.path.dirname(base) if base else "."
        path = os.path.join(d or ".",
                            f"dynflight-{safe}-{os.getpid()}.json")
        out = self.dump(path)
        if out is not None:
            self.flight_dumps.append(out)
            logger.warning("flight recorder dumped %d spans to %s (%s)",
                           len(self.spans), out, reason)
        return out

    # -- install ----------------------------------------------------------
    def install(self) -> "Tracer":
        global _TRACER
        _TRACER = self
        return self

    def uninstall(self) -> None:
        global _TRACER
        if _TRACER is self:
            _TRACER = None

    def __enter__(self) -> "Tracer":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


_TRACER: Optional[Tracer] = None

# the forensics plane's hop taxonomy, re-exported here so call sites
# (and the DYN012 lint) address it as ``obs.HOP_KINDS`` — the same
# one-registry pattern as SPAN_KINDS above (forensics.py is stdlib-only,
# so this import stays cheap for the lint's registry load)
from .forensics import HOP_KINDS  # noqa: E402


def tracer() -> Optional[Tracer]:
    return _TRACER


def enabled() -> bool:
    return _TRACER is not None


# -- hot-path helpers --------------------------------------------------------
# begin()/end() is the zero-allocation pair for the scheduler loop: the
# disabled branch returns the shared 0.0 and end() drops a 0.0 handle even
# if a tracer appeared mid-span (a span must never report a bogus start).


def begin() -> float:
    """Span start handle: a monotonic timestamp, or 0.0 when disabled."""
    return time.monotonic() if _TRACER is not None else 0.0


def end(kind: str, t0: float, track: Optional[str] = None,
        trace_id: Optional[str] = None, **attrs) -> None:
    """Record [t0, now) as one span.  No-op when disabled or when the
    span began disabled (t0 == 0.0)."""
    tr = _TRACER
    if tr is None or t0 == 0.0:
        return
    tr.record(kind, t0, time.monotonic(), attrs or None, trace_id, track)


class _NullSpan:
    """Shared no-op context manager: span() allocates nothing when
    tracing is off."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("kind", "track", "trace_id", "attrs", "_t0")

    def __init__(self, kind: str, track: Optional[str],
                 trace_id: Optional[str], attrs: Optional[dict]):
        self.kind = kind
        self.track = track
        self.trace_id = trace_id
        self.attrs = attrs
        self._t0 = 0.0

    def __enter__(self) -> "_Span":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> bool:
        tr = _TRACER
        if tr is not None and self._t0:
            tr.record(self.kind, self._t0, time.monotonic(), self.attrs,
                      self.trace_id, self.track)
        return False


def span(kind: str, track: Optional[str] = None,
         trace_id: Optional[str] = None, **attrs):
    """Context-manager span for non-hot paths (frontend, pulls, KVBM).
    Returns the shared no-op when tracing is disabled."""
    if _TRACER is None:
        return _NULL_SPAN
    return _Span(kind, track, trace_id, attrs or None)


def flight_dump(reason: str) -> Optional[str]:
    """Module-level flight-recorder trigger (chaos seams, drain/abort,
    migration); no-op when tracing is disabled."""
    tr = _TRACER
    if tr is None:
        return None
    return tr.flight_dump(reason)


# -- log<->trace correlation -------------------------------------------------
# The frontend binds the request's trace_id for the duration of its
# handler task; workers bind it around one generate() stream.  The
# logging filter (runtime/logging.py TraceIdFilter) stamps it onto every
# record emitted inside that context, so a request's log lines join its
# spans and request_end record on one id.  ContextVars follow asyncio
# task context, so concurrent requests never see each other's ids.
from contextvars import ContextVar as _ContextVar

_TRACE_ID_VAR: "_ContextVar[Optional[str]]" = _ContextVar(
    "dyn_trace_id", default=None)


def bind_trace_id(trace_id: Optional[str]):
    """Bind `trace_id` to the current (task) context for log
    correlation; None is a no-op.  Returns a reset token (or None)."""
    if trace_id is None:
        return None
    return _TRACE_ID_VAR.set(trace_id)


def unbind_trace_id(token) -> None:
    if token is not None:
        _TRACE_ID_VAR.reset(token)


def current_trace_id() -> Optional[str]:
    return _TRACE_ID_VAR.get()


def trace_id_from_annotations(annotations) -> Optional[str]:
    """The trace_id the frontend propagated via a
    ``traceparent:00-<trace>-<span>-01`` request annotation — how worker
    spans join the frontend's trace."""
    for a in annotations or ():
        if a.startswith("traceparent:"):
            parts = a.split(":", 1)[1].split("-")
            if len(parts) == 4 and len(parts[1]) == 32:
                return parts[1].lower()
    return None


def install_from_env() -> Optional[Tracer]:
    """Process-entry hook (engine/mocker/frontend mains): install a
    tracer when DYN_TRACE is set, dumping to DYN_TRACE_OUT at exit."""
    if os.environ.get("DYN_TRACE", "").lower() not in ("1", "true", "yes",
                                                       "on"):
        return None
    try:
        ring = int(os.environ.get("DYN_TRACE_RING", str(DEFAULT_RING)))
    except ValueError:
        ring = DEFAULT_RING
    tr = Tracer(ring=ring,
                out_path=os.environ.get("DYN_TRACE_OUT") or None).install()
    if tr.out_path:
        atexit.register(tr.dump)
    logger.info("timeline tracing enabled (ring=%d, out=%s)",
                ring, tr.out_path)
    return tr


__all__ = [
    "DEFAULT_RING",
    "HOP_KINDS",
    "SPAN_KINDS",
    "STEP_PHASES",
    "Tracer",
    "begin",
    "bind_trace_id",
    "current_trace_id",
    "enabled",
    "end",
    "flight_dump",
    "install_from_env",
    "span",
    "trace_id_from_annotations",
    "tracer",
    "unbind_trace_id",
]
