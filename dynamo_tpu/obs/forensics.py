"""Tail-latency forensics plane: always-on per-request hop timelines,
tail-exemplar retention, and the autopsy partition.

The SLO plane (obs/slo.py) says *that* p95 TTFT breached; the tracing
plane (obs/__init__.py) needs ``DYN_TRACE=1`` and only keeps a ring of
recent spans — by the time anyone looks, the tail request's timeline is
gone.  This module is the qualitative complement: every request carries
an ordered **hop timeline** (frontend/request_trace.py RequestTracker),
and this plane retains the exemplars worth autopsying:

  * **Hop taxonomy** (``HOP_KINDS`` — the DYN012 lint checks every
    ``tracker.hop(...)`` literal against it, the DYN006 pattern):

      received       tracker created (t=0 of the timeline)
      routed         router decision made; attrs carry the chosen
                     worker, per-candidate cost scores, predicted
                     overlap blocks, best rejected candidate, regret
      dispatched     one dispatch attempt opened (attempt n; every
                     attempt after the first is a migration — a
                     drain-abort/worker-death replay appends a second
                     dispatched hop to the SAME record)
      prefill_open   remote-prefill hop began (disagg)
      prefill_done   remote prefill returned (disagg)
      worker_stamp   worker-side facts stamped back via the stream
                     (realized prefix reuse, queue position at
                     admission, step counts) — attrs, not a boundary
      first_token    first token reached the frontend
      decode_stall   a token gap exceeded the stall threshold; attrs
                     carry the gap duration (coarse: capped count,
                     exact total in ``stall_ms``)
      finish         terminal outcome (implicit boundary: the record's
                     total_time_ms)

  * **Exact phase partition** (``phase_partition``): each exemplar's
    e2e decomposes into ``queue / route / prefill / transfer / decode /
    stall`` by telescoping over the boundary hops, so the six phases
    sum to the e2e *exactly* (tested to 1%) — no span recording or
    sampling involved, which is what makes the plane always-on.

  * **Tail-exemplar reservoir** (``ForensicsPlane``): per (model,
    wall-clock window) keep the slowest-K complete timelines by TTFT
    and by mean ITL, plus EVERY SLO breach (bounded); breaches
    additionally pin the correlated flight-recorder span snapshot by
    trace_id while ``DYN_TRACE=1`` — the ring's contents for that
    request survive past the ring.

  * **Serving**: ``dump()`` (schema ``dynamo.forensics.v1``) backs the
    token-gated ``/debug/requests`` route (runtime/system_status.py),
    is folded into the fleet snapshot (obs/fleet.py scrapes it from
    frontends), and renders as the ``obs.report`` tail-autopsy section.

Env vocabulary (the request-trace config style)::

    DYN_FORENSICS=0          disable the plane (default: ON)
    DYN_FORENSICS_K=8        exemplars kept per (model, window, rank)
    DYN_FORENSICS_WINDOW_S=600
    DYN_STALL_THRESHOLD_S=0.25   decode-stall hop threshold
"""

from __future__ import annotations

import logging
import os
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

SCHEMA = "dynamo.forensics.v1"

# THE canonical hop taxonomy (the docstring table above): every
# RequestTracker.hop() call site names one of these, and the DYN012
# lint (lint/rules.py) checks the literals statically — a typo'd hop
# would otherwise produce an orphan timeline row the partition and the
# autopsy never join on.  Extend this set and the docstring table
# together when adding a kind.
HOP_KINDS = frozenset({
    "received",
    "routed",
    "dispatched",
    "prefill_open",
    "prefill_done",
    "worker_stamp",
    "first_token",
    "decode_stall",
    "finish",
})

# the partition vocabulary, in render order
PHASES = ("queue", "route", "prefill", "transfer", "decode", "stall")

# hop kinds that act as phase BOUNDARIES in the partition sweep
# (worker_stamp/decode_stall/finish carry attrs, not boundaries)
_BOUNDARY_KINDS = ("routed", "dispatched", "prefill_open", "prefill_done",
                   "first_token")

DEFAULT_K = 8
DEFAULT_WINDOW_S = 600.0
MAX_WINDOWS = 2          # current + previous
BREACH_CAP = 64          # breach exemplars retained per (model, window)
PIN_SPANS = 64           # flight-recorder spans pinned per breach


def forensics_enabled() -> bool:
    """Plane on by default; DYN_FORENSICS=0 turns it off (the bench
    A/B smoke proves token streams are byte-identical either way)."""
    return os.environ.get("DYN_FORENSICS", "1").lower() not in (
        "0", "false", "no", "off")


def stall_threshold_s() -> float:
    try:
        return float(os.environ.get("DYN_STALL_THRESHOLD_S", "0.25"))
    except ValueError:
        return 0.25


# ---------------------------------------------------------------------------
# exact phase partition
# ---------------------------------------------------------------------------


def phase_partition(hops: List[dict], total_ms: float,
                    stall_ms: float = 0.0) -> Dict[str, float]:
    """Partition ``[0, total_ms]`` into PHASES *exactly* (telescoping
    over boundary hops, so the six values sum to total_ms by
    construction, modulo float rounding):

      received→routed          route   (preprocess + routing decision)
      routed→dispatched        queue   (admission / dispatch wait)
      received→prefill_open    queue   (disagg: the hop IS the first
                                        dispatch, so the wait before it
                                        is admission)
      prefill_open→prefill_done prefill (the remote prefill itself)
      dispatched→first_token   prefill (local path: worker queue +
                                        prefill compute) or transfer
                                        (disagg: KV pull + first decode)
      first_token→finish       decode, with the accumulated stall time
                               carved out as stall

    Only the FIRST occurrence of each boundary kind partitions (a
    migration's second dispatched hop restarts nothing — its wait is
    part of the decode/stall story the stall hops already tell)."""
    t: Dict[str, float] = {}
    for h in hops:
        k = h.get("hop")
        if k in _BOUNDARY_KINDS and k not in t:
            t[k] = float(h.get("t_ms", 0.0))
    out = {p: 0.0 for p in PHASES}
    prev = 0.0
    disagg = False        # a remote prefill completed
    dispatched = False
    for tv, k in sorted((v, k) for k, v in t.items()):
        seg = tv - prev
        if seg > 0.0:
            if k == "routed":
                out["route"] += seg
            elif k in ("dispatched", "prefill_open"):
                out["queue"] += seg
            elif k == "prefill_done":
                out["prefill"] += seg
            elif k == "first_token":
                out["transfer" if disagg
                    else ("prefill" if dispatched else "queue")] += seg
            prev = tv
        if k == "prefill_done":
            disagg = True
        elif k in ("dispatched", "prefill_open"):
            dispatched = True
    tail = total_ms - prev
    if tail > 0.0:
        if "first_token" in t:
            st = min(max(stall_ms, 0.0), tail)
            out["stall"] += st
            out["decode"] += tail - st
        else:
            # never produced a token: the terminal interval belongs to
            # whatever phase the request died in
            out["transfer" if disagg
                else ("prefill" if dispatched else "queue")] += tail
    return out


# ---------------------------------------------------------------------------
# exemplars + reservoir
# ---------------------------------------------------------------------------


@dataclass
class TailExemplar:
    """One retained request: the full request_end record (which carries
    the timeline), its partition, and — for breaches — the pinned span
    snapshot."""

    request_id: str
    model: str
    ts_unix: float
    outcome: str
    e2e_ms: float
    ttft_ms: Optional[float] = None
    avg_itl_ms: Optional[float] = None
    breach: Optional[str] = None
    partition: Dict[str, float] = field(default_factory=dict)
    record: Dict[str, Any] = field(default_factory=dict)
    spans: Optional[List[dict]] = None

    def to_dict(self) -> dict:
        d = {
            "request_id": self.request_id,
            "model": self.model,
            "ts_unix": round(self.ts_unix, 3),
            "outcome": self.outcome,
            "e2e_ms": round(self.e2e_ms, 3),
            "partition": {p: round(v, 3)
                          for p, v in self.partition.items()},
            "record": self.record,
        }
        if self.ttft_ms is not None:
            d["ttft_ms"] = round(self.ttft_ms, 3)
        if self.avg_itl_ms is not None:
            d["avg_itl_ms"] = round(self.avg_itl_ms, 3)
        if self.breach is not None:
            d["breach"] = self.breach
        if self.spans is not None:
            d["spans"] = self.spans
        return d


def _pin_spans(trace_id: Optional[str], limit: int = PIN_SPANS
               ) -> Optional[List[dict]]:
    """Snapshot the flight-recorder ring's spans for one trace_id —
    how a breach's timeline survives the ring's churn.  None when
    tracing is off or the request carries no trace_id."""
    if trace_id is None:
        return None
    from .. import obs

    tr = obs.tracer()
    if tr is None:
        return None
    with tr._lock:
        ring = list(tr.spans)
    now = time.monotonic()
    out = []
    for kind, t0, t1, track, attrs, tid in ring:
        if tid != trace_id:
            continue
        out.append({
            "kind": kind, "age_s": round(now - t1, 4),
            "dur_ms": round((t1 - t0) * 1e3, 3), "track": track,
            **({"attrs": attrs} if attrs else {}),
        })
    return out[-limit:]


class ForensicsPlane:
    """Tail-exemplar reservoir: per (model, wall-clock window) keep the
    slowest-K timelines by TTFT and by mean ITL, plus every breach.

    Fed from ``RequestTracker.finish`` (the one funnel every terminal
    path goes through), exactly like the SLO plane; exceptions are
    swallowed with a log line — forensics must never take down serving.
    Retention work is O(K) per finish (one ranked insert per
    criterion), which is what keeps the plane always-on."""

    def __init__(self, metrics=None, slo_config=None,
                 k: Optional[int] = None,
                 window_s: Optional[float] = None,
                 max_windows: int = MAX_WINDOWS,
                 breach_cap: int = BREACH_CAP):
        self.m = metrics
        self.slo_config = slo_config
        if k is None:
            try:
                k = int(os.environ.get("DYN_FORENSICS_K", str(DEFAULT_K)))
            except ValueError:
                k = DEFAULT_K
        if window_s is None:
            try:
                window_s = float(os.environ.get("DYN_FORENSICS_WINDOW_S",
                                                str(DEFAULT_WINDOW_S)))
            except ValueError:
                window_s = DEFAULT_WINDOW_S
        self.k = max(1, k)
        self.window_s = max(0.01, window_s)
        self.max_windows = max(1, max_windows)
        self.breach_cap = breach_cap
        # window_idx -> model -> {"ttft": [exemplars desc], "itl": [...],
        #                         "breach": deque}
        self._windows: "OrderedDict[int, Dict[str, dict]]" = OrderedDict()
        # predicted-vs-realized overlap accounting across finishes (the
        # router's own gauges are per-decision; this is the per-REQUEST
        # realized-reuse rate the bench tail block reports)
        self._realized_tokens = 0
        self._input_tokens = 0
        self._stamped = 0
        self._finished = 0
        if metrics is not None:
            metrics.gauge(
                "dynamo_frontend_realized_overlap_ratio",
                "worker-realized prefix-cache reuse over input tokens, "
                "across requests that stamped forensics back")

    # -- ingestion (RequestTracker.finish calls this) ---------------------
    def observe_finish(self, tracker, record: dict) -> None:
        try:
            self._observe(tracker, record)
        except Exception:
            logger.warning("forensics observation failed", exc_info=True)

    def _observe(self, tracker, record: dict) -> None:
        from .slo import breach_reason

        req = record.get("request", {})
        timeline = record.get("timeline") or {}
        model = req.get("model", "")
        total_ms = float(req.get("total_time_ms", 0.0))
        partition = timeline.get("partition") or phase_partition(
            timeline.get("hops") or [], total_ms,
            float(timeline.get("stall_ms", 0.0)))
        breach = breach_reason(self.slo_config, record)
        ex = TailExemplar(
            request_id=req.get("request_id", ""),
            model=model,
            ts_unix=time.time(),
            outcome=req.get("outcome", "ok"),
            e2e_ms=total_ms,
            ttft_ms=req.get("ttft_ms"),
            avg_itl_ms=req.get("avg_itl_ms"),
            breach=breach,
            partition=partition,
            record=record,
        )
        self._finished += 1
        stamp = timeline.get("worker")
        if stamp is not None:
            self._stamped += 1
            self._realized_tokens += int(stamp.get("cached_tokens") or 0)
            self._input_tokens += int(req.get("input_tokens") or 0)
            if self.m is not None and self._input_tokens:
                self.m.set("dynamo_frontend_realized_overlap_ratio",
                           self._realized_tokens / self._input_tokens)
        widx = int(ex.ts_unix // self.window_s)
        w = self._windows.setdefault(widx, {})
        while len(self._windows) > self.max_windows:
            self._windows.popitem(last=False)  # oldest window evicted first
        per = w.setdefault(model, {
            "ttft": [], "itl": [], "breach": deque(maxlen=self.breach_cap),
        })
        if breach is not None:
            # every breach is retained (bounded), and pins its span
            # snapshot NOW — the ring will have churned past this
            # request by the time anyone reads the dump
            ex.spans = _pin_spans(getattr(tracker, "trace_id", None))
            per["breach"].append(ex)
            if self.m is not None:
                self.m.inc("dynamo_frontend_forensics_retained_total",
                           kind="breach")
        for rank_key, metric in (("ttft", ex.ttft_ms),
                                 ("itl", ex.avg_itl_ms)):
            if metric is None:
                continue
            self._rank_insert(per[rank_key], rank_key, ex, metric)

    def _rank_insert(self, ranked: List[TailExemplar], rank_key: str,
                     ex: TailExemplar, metric: float) -> None:
        """Keep the K SLOWEST, descending: a full list evicts its
        fastest (last) entry — the eviction order the tests pin."""
        key = {"ttft": lambda e: e.ttft_ms or 0.0,
               "itl": lambda e: e.avg_itl_ms or 0.0}[rank_key]
        if len(ranked) >= self.k and metric <= key(ranked[-1]):
            return
        ranked.append(ex)
        ranked.sort(key=key, reverse=True)
        while len(ranked) > self.k:
            ranked.pop()  # fastest exemplar falls off
        if self.m is not None:
            self.m.inc("dynamo_frontend_forensics_retained_total",
                       kind=rank_key)

    # -- read side --------------------------------------------------------
    def realized_overlap(self) -> dict:
        return {
            "requests": self._finished,
            "stamped": self._stamped,
            "realized_tokens": self._realized_tokens,
            "input_tokens": self._input_tokens,
            "ratio": (round(self._realized_tokens / self._input_tokens, 4)
                      if self._input_tokens else None),
        }

    def worst(self, rank_key: str = "ttft",
              model: Optional[str] = None) -> Optional[TailExemplar]:
        """The single slowest retained exemplar by `rank_key` across
        windows (the bench tail block's p99 stand-in: the reservoir
        already IS the tail)."""
        key = {"ttft": lambda e: e.ttft_ms or 0.0,
               "itl": lambda e: e.avg_itl_ms or 0.0}[rank_key]
        best: Optional[TailExemplar] = None
        for w in self._windows.values():
            for m, per in w.items():
                if model is not None and m != model:
                    continue
                for ex in per[rank_key][:1]:
                    if best is None or key(ex) > key(best):
                        best = ex
        return best

    @staticmethod
    def _distinct(per: dict) -> int:
        """Distinct retained requests in one (model, window) bucket —
        the same exemplar commonly sits in both ranked lists (and the
        breach deque), and the count must agree with the tail
        autopsy's request_id dedupe, not double-count."""
        return len({e.request_id
                    for key in ("ttft", "itl", "breach")
                    for e in per[key]})

    def counts(self) -> dict:
        """Cheap retained-exemplar counts (the /debug/state tail line —
        the full payload lives on /debug/requests)."""
        n_ex = n_breach = 0
        for w in self._windows.values():
            for per in w.values():
                n_ex += self._distinct(per)
                n_breach += len(per["breach"])
        return {"exemplars": n_ex, "breaches": n_breach}

    def dump(self) -> dict:
        """The /debug/requests payload (schema dynamo.forensics.v1)."""
        models: Dict[str, list] = {}
        n_ex = n_breach = 0
        for widx, w in self._windows.items():
            for model, per in w.items():
                n_ex += self._distinct(per)
                n_breach += len(per["breach"])
                models.setdefault(model, []).append({
                    "window": widx,
                    "window_start_unix": widx * self.window_s,
                    "ttft": [e.to_dict() for e in per["ttft"]],
                    "itl": [e.to_dict() for e in per["itl"]],
                    "breach": [e.to_dict() for e in per["breach"]],
                })
        return {
            "schema": SCHEMA,
            "ts_unix": round(time.time(), 3),
            "window_s": self.window_s,
            "k": self.k,
            "exemplars": n_ex,
            "breaches": n_breach,
            "realized_overlap": self.realized_overlap(),
            "models": models,
        }


__all__ = [
    "HOP_KINDS",
    "PHASES",
    "SCHEMA",
    "ForensicsPlane",
    "TailExemplar",
    "forensics_enabled",
    "phase_partition",
    "stall_threshold_s",
]
