from .llm import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from .model_card import ModelDeploymentCard

__all__ = [
    "FinishReason",
    "LLMEngineOutput",
    "ModelDeploymentCard",
    "PreprocessedRequest",
    "SamplingOptions",
    "StopConditions",
]
