from .llm import (
    DRAIN_ABORT,
    DRAIN_REJECT,
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from .model_card import ModelDeploymentCard

__all__ = [
    "DRAIN_ABORT",
    "DRAIN_REJECT",
    "FinishReason",
    "LLMEngineOutput",
    "ModelDeploymentCard",
    "PreprocessedRequest",
    "SamplingOptions",
    "StopConditions",
]
