"""Engine-facing request/response protocol.

Ref: lib/llm/src/protocols/ — `PreprocessedRequest` is what the frontend's
preprocessor emits and every engine backend (mocker, JAX) consumes;
`LLMEngineOutput` is the per-step stream item flowing back.  These cross the
request plane as msgpack dicts, so each type round-trips via to_dict/from_dict
with only wire-safe values (ints ≤ 64 bit, strings, lists).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

FinishReason = str  # "stop" | "length" | "eos" | "cancelled" | "error"

# request annotation marking a disaggregated-prefill hop (the worker runs
# prefill only and parks the KV for the decode worker to pull)
DISAGG_ANNOTATION = "disagg_prefill"

# graceful-drain error markers (engine/worker.py drain(), mocker drain):
# one shared definition because BOTH engines must emit byte-identical
# text and the frontend's migratable classification
# (frontend/pipeline.py MIGRATABLE_MARKERS) substring-matches the
# "worker draining" prefix — a reworded copy in one engine would
# silently break token-replay migration for that engine only
DRAIN_REJECT = "worker draining: request rejected before admission"
DRAIN_ABORT = "worker draining: in-flight request migrating"


@dataclass
class SamplingOptions:
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled
    seed: Optional[int] = None
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    # guided decoding (ref structural outputs / guided_json): constrain
    # output to a JSON document conforming to this schema
    # (guided/json_prefix.py); None = unconstrained
    guided_json: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "temperature": self.temperature,
            "top_p": self.top_p,
            "top_k": self.top_k,
            "seed": self.seed,
            "frequency_penalty": self.frequency_penalty,
            "presence_penalty": self.presence_penalty,
            "guided_json": self.guided_json,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "SamplingOptions":
        return SamplingOptions(
            temperature=d.get("temperature", 1.0),
            top_p=d.get("top_p", 1.0),
            top_k=d.get("top_k", 0),
            seed=d.get("seed"),
            frequency_penalty=d.get("frequency_penalty", 0.0),
            guided_json=d.get("guided_json"),
            presence_penalty=d.get("presence_penalty", 0.0),
        )


@dataclass
class StopConditions:
    max_tokens: int = 16
    stop: List[str] = field(default_factory=list)
    stop_token_ids: List[int] = field(default_factory=list)
    ignore_eos: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "max_tokens": self.max_tokens,
            "stop": self.stop,
            "stop_token_ids": self.stop_token_ids,
            "ignore_eos": self.ignore_eos,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "StopConditions":
        return StopConditions(
            max_tokens=d.get("max_tokens", 16),
            stop=d.get("stop", []),
            stop_token_ids=d.get("stop_token_ids", []),
            ignore_eos=d.get("ignore_eos", False),
        )


# minimal liveness probe riding the real generate path (ref
# health_check.rs canary payloads): 2-token prompt, 1 greedy token out
CANARY_GENERATE_PAYLOAD: Dict[str, Any] = {
    "token_ids": [1, 2],
    "stop": {"max_tokens": 1, "ignore_eos": True},
    "annotations": ["canary"],
}


@dataclass
class PreprocessedRequest:
    """Tokenized request, ready for an engine (ref: protocols PreprocessedRequest)."""

    token_ids: List[int]
    model: str = ""
    request_id: str = ""
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stop: StopConditions = field(default_factory=StopConditions)
    lora_name: Optional[str] = None
    # agent session identity (ref protocols/agents.rs): sticky routing via
    # session affinity; session_final marks the session's last request
    session_id: Optional[str] = None
    session_final: bool = False
    # disaggregation: set by the prefill worker, consumed by decode
    disaggregated_params: Optional[Dict[str, Any]] = None
    # annotations requested by the client (e.g. request tracing)
    annotations: List[str] = field(default_factory=list)
    # data-parallel rank of the target engine (ref WorkerWithDpRank,
    # selector.rs:33): set by the KV router when it picks a specific dp
    # rank; workers with dp ranks dispatch the request to that rank's
    # scheduler/cache
    dp_rank: int = 0
    # multimodal items (encoder disagg, multimodal/): before the encoder
    # hop each item is a descriptor {media_hash, data_uri, insert_pos};
    # after it, {media_hash, n_tokens, embedding(bytes), shape, dtype}.
    # media_hash also salts KV block hashing so identical placeholder
    # tokens with different media never alias in any cache.
    multimodal: Optional[List[Dict[str, Any]]] = None

    @property
    def media_hashes(self) -> List[str]:
        return [m["media_hash"] for m in self.multimodal or []
                if m.get("media_hash")]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "token_ids": list(self.token_ids),
            "model": self.model,
            "request_id": self.request_id,
            "sampling": self.sampling.to_dict(),
            "stop": self.stop.to_dict(),
            "lora_name": self.lora_name,
            "session_id": self.session_id,
            "session_final": self.session_final,
            "disaggregated_params": self.disaggregated_params,
            "annotations": self.annotations,
            "dp_rank": self.dp_rank,
            "multimodal": self.multimodal,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PreprocessedRequest":
        return PreprocessedRequest(
            token_ids=list(d.get("token_ids", [])),
            model=d.get("model", ""),
            request_id=d.get("request_id", ""),
            sampling=SamplingOptions.from_dict(d.get("sampling", {})),
            stop=StopConditions.from_dict(d.get("stop", {})),
            lora_name=d.get("lora_name"),
            session_id=d.get("session_id"),
            session_final=bool(d.get("session_final", False)),
            disaggregated_params=d.get("disaggregated_params"),
            annotations=d.get("annotations", []),
            dp_rank=int(d.get("dp_rank", 0)),
            multimodal=d.get("multimodal"),
        )


@dataclass
class LLMEngineOutput:
    """One stream item from an engine: a batch of new tokens (usually 1).

    Ref: protocols LLMEngineOutput / BackendOutput.  `kv_transfer_params`
    carries disagg metadata on the prefill response's final item.
    """

    token_ids: List[int] = field(default_factory=list)
    finish_reason: Optional[FinishReason] = None
    cum_log_prob: Optional[float] = None
    kv_transfer_params: Optional[Dict[str, Any]] = None
    # engine-side observability (FPM): step latency, queue depth, etc.
    metrics: Optional[Dict[str, Any]] = None
    # set when finish_reason == "error": what failed.  "worker engine
    # error" prefixed messages are migratable (worker-side failure);
    # anything else is a terminal request error.
    error: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"token_ids": list(self.token_ids)}
        if self.finish_reason is not None:
            d["finish_reason"] = self.finish_reason
        if self.cum_log_prob is not None:
            d["cum_log_prob"] = self.cum_log_prob
        if self.kv_transfer_params is not None:
            d["kv_transfer_params"] = self.kv_transfer_params
        if self.metrics is not None:
            d["metrics"] = self.metrics
        if self.error is not None:
            d["error"] = self.error
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "LLMEngineOutput":
        return LLMEngineOutput(
            token_ids=list(d.get("token_ids", [])),
            finish_reason=d.get("finish_reason"),
            cum_log_prob=d.get("cum_log_prob"),
            kv_transfer_params=d.get("kv_transfer_params"),
            metrics=d.get("metrics"),
            error=d.get("error"),
        )
