"""ModelDeploymentCard: everything a frontend needs to serve a model.

Ref: lib/llm/src/model_card.rs:821 — published by workers under
`v1/mdc/{namespace}/{model_slug}` (ref :110) and consumed by the frontend's
ModelWatcher.  Carries tokenizer identity, chat template, KV block size,
context length, and runtime config (capacity hints for routing/planning).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..runtime.discovery import MDC_PREFIX


def model_slug(name: str) -> str:
    return name.replace("/", "--")


@dataclass
class ModelDeploymentCard:
    name: str
    namespace: str = "dynamo"
    component: str = "backend"
    endpoint: str = "generate"
    model_type: str = "chat"  # chat | completions | embedding | encoder
    # tokenizer: {"type": "byte"} or {"type": "hf", "path"/"json": ...}
    tokenizer: Dict[str, Any] = field(default_factory=lambda: {"type": "byte"})
    chat_template: Optional[str] = None
    context_length: int = 8192
    kv_cache_block_size: int = 64
    migration_limit: int = 0
    runtime_config: Dict[str, Any] = field(default_factory=dict)

    def key(self, instance_id: Optional[int] = None) -> str:
        """MDC discovery key.  Per-worker keys (with instance_id) let many
        workers serve one model: the frontend drops the model only when the
        LAST worker's card disappears."""
        base = f"{MDC_PREFIX}/{self.namespace}/{model_slug(self.name)}"
        return f"{base}/{instance_id}" if instance_id is not None else base

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "namespace": self.namespace,
            "component": self.component,
            "endpoint": self.endpoint,
            "model_type": self.model_type,
            "tokenizer": self.tokenizer,
            "chat_template": self.chat_template,
            "context_length": self.context_length,
            "kv_cache_block_size": self.kv_cache_block_size,
            "migration_limit": self.migration_limit,
            "runtime_config": self.runtime_config,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ModelDeploymentCard":
        return ModelDeploymentCard(
            name=d["name"],
            namespace=d.get("namespace", "dynamo"),
            component=d.get("component", "backend"),
            endpoint=d.get("endpoint", "generate"),
            model_type=d.get("model_type", "chat"),
            tokenizer=d.get("tokenizer", {"type": "byte"}),
            chat_template=d.get("chat_template"),
            context_length=d.get("context_length", 8192),
            kv_cache_block_size=d.get("kv_cache_block_size", 64),
            migration_limit=d.get("migration_limit", 0),
            runtime_config=d.get("runtime_config", {}),
        )


async def register_model(runtime, card: ModelDeploymentCard,
                         instance_id: Optional[int] = None) -> None:
    """Publish the MDC (ref: lib/bindings/python/rust/lib.rs:368 register_model)."""
    await runtime.discovery.put(card.key(instance_id), card.to_dict())


async def deregister_model(runtime, card: ModelDeploymentCard,
                           instance_id: Optional[int] = None) -> None:
    await runtime.discovery.delete(card.key(instance_id))
