"""Multi-host SPMD serving: one routing identity per N-host slice.

The reference's workers are one-process-one-GPU with NCCL underneath; a
TPU slice is different: ONE jit program spans N hosts (jax.distributed),
every process must execute the SAME sequence of jit calls, and only the
slice — not each host — is a meaningful routing target (SURVEY §7 hard
part 3).  This module maps that model onto the worker contract:

  * MultihostContext — who am I in the slice.  Detected from
    jax.process_index()/process_count() (overridable via DYN_MH_RANK /
    DYN_MH_WORLD for tests and non-jax transports).
  * Leader gating — ONLY process 0 registers the model card and serves
    the generate/clear/kv_* endpoints, so the router sees one instance
    per slice.  Followers hold the same weights/KV shards and execute
    the same programs, but have no network identity.
  * StepBroadcaster / StepFollower — the leader's scheduler publishes an
    ordered stream of step descriptors (kind + host batch arrays) on the
    event plane; followers replay them call-for-call, keeping every
    process's jit sequence identical.  Step kinds span the whole compute
    surface: prefill (single/batched/packed/ring), decode (full/multi/
    continuation), guided top-M, speculative verification (spec_verify),
    KV gather/inject, lora_write, and embed — see engine/core.py
    apply_step.  Sequence numbers make gaps loud:
    a follower that misses a step CANNOT continue (its next collective
    would deadlock or corrupt), so it raises instead of resubscribing.

What is validated where: protocol ordering/gating is tested single-host
(tests/test_multihost.py, two engine replicas standing in for two host
shards); the XLA side (jax.distributed.initialize + global arrays) needs
real multi-host hardware and is intentionally a thin, documented seam —
`initialize()` below.
"""

from __future__ import annotations

import asyncio
import logging
import os
from dataclasses import dataclass
from typing import AsyncIterator, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """jax.distributed.initialize with env fallbacks (JAX's own
    COORDINATOR_ADDRESS etc. still apply).  Call before first jax use on
    every host of the slice."""
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator, num_processes=num_processes,
        process_id=process_id,
    )


@dataclass(frozen=True)
class MultihostContext:
    rank: int = 0
    world: int = 1

    @property
    def is_leader(self) -> bool:
        return self.rank == 0

    @classmethod
    def detect(cls) -> "MultihostContext":
        """DYN_MH_RANK/DYN_MH_WORLD override (tests, pre-init tooling);
        otherwise whatever jax.distributed reports."""
        if "DYN_MH_RANK" in os.environ:
            return cls(rank=int(os.environ["DYN_MH_RANK"]),
                       world=int(os.environ.get("DYN_MH_WORLD", "1")))
        try:
            import jax

            return cls(rank=jax.process_index(), world=jax.process_count())
        except Exception:  # pragma: no cover — jax not initialized yet
            return cls()


def step_subject(namespace: str, component: str, instance_id: int) -> str:
    return f"mh_step.{namespace}.{component}.{instance_id}"


def _pack(arrays: Dict[str, np.ndarray]) -> Dict[str, dict]:
    return {
        k: {"b": np.ascontiguousarray(a).tobytes(),
            "shape": list(a.shape), "dtype": a.dtype.name}
        for k, a in arrays.items()
    }


def _unpack(wire: Dict[str, dict]) -> Dict[str, np.ndarray]:
    return {
        k: np.frombuffer(d["b"], dtype=np.dtype(d["dtype"]))
        .reshape(d["shape"])
        for k, d in wire.items()
    }


def ready_subject(namespace: str, component: str, instance_id: int) -> str:
    return f"mh_ready.{namespace}.{component}.{instance_id}"


class StepBroadcaster:
    """Leader side: ordered step-descriptor stream for the slice.

    Synchronous enqueue (call from the scheduler thread via the loop, like
    KV events) + single-writer publish keeps wire order equal to execution
    order.  A publish that still fails after retries is FATAL (via
    on_fatal): dropping one frame would turn into a permanent sequence gap
    that kills every follower while the leader keeps serving — the slice
    must restart together instead."""

    def __init__(self, runtime, namespace: str, component: str,
                 instance_id: int, on_fatal=None):
        self.runtime = runtime
        self.subject = step_subject(namespace, component, instance_id)
        self._seq = 0
        self._outbox: asyncio.Queue = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self.on_fatal = on_fatal

    async def start(self) -> "StepBroadcaster":
        self._task = asyncio.create_task(self._drain())
        return self

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()

    async def hello(self) -> None:
        """Barrier probe: a sentinel (seq -1) on the step subject.  A
        follower acks the barrier only after receiving one — proof positive
        its subscription is attached to THIS broadcaster's stream, with no
        assumptions about pub/sub join timing.  Safe to publish directly
        (not via the outbox): hellos happen strictly before the barrier
        passes and steps strictly after, so they never interleave."""
        await self.runtime.event_plane.publish(
            self.subject, {"seq": -1, "kind": "__hello__", "meta": {},
                           "arrays": {}})

    def publish_step(self, kind: str,
                     arrays: Optional[Dict[str, np.ndarray]] = None,
                     meta: Optional[dict] = None) -> int:
        seq = self._seq
        self._seq += 1
        self._outbox.put_nowait({
            "seq": seq, "kind": kind, "meta": meta or {},
            "arrays": _pack(arrays or {}),
        })
        return seq

    async def _drain(self) -> None:
        try:
            while True:
                msg = await self._outbox.get()
                for attempt in range(3):
                    try:
                        await self.runtime.event_plane.publish(
                            self.subject, msg)
                        break
                    except Exception:
                        logger.warning("step broadcast attempt %d failed",
                                       attempt + 1, exc_info=True)
                        await asyncio.sleep(0.05 * (attempt + 1))
                else:
                    logger.critical(
                        "step %s unpublishable; slice is broken — leader "
                        "must restart", msg.get("seq"))
                    if self.on_fatal is not None:
                        self.on_fatal()
                    return
        except asyncio.CancelledError:
            pass


class StepGapError(RuntimeError):
    """A follower missed a step: its jit sequence has diverged from the
    slice and it must crash-restart (collectives would hang otherwise)."""


class StepFollower:
    """Follower side: yields (kind, arrays, meta) strictly in order."""

    def __init__(self, runtime, namespace: str, component: str,
                 instance_id: int):
        self.runtime = runtime
        self.subject = step_subject(namespace, component, instance_id)
        self._cancel = asyncio.Event()
        self._next = 0
        #: pulsed on every hello sentinel received from the leader.  A
        #: hello in hand proves this follower's subscription is attached to
        #: the leader's stream, so acking the barrier after one can never
        #: leave step 0 published into the void (permanent StepGapError).
        self.hello = asyncio.Event()

    async def steps(self) -> AsyncIterator[Tuple[str, Dict[str, np.ndarray],
                                                 dict]]:
        async for _subj, msg in self.runtime.event_plane.subscribe(
            self.subject, cancel=self._cancel
        ):
            seq = msg.get("seq")
            if seq == -1:  # barrier probe, not a step
                self.hello.set()
                continue
            if seq != self._next:
                raise StepGapError(
                    f"expected step {self._next}, got {seq}: this follower "
                    "has diverged from the slice and must restart"
                )
            self._next += 1
            yield msg["kind"], _unpack(msg["arrays"]), msg.get("meta", {})

    def stop(self) -> None:
        self._cancel.set()
