"""Device mesh + sharding policy for the JAX engine.

TPU-first design: intra-model parallelism is expressed as NamedSharding over
a (dp, tp) mesh and compiled by XLA into ICI collectives — the equivalent of
the engine-internal NCCL TP the reference passes through to vLLM/TRT-LLM
(SURVEY.md §2.4).  Axes:

  dp — data parallel: replicas of the model, each with its own KV cache and
       its own routing identity (WorkerWithDpRank in the router).
  tp — tensor parallel: attention heads / MLP hidden / vocab sharded; KV
       cache sharded over kv_heads.

  sp — sequence parallel: long-context ring attention
       (ops/ring_attention.py) shards the sequence axis here.

Expert parallel ("ep", MoE) reuses the tp axis by default.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass
class MeshConfig:
    dp: int = 1
    tp: int = 1
    sp: int = 1  # sequence parallel: the ring axis of ops/ring_attention.py

    @property
    def num_devices(self) -> int:
        return self.dp * self.tp * self.sp


def make_mesh(cfg: Optional[MeshConfig] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if cfg is None:
        cfg = MeshConfig(dp=1, tp=len(devices))
    if cfg.num_devices > len(devices):
        raise ValueError(
            f"mesh needs {cfg.num_devices} devices, have {len(devices)}"
        )
    dev_array = np.array(devices[: cfg.num_devices]).reshape(
        cfg.dp, cfg.tp, cfg.sp
    )
    return Mesh(dev_array, axis_names=("dp", "tp", "sp"))


def param_sharding_rules() -> dict:
    """Parameter PartitionSpecs by logical name (Llama-family layout).

    Column-parallel projections shard the output feature dim; row-parallel
    shard the input dim so XLA inserts a psum on the way out — the standard
    Megatron layout mapped onto GSPMD.
    """
    return {
        "embedding": P("tp", None),        # [vocab, d_model]
        "wq": P(None, "tp"),               # [d_model, q_heads*hd]
        "wk": P(None, "tp"),               # [d_model, kv_heads*hd]
        "wv": P(None, "tp"),
        "wo": P("tp", None),               # [q_heads*hd, d_model]
        "w_gate": P(None, "tp"),           # [d_model, ffn]
        "w_up": P(None, "tp"),
        "w_down": P("tp", None),           # [ffn, d_model]
        "norm": P(None),
        "lm_head": P(None, "tp"),          # [d_model, vocab]
        # MoE (expert-sharded over tp)
        "moe_gate": P(None, None),
        "moe_w_gate": P("tp", None, None),  # [experts, d_model, ffn]
        "moe_w_up": P("tp", None, None),
        "moe_w_down": P("tp", None, None),
        # MLA (DeepSeek family, models/deepseek.py): heads shard over tp
        # through the query up-projection and the latent up-projections;
        # the shared latent path (wkv_a) is replicated like the cache
        "wq_a": P(None, None),              # [d_model, q_lora_rank]
        "wq_b": P(None, "tp"),              # [q_lora_rank, nh*qk_head]
        "wkv_a": P(None, None),             # [d_model, R+dr]
        "w_uk": P("tp", None, None),        # [nh, R, dn]
        "w_uv": P("tp", None, None),        # [nh, R, dv]
    }


def shard_params(params, mesh: Mesh):
    """Apply the sharding rules to a parameter pytree.

    The rule key is the innermost dict key on the leaf's path (pytree
    structure — lists of layers etc. — is preserved)."""
    rules = param_sharding_rules()

    def put(path, leaf):
        name = None
        for k in reversed(path):
            key = getattr(k, "key", None)
            if isinstance(key, str):
                name = key
                break
        spec = rules.get(name, P())
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(put, params)


def kv_cache_spec() -> P:
    """KV cache [layers, kv_heads, blocks, head_dim, block_size]: shard the
    kv_heads axis over tp (same split as the attention heads).  Head-major
    layout keeps each tp shard a single contiguous slab."""
    return P(None, "tp", None, None, None)


def kv_scale_spec() -> P:
    """Quantization scales [layers, kv_heads, blocks, block_size] riding
    next to an int8 cache (quant/kv.py): same kv_heads split over tp, so
    a shard's scale plane stays co-resident with its cache slab."""
    return P(None, "tp", None, None)
