"""Pipeline parallelism: stage-stacked SPMD pipelining over a "pp" axis.

The TPU-native expression of the reference's PP strategy (SURVEY §2.4):
instead of one process per stage exchanging activations over NCCL P2P, ALL
stages run one SPMD program.  Layer parameters (and any per-stage state,
e.g. that stage's KV slice) are stacked on a leading stage axis and sharded
over the "pp" mesh axis, so each device physically holds only its own
stage's weights; activations rotate stage-to-stage with `lax.ppermute`
(neighbor hops on the ICI ring) under `shard_map`.

Schedule: the standard rotating microbatch pipeline (GPipe-style fill +
drain).  With S stages and M microbatches, the loop runs S+M-1 ticks; at
tick t, stage s processes microbatch m = t - s when 0 <= m < M, else it is
a bubble.  Utilization is M/(S+M-1) — callers should feed M >= S
microbatches.  Bubbles still execute the stage computation (SPMD programs
cannot diverge) but their `active` flag is False so stage_fn masks its
state writes and the result is discarded.

This module is the PP primitive; the serving engine composes it by making
one "stage" = its contiguous slice of transformer layers with that slice's
KV as the per-stage state.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .compat import pvary, shard_map

# stage_fn(params_slice, state_slice, x, active) -> (y, new_state_slice)
#   params_slice/state_slice: this stage's slice (leading stage axis
#   removed), x: one microbatch's activations, active: bool scalar — False
#   during pipeline bubbles; stage_fn MUST make state writes a no-op then.
StageFn = Callable


def _pipeline_shard(params, state, xs, *, stage_fn: StageFn, axis: str,
                    n_micro: int):
    """Per-device body.  params/state arrive as this stage's slice with a
    leading axis of size 1; xs [M, ...] is replicated."""
    S = lax.psum(1, axis)
    sidx = lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda a: a[0], params)
    state = jax.tree_util.tree_map(lambda a: a[0], state)
    M = n_micro

    def tick(t, carry):
        buf, ys, state = carry
        m = t - sidx                      # microbatch at this stage now
        active = (m >= 0) & (m < M)
        m_c = jnp.clip(m, 0, M - 1)
        # stage 0 ingests fresh microbatches; later stages consume the
        # rotated activation from their predecessor
        x_in = jnp.where(sidx == 0, xs[m_c], buf)
        y, state = stage_fn(params, state, x_in, active)
        # the LAST stage's result is final: accumulate into ys (masked)
        is_out = active & (sidx == S - 1)
        ys = ys.at[m_c].set(jnp.where(is_out, y, ys[m_c]))
        # rotate activations one stage forward (ring hop)
        perm = [(j, (j + 1) % S) for j in range(S)]
        buf = lax.ppermute(y, axis, perm)
        return buf, ys, state

    buf0 = pvary(jnp.zeros_like(xs[0]), axis)
    ys0 = pvary(jnp.zeros_like(xs), axis)
    _, ys, state = lax.fori_loop(0, S + M - 1, tick, (buf0, ys0, state))
    # outputs live on the last stage only; sum-reduce replicates them
    ys = lax.psum(ys, axis)
    state = jax.tree_util.tree_map(lambda a: a[None], state)
    return ys, state


def pipeline_apply(
    stage_fn: StageFn,
    params,            # pytree, leaves [S, ...] (stage-stacked)
    state,             # pytree, leaves [S, ...] (per-stage state; may be {})
    xs: jax.Array,     # [M, ...] microbatches
    mesh: Mesh,
    axis: str = "pp",
) -> Tuple[jax.Array, object]:
    """Run every microbatch through all S stages; returns (ys [M, ...],
    updated per-stage state, still stage-stacked/sharded)."""
    S = mesh.shape[axis]
    for path, leaf in jax.tree_util.tree_leaves_with_path(params) + \
            jax.tree_util.tree_leaves_with_path(state):
        if leaf.shape[:1] != (S,):
            # P(axis) would hand each device a multi-stage slice and the
            # body would silently apply only the first — be loud instead
            raise ValueError(
                f"stage-stacked leaf {jax.tree_util.keystr(path)} has "
                f"leading dim {leaf.shape[0] if leaf.ndim else None}, "
                f"expected the pp axis size {S}"
            )
    n_micro = xs.shape[0]
    stage_spec = jax.tree_util.tree_map(lambda _: P(axis), params)
    state_spec = jax.tree_util.tree_map(lambda _: P(axis), state)
    fn = shard_map(
        partial(_pipeline_shard, stage_fn=stage_fn, axis=axis,
                n_micro=n_micro),
        mesh=mesh,
        in_specs=(stage_spec, state_spec, P()),
        out_specs=(P(), state_spec),
    )
    return fn(params, state, xs)
