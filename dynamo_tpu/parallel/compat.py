"""jax version-compat shims shared by the shard_map-based parallel ops
(ops/ring_attention.py, parallel/pipeline.py)."""

from __future__ import annotations

from jax import lax

try:  # promoted API in jax>=0.8; experimental path for older
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *, check_vma=None, **kw):
        """The experimental API spells the replication check `check_rep`."""
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _shard_map_exp(f, **kw)


def pvary(x, axis_name: str):
    """Mark a device-invariant value as varying over `axis_name` (jax>=0.9
    varying-manual-axes tracking); identity on older jax."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis_name,), to="varying")
    return x
