"""dynamo_tpu — a TPU-native distributed LLM inference framework.

Capability-parity rebuild of NVIDIA Dynamo (surveyed in SURVEY.md) designed
TPU-first: the orchestration layer (runtime, KV-aware router, disaggregated
serving, KV block manager, planner) plus — unlike the reference, which
delegates to vLLM/SGLang/TRT-LLM — a native JAX/XLA/Pallas inference engine.

Layer map (mirrors reference layers L0..L8, see SURVEY.md §1):
  runtime/    — distributed runtime: discovery, request plane, endpoints,
                cancellation, metrics (ref: lib/runtime)
  tokens/     — token blocks + PositionalLineageHash contract
                (ref: lib/tokens, lib/kv-hashing)
  router/     — KV-aware routing: indexer, selector, slot manager
                (ref: lib/kv-router, lib/llm/src/kv_router)
  mocker/     — GPU/TPU-free simulated engine for CPU-only testing
                (ref: lib/mocker)
  frontend/   — OpenAI-compatible HTTP service + preprocessor + pipeline
                (ref: lib/llm/src/http, preprocessor, entrypoint)
  engine/     — native JAX engine: continuous batching, paged KV cache,
                sampling, worker contract (new; no reference equivalent)
  models/     — model families (Llama dense, MoE) as functional JAX code
  ops/        — Pallas/XLA kernels (paged attention, block gather/scatter)
  parallel/   — mesh/sharding policy (tp/dp/ep/sp over ICI)
  kvbm/       — multi-tier KV block manager G1(HBM)/G2(host)/G3(disk)
                (ref: lib/kvbm-*)
  planner/    — SLA autoscaler OBSERVE→PREDICT→PROPOSE→EXECUTE
                (ref: components/src/dynamo/planner)
  lint/       — "dynlint": AST project lint turning shipped bug classes
                into enforced invariants (the rustc/clippy analogue the
                reference leans on; tier-1 gate in tests/test_lint.py)
"""

__version__ = "0.1.0"
