"""Host-RAM weight cache: fast worker restart without a disk reload.

Ref role: the reference's GPU Memory Service + ModelExpress keep weights
warm across process restarts (README.md:79 "7x faster startup",
lib/gpu_memory_service/README.md) — CUDA VMM handles have no TPU
analogue, so the TPU-native equivalent caches the POST-PROCESSED weight
tensors in tmpfs (/dev/shm — RAM-backed, survives process exit) keyed by
checkpoint path:

  * first load streams the HF checkpoint as usual (safetensors parse,
    transposes, dtype casts, expert stacking) and then writes each leaf
    of the final params pytree into the cache, one raw-bytes file per
    tensor + an index of (pytree path, shape, dtype)
  * a restarted worker maps each cached tensor with np.memmap (zero-copy
    from tmpfs) and device_puts it straight to its mesh sharding —
    skipping disk, parsing, and every transform

The cached form is the ENGINE's layout, not the checkpoint's, so the
cache also amortizes the expensive transforms (DeepSeek's q/kv
de-interleaves, MoE expert stacking), and it is sharding-agnostic: the
reader re-derives each leaf's NamedSharding from the same
param_sharding_rules() the loader uses, so a restarted worker may even
come back with a different tp.

Writes are atomic (tmp + rename of the index LAST), so a crashed writer
leaves no readable-but-partial cache.  Invalidation is by checkpoint
fingerprint (safetensors file names + sizes + mtimes) recorded in the
index: a re-downloaded checkpoint misses and rewrites.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
from typing import Any, Dict, Optional

import numpy as np

logger = logging.getLogger(__name__)

DEFAULT_DIR = "/dev/shm/dynamo_weight_cache"


def default_cache_dir() -> Optional[str]:
    """tmpfs when present (the point is RAM residency); None disables.
    The DYN_WEIGHT_CACHE=0 kill switch wins over DYN_WEIGHT_CACHE_DIR so
    an operator can force a clean checkpoint reload without unsetting
    the relocation var."""
    if os.environ.get("DYN_WEIGHT_CACHE", "1").lower() in ("0", "false",
                                                           "off", "no"):
        return None
    env = os.environ.get("DYN_WEIGHT_CACHE_DIR")
    if env:
        return env
    return DEFAULT_DIR if os.path.isdir("/dev/shm") else None


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def checkpoint_fingerprint(model_path: str) -> str:
    """Identity of the on-disk checkpoint: names + sizes + mtimes of its
    weight files (content hashing would cost a full disk read — the
    thing the cache exists to avoid)."""
    parts = []
    for f in sorted(os.listdir(model_path)):
        if f.endswith((".safetensors", ".json")):
            st = os.stat(os.path.join(model_path, f))
            parts.append(f"{f}:{st.st_size}:{int(st.st_mtime)}")
    return hashlib.sha1("|".join(parts).encode()).hexdigest()


def _entry_dir(cache_dir: str, model_path: str) -> str:
    h = hashlib.sha1(os.path.abspath(model_path).encode()).hexdigest()[:16]
    return os.path.join(cache_dir, h)


# -- pytree path <-> string -------------------------------------------------


def _flatten_with_paths(tree, prefix=""):
    """Yield (path, leaf) for dict/list pytrees ('layers.3.wq' form)."""
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _flatten_with_paths(tree[k], f"{prefix}{k}.")
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _flatten_with_paths(v, f"{prefix}{i}.")
    else:
        yield prefix[:-1], tree


def _insert_path(root: Dict[str, Any], path: str, value) -> None:
    parts = path.split(".")
    node = root
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value


def _listify(node):
    """Dicts whose keys are all consecutive ints become lists (restores
    the params['layers'] list)."""
    if not isinstance(node, dict):
        return node
    out = {k: _listify(v) for k, v in node.items()}
    if out and all(k.isdigit() for k in out):
        idx = sorted(out, key=int)
        if [int(k) for k in idx] == list(range(len(idx))):
            return [out[k] for k in idx]
    return out


# -- write ------------------------------------------------------------------


def write_cache(cache_dir: str, model_path: str, params) -> bool:
    """Persist the final params pytree leaf-by-leaf (one host staging
    buffer at a time).  Returns False (and cleans up) on any failure —
    the cache is an optimization, never a correctness dependency."""
    entry = _entry_dir(cache_dir, model_path)
    tmp = entry + ".tmp"
    try:
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp, exist_ok=True)
        index = {"fingerprint": checkpoint_fingerprint(model_path),
                 "tensors": {}}
        for i, (path, leaf) in enumerate(_flatten_with_paths(params)):
            arr = np.asarray(leaf)  # device->host, one leaf at a time
            fname = f"t{i}.bin"
            with open(os.path.join(tmp, fname), "wb") as f:
                f.write(np.ascontiguousarray(arr).view(np.uint8).tobytes())
            index["tensors"][path] = {
                "file": fname, "shape": list(arr.shape),
                "dtype": str(arr.dtype),
            }
        with open(os.path.join(tmp, "index.json.tmp"), "w") as f:
            json.dump(index, f)
        # index written LAST and atomically: readers key on its presence
        os.replace(os.path.join(tmp, "index.json.tmp"),
                   os.path.join(tmp, "index.json"))
        shutil.rmtree(entry, ignore_errors=True)
        os.replace(tmp, entry)
        logger.info("weight cache written for %s (%d tensors) -> %s",
                    model_path, len(index["tensors"]), entry)
        return True
    except Exception:
        logger.warning("weight cache write failed for %s", model_path,
                       exc_info=True)
        shutil.rmtree(tmp, ignore_errors=True)
        return False


# -- read -------------------------------------------------------------------


def read_cache(cache_dir: str, model_path: str, mesh=None):
    """Rebuild the params pytree from the cache, or None on miss/stale.

    Each tensor memmaps from tmpfs and device_puts onto its sharding —
    the fast-restart path (no disk, no parse, no transforms)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from ..parallel.mesh import param_sharding_rules

    entry = _entry_dir(cache_dir, model_path)
    index_path = os.path.join(entry, "index.json")
    try:
        with open(index_path) as f:
            index = json.load(f)
    except (OSError, ValueError):
        return None
    if index.get("fingerprint") != checkpoint_fingerprint(model_path):
        logger.info("weight cache stale for %s (checkpoint changed)",
                    model_path)
        return None
    rules = param_sharding_rules()
    root: Dict[str, Any] = {}
    try:
        for path, meta in index["tensors"].items():
            dt = _np_dtype(meta["dtype"])
            arr = np.memmap(os.path.join(entry, meta["file"]),
                            dtype=dt, mode="r",
                            shape=tuple(meta["shape"]))
            rule_key = path.split(".")[-1]
            if mesh is not None:
                leaf = jax.device_put(
                    arr, NamedSharding(
                        mesh, rules.get(rule_key, PartitionSpec())))
            else:
                leaf = jnp.asarray(arr)
            _insert_path(root, path, leaf)
    except Exception:
        logger.warning("weight cache read failed for %s; falling back to "
                       "checkpoint", model_path, exc_info=True)
        return None
    logger.info("weights restored from host cache for %s (%d tensors)",
                model_path, len(index["tensors"]))
    return _listify(root)


def clear_cache(cache_dir: str, model_path: Optional[str] = None) -> None:
    if model_path is not None:
        shutil.rmtree(_entry_dir(cache_dir, model_path),
                      ignore_errors=True)
    else:
        shutil.rmtree(cache_dir, ignore_errors=True)
