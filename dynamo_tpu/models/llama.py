"""Llama-family decoder as functional JAX code over a paged KV cache.

Covers the dense families in BASELINE.md configs (Llama-3 8B/70B, Qwen3
dense via qk_norm).  Pure functions over a params pytree — no Module
framework — so pjit/GSPMD shardings (parallel/mesh.py) and donation apply
cleanly.  Forward passes read/write KV through the paged cache ops in
ops/paged_attention.py; everything is static-shape for XLA.

Weights are bf16 by default (MXU-native); activations bf16 with fp32 for
norms/softmax accumulation.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple  # noqa: F401 (Tuple in cfg)

import jax
import jax.numpy as jnp

from ..ops.packed_prefill import packed_prefill_attention, write_packed_kv
from ..ops.paged_attention import (
    paged_attention_decode,
    paged_prefill_attention,
    write_prompt_kv,
    write_prompt_kv_batched,
    write_token_kv,
)
from ..quant.kv import unpack_kv


@dataclass(frozen=True)
class LlamaConfig:
    name: str = "tiny"
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    head_dim: int = 64
    ffn_dim: int = 1408
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    qk_norm: bool = False  # Qwen3-style per-head q/k RMSNorm
    tie_embeddings: bool = False
    max_context: int = 8192
    dtype: Any = jnp.bfloat16
    # decode attention path: "auto" | "pallas" | "pallas_interpret" |
    # "jnp" | "jnp_bf16" (ops/paged_attention.py rationale; every
    # choice accepts int8 caches — the Pallas kernel dequantizes
    # in-kernel)
    attn_impl: str = "auto"
    # packed-prefill attention path: "auto"/"xla" (the masked XLA
    # reference) | "pallas"/"pallas_interpret" (the tile-skip kernel,
    # ops/pallas_packed_prefill.py)
    packed_attn_impl: str = "auto"
    # stop-token set (instruct checkpoints often declare several, e.g.
    # llama-3's <|end_of_text|> and <|eot_id|>)
    eos_token_ids: Tuple[int, ...] = (2,)
    # MoE (Mixtral-family): 0 experts = dense MLP.  Experts shard over the
    # "tp" mesh axis (EP reuses tp, parallel/mesh.py moe_w_* rules).
    # Dispatch modes:
    #   "dense"    — every expert computes every token; the router weight
    #                matrix masks the combine.  DROPLESS and batch-
    #                invariant (same token -> same output regardless of
    #                chunking/co-batch), which prefix caching and greedy
    #                determinism rely on.  Costs E/k x the routed MLP
    #                FLOPs — the right trade for decode (bandwidth-bound)
    #                and correctness-critical serving.
    #   "capacity" — GShard capacity dispatch: tokens over an expert's
    #                C = ceil(T*k/E * capacity_factor) are dropped.  k/E
    #                of the FLOPs, but outputs vary with batch shape; use
    #                for throughput-oriented long-prefill deployments.
    n_experts: int = 0
    experts_per_token: int = 2
    moe_dispatch: str = "dense"
    moe_capacity_factor: float = 1.25

    @property
    def q_dim(self) -> int:
        return self.n_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.n_kv_heads * self.head_dim


def kv_cache_shapes(cfg: "LlamaConfig", num_blocks: int,
                    block_size: int) -> tuple:
    """(k, v) cache shapes in the head-major transposed block layout
    (ops/paged_attention.py)."""
    shape = (cfg.n_layers, cfg.n_kv_heads, num_blocks, cfg.head_dim,
             block_size)
    return shape, shape


def kv_cache_specs() -> tuple:
    """kv_heads sharded over tp (parallel/mesh.py kv_cache_spec)."""
    from ..parallel.mesh import kv_cache_spec

    return kv_cache_spec(), kv_cache_spec()


def kv_cache_scale_shapes(cfg: "LlamaConfig", num_blocks: int,
                          block_size: int) -> tuple:
    """(k_scale, v_scale) shapes for an int8 cache (quant/kv.py): one
    fp32 scale per (layer, kv_head, block, position), sibling to the
    paged cache.  The presence of this function is what marks a family
    as supporting `kv_cache_dtype="int8"` — families without it (MLA)
    auto-fall back to bf16 in the engine."""
    shape = (cfg.n_layers, cfg.n_kv_heads, num_blocks, block_size)
    return shape, shape


def kv_cache_scale_specs() -> tuple:
    """Scale planes shard with the cache (parallel/mesh.py
    kv_scale_spec: kv_heads over tp)."""
    from ..parallel.mesh import kv_scale_spec

    return kv_scale_spec(), kv_scale_spec()


# (k, v, k_scale | None, v_scale | None) from either cache arity —
# the shared tuple convention lives in quant/kv.py
_unpack_kv = unpack_kv


def _write_kv(fn, kv_cache, layer, *args):
    """Dispatch a cache write through `fn` (a write_* op from
    ops/paged_attention.py or ops/packed_prefill.py), threading the
    quantization scales when the cache is int8.  Returns the new cache
    tuple in the input's arity."""
    if len(kv_cache) == 4:
        k, v, ks, vs = kv_cache
        return fn(k, v, layer, *args, k_scale=ks, v_scale=vs)
    k, v = kv_cache
    return fn(k, v, layer, *args)


def prefill_ring(
    params: Dict[str, Any],
    cfg: "LlamaConfig",
    kv_cache: Tuple[jax.Array, jax.Array],
    token_ids: jax.Array,      # [T_pad] int32 (one sequence, padded)
    positions: jax.Array,      # [T_pad] int32, absolute positions
    block_table: jax.Array,    # [max_blocks] int32
    true_len: jax.Array,       # scalar int32: valid tokens
    mesh=None,
):
    """Sequence-parallel COLD prefill: attention FLOPs shard over the
    mesh's sp axis via ring attention (ops/ring_attention.py) instead of
    running the whole O(T^2) prompt on every device — the long-context
    path for prompts beyond the chunked-prefill buckets (SURVEY §5: the
    reference's engines own this; here it is native).

    One-shot (ctx_len=0, no prefix reuse — a partially cached long prompt
    falls back to chunked prefill).  Causality alone isolates the padded
    tail: valid queries only attend to j <= i < true_len, and
    write_prompt_kv masks the padded KV writes.  Returns
    (logits at the last valid position, updated kv_cache)."""
    from ..ops.ring_attention import ring_attention

    zero = jnp.int32(0)
    x = params["embedding"][token_ids].astype(cfg.dtype)  # [T, d]
    T = x.shape[0]
    for li, layer in enumerate(params["layers"]):
        h = rms_norm(x, layer["attn_norm"]["norm"], cfg.rms_eps)
        q, k, v = _qkv(layer, cfg, h, positions)
        kv_cache = _write_kv(write_prompt_kv, kv_cache, li, k, v,
                             block_table, zero, true_len)
        attn = ring_attention(q[None], k[None], v[None], mesh,
                              head_axis="tp")[0]
        x = x + _attn_out(layer, attn.reshape(T, cfg.q_dim))
        h = rms_norm(x, layer["mlp_norm"]["norm"], cfg.rms_eps)
        x = x + _ffn(layer, cfg, h, valid=jnp.arange(T) < true_len)
    last = jnp.maximum(true_len - 1, 0)
    logits = _logits(params, cfg, x[last])
    return logits, kv_cache


PRESETS: Dict[str, LlamaConfig] = {
    # test-scale
    "tiny": LlamaConfig(),
    "tiny-gqa": LlamaConfig(name="tiny-gqa", n_heads=8, n_kv_heads=2),
    # benchmark-scale (single v5e chip fits ~1-2B bf16 + KV)
    "llama-1b": LlamaConfig(
        name="llama-1b", vocab_size=128256, d_model=2048, n_layers=16,
        n_heads=32, n_kv_heads=8, head_dim=64, ffn_dim=8192,
        max_context=131072,
    ),
    # largest public-architecture config that fits ONE v5e chip (16G HBM)
    # with a serving KV cache: ~3.2B bf16 = ~6.4G weights (Llama-3.2-3B
    # geometry); the single-chip north-star bench model
    "llama-3b": LlamaConfig(
        name="llama-3b", vocab_size=128256, d_model=3072, n_layers=28,
        n_heads=24, n_kv_heads=8, head_dim=128, ffn_dim=8192,
        max_context=131072,
    ),
    # target configs (multi-chip; shapes from the public architectures)
    "llama-8b": LlamaConfig(
        name="llama-8b", vocab_size=128256, d_model=4096, n_layers=32,
        n_heads=32, n_kv_heads=8, head_dim=128, ffn_dim=14336,
        max_context=131072,
    ),
    "llama-70b": LlamaConfig(
        name="llama-70b", vocab_size=128256, d_model=8192, n_layers=80,
        n_heads=64, n_kv_heads=8, head_dim=128, ffn_dim=28672,
        max_context=131072,
    ),
    "qwen3-32b": LlamaConfig(
        name="qwen3-32b", vocab_size=151936, d_model=5120, n_layers=64,
        n_heads=64, n_kv_heads=8, head_dim=128, ffn_dim=25600,
        qk_norm=True, rope_theta=1000000.0, max_context=40960,
    ),
    # MoE family
    "tiny-moe": LlamaConfig(
        name="tiny-moe", vocab_size=256, d_model=64, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=16, ffn_dim=128,
        n_experts=4, experts_per_token=2,
    ),
    "mixtral-8x7b": LlamaConfig(
        name="mixtral-8x7b", vocab_size=32000, d_model=4096, n_layers=32,
        n_heads=32, n_kv_heads=8, head_dim=128, ffn_dim=14336,
        rope_theta=1000000.0, max_context=32768,
        n_experts=8, experts_per_token=2,
    ),
}


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def init_params(cfg: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    """Random-init parameter pytree (weight loading fills the same tree)."""

    def dense(key, shape, scale=None):
        scale = scale if scale is not None else 1.0 / math.sqrt(shape[0])
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(
            cfg.dtype
        )

    keys = jax.random.split(key, cfg.n_layers + 3)
    params: Dict[str, Any] = {
        "embedding": dense(keys[0], (cfg.vocab_size, cfg.d_model), scale=0.02),
        "final_norm": {"norm": jnp.ones((cfg.d_model,), jnp.float32)},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(keys[1], (cfg.d_model, cfg.vocab_size))
    layers = []
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[2 + i], 8)
        layer = {
            "attn_norm": {"norm": jnp.ones((cfg.d_model,), jnp.float32)},
            "mlp_norm": {"norm": jnp.ones((cfg.d_model,), jnp.float32)},
            "wq": dense(k[0], (cfg.d_model, cfg.q_dim)),
            "wk": dense(k[1], (cfg.d_model, cfg.kv_dim)),
            "wv": dense(k[2], (cfg.d_model, cfg.kv_dim)),
            "wo": dense(k[3], (cfg.q_dim, cfg.d_model)),
        }
        if cfg.n_experts > 0:
            E = cfg.n_experts
            layer["moe_gate"] = dense(k[4], (cfg.d_model, E))
            layer["moe_w_gate"] = dense(k[5], (E, cfg.d_model, cfg.ffn_dim),
                                        scale=1.0 / math.sqrt(cfg.d_model))
            layer["moe_w_up"] = dense(k[6], (E, cfg.d_model, cfg.ffn_dim),
                                      scale=1.0 / math.sqrt(cfg.d_model))
            layer["moe_w_down"] = dense(k[7], (E, cfg.ffn_dim, cfg.d_model),
                                        scale=1.0 / math.sqrt(cfg.ffn_dim))
        else:
            layer["w_gate"] = dense(k[4], (cfg.d_model, cfg.ffn_dim))
            layer["w_up"] = dense(k[5], (cfg.d_model, cfg.ffn_dim))
            layer["w_down"] = dense(k[6], (cfg.ffn_dim, cfg.d_model))
        if cfg.qk_norm:
            layer["q_norm"] = {"norm": jnp.ones((cfg.head_dim,), jnp.float32)}
            layer["k_norm"] = {"norm": jnp.ones((cfg.head_dim,), jnp.float32)}
        layers.append(layer)
    params["layers"] = layers
    return params


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------


def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps) * w).astype(x.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding.  x: [..., seq, heads, head_dim], positions: [..., seq]."""
    hd = x.shape[-1]
    half = hd // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., :, None].astype(jnp.float32) * freqs  # [..., seq, half]
    cos = jnp.cos(angles)[..., :, None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin], axis=-1
    )
    return out.astype(x.dtype)


def _qkv(layer, cfg: LlamaConfig, x: jax.Array, positions: jax.Array,
         lora=None):
    """x: [..., seq, d_model] -> q [..., seq, nh, hd], k/v [..., seq, nkv, hd].

    `lora`: optional (bank_layer, adapter_idx) — batched low-rank deltas
    added to the projections (lora/bank.py); slot 0 is zeros so mixed
    base/adapter batches share this program."""
    *lead, seq, _ = x.shape
    zq = x @ layer["wq"]
    zk = x @ layer["wk"]
    zv = x @ layer["wv"]
    if lora is not None:
        from ..lora.bank import lora_delta

        bl, idx = lora
        zq = zq + lora_delta(x, bl["A_q"], bl["B_q"], idx)
        zk = zk + lora_delta(x, bl["A_k"], bl["B_k"], idx)
        zv = zv + lora_delta(x, bl["A_v"], bl["B_v"], idx)
    q = zq.reshape(*lead, seq, cfg.n_heads, cfg.head_dim)
    k = zk.reshape(*lead, seq, cfg.n_kv_heads, cfg.head_dim)
    v = zv.reshape(*lead, seq, cfg.n_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        q = rms_norm(q, layer["q_norm"]["norm"], cfg.rms_eps)
        k = rms_norm(k, layer["k_norm"]["norm"], cfg.rms_eps)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    return q, k, v


def _attn_out(layer, attn_flat: jax.Array, lora=None) -> jax.Array:
    o = attn_flat @ layer["wo"]
    if lora is not None:
        from ..lora.bank import lora_delta

        bl, idx = lora
        o = o + lora_delta(attn_flat, bl["A_o"], bl["B_o"], idx)
    return o


def _lora_ctx(lora_bank, adapter_idx, li):
    """Per-layer LoRA context for _qkv/_attn_out, or None when disabled."""
    if lora_bank is None or adapter_idx is None:
        return None
    from ..lora.bank import bank_layer

    return bank_layer(lora_bank, li), adapter_idx


def _mlp(layer, x: jax.Array) -> jax.Array:
    return (jax.nn.silu(x @ layer["w_gate"]) * (x @ layer["w_up"])) @ layer[
        "w_down"
    ]


def _moe_router(layer, cfg: LlamaConfig, x: jax.Array):
    """Top-k routing: returns (weights [T,k] softmaxed, expert ids [T,k]).

    topk-then-softmax == HF Mixtral's softmax-topk-renormalize (softmax of
    the selected logits), verified against transformers in
    tests/test_loader.py."""
    router = (x.astype(jnp.float32) @ layer["moe_gate"].astype(jnp.float32))
    top_w, top_e = jax.lax.top_k(router, cfg.experts_per_token)
    return jax.nn.softmax(top_w, axis=-1), top_e


def moe_dispatch_dense(layer, cfg: LlamaConfig, x: jax.Array,
                       top_w: jax.Array, top_e: jax.Array,
                       valid: Optional[jax.Array] = None) -> jax.Array:
    """Dropless masked-dense MoE dispatch for precomputed routing
    (top_w/top_e [T, k]): all experts compute all tokens, the router
    matrix masks the combine.  Batch-invariant by construction.

    With experts sharded over tp, the expert einsums run local to each
    shard and the final combine reduces over the expert axis (one psum on
    the way out) — no dispatch tensors, no all-to-all."""
    T, d = x.shape
    E = cfg.n_experts
    wmat = jnp.zeros((T, E), jnp.float32).at[
        jnp.arange(T)[:, None], top_e
    ].set(top_w)                                       # [T, E]
    if valid is not None:
        wmat = wmat * valid.astype(jnp.float32)[:, None]
    h = jnp.einsum("td,edf->etf", x, layer["moe_w_gate"])
    h = jax.nn.silu(h) * jnp.einsum("td,edf->etf", x, layer["moe_w_up"])
    eout = jnp.einsum("etf,efd->etd", h, layer["moe_w_down"])
    return jnp.einsum("etd,te->td", eout, wmat.astype(cfg.dtype))


def _moe_mlp_dense(layer, cfg: LlamaConfig, x: jax.Array,
                   valid: Optional[jax.Array] = None) -> jax.Array:
    top_w, top_e = _moe_router(layer, cfg, x)
    return moe_dispatch_dense(layer, cfg, x, top_w, top_e, valid)


def moe_dispatch_capacity(layer, cfg: LlamaConfig, x: jax.Array,
                          top_w: jax.Array, top_e: jax.Array,
                          valid: Optional[jax.Array] = None) -> jax.Array:
    """Top-k routed expert MLP for precomputed routing, GShard
    capacity-dispatch formulation.

    x [T, d] -> [T, d].  Every step is a static-shape einsum so GSPMD can
    shard the expert axis (EP over the "tp" mesh axis via the moe_w_* rules
    in parallel/mesh.py) and insert the dispatch/combine all-to-alls —
    the TPU-native expression of the reference's EP path (SURVEY §2.4).
    Tokens past an expert's capacity C = ceil(T*k/E * capacity_factor) are
    dropped (their residual stream passes through), the standard
    inference-time overflow policy.

    `valid` [T] bool masks batch-padding rows OUT of dispatch entirely:
    the serving engine decodes a fixed batch whose inactive slots all embed
    token 0, route identically, and would otherwise eat the real tokens'
    expert capacity."""
    T, d = x.shape
    E, k = cfg.n_experts, cfg.experts_per_token
    C = max(1, math.ceil(T * k / E * cfg.moe_capacity_factor))

    e_flat = top_e.reshape(-1)                         # [T*k]
    w_flat = top_w.reshape(-1)
    onehot = jax.nn.one_hot(e_flat, E, dtype=jnp.int32)       # [Tk, E]
    if valid is not None:
        onehot = onehot * jnp.repeat(valid.astype(jnp.int32), k)[:, None]
    # each (token, slot)'s position within its expert's capacity buffer;
    # masked rows have all-zero onehot so they claim no position, and
    # one_hot(pos, C) zeroes any row with pos >= C (capacity drop)
    pos = jnp.take_along_axis(
        jnp.cumsum(onehot, axis=0) - onehot, e_flat[:, None], axis=1
    )[:, 0]                                            # [Tk]
    # dispatch [Tk, E, C]: one-hot (expert, slot) placement
    disp = onehot.astype(jnp.float32)[:, :, None] \
        * jax.nn.one_hot(pos, C, dtype=jnp.float32)[:, None, :]
    comb = disp * w_flat[:, None, None]                # combine weights

    x_rep = jnp.repeat(x, k, axis=0)                   # [Tk, d]
    ein = jnp.einsum("sec,sd->ecd", disp.astype(cfg.dtype), x_rep)
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", ein, layer["moe_w_gate"])) \
        * jnp.einsum("ecd,edf->ecf", ein, layer["moe_w_up"])
    eout = jnp.einsum("ecf,efd->ecd", h, layer["moe_w_down"])
    out = jnp.einsum("sec,ecd->sd", comb.astype(cfg.dtype), eout)
    return out.reshape(T, k, d).sum(axis=1)


def _moe_mlp(layer, cfg: LlamaConfig, x: jax.Array,
             valid: Optional[jax.Array] = None) -> jax.Array:
    top_w, top_e = _moe_router(layer, cfg, x)
    return moe_dispatch_capacity(layer, cfg, x, top_w, top_e, valid)


def _ffn(layer, cfg: LlamaConfig, x: jax.Array,
         valid: Optional[jax.Array] = None) -> jax.Array:
    """Dense or routed MLP over [..., d] (leading dims flattened for MoE)."""
    if cfg.n_experts <= 0:
        return _mlp(layer, x)
    if cfg.moe_dispatch not in ("dense", "capacity"):
        raise ValueError(
            f"moe_dispatch must be 'dense' or 'capacity', "
            f"got {cfg.moe_dispatch!r}"
        )
    lead = x.shape[:-1]
    if valid is not None:
        valid = valid.reshape(-1)
    moe = _moe_mlp if cfg.moe_dispatch == "capacity" else _moe_mlp_dense
    out = moe(layer, cfg, x.reshape(-1, x.shape[-1]), valid)
    return out.reshape(*lead, x.shape[-1])


def _logits(params, cfg: LlamaConfig, x: jax.Array) -> jax.Array:
    x = rms_norm(x, params["final_norm"]["norm"], cfg.rms_eps)
    if cfg.tie_embeddings:
        return (x @ params["embedding"].T).astype(jnp.float32)
    return (x @ params["lm_head"]).astype(jnp.float32)


# ---------------------------------------------------------------------------
# prefill: T_new prompt tokens attend to cached context + themselves (causal)
# ---------------------------------------------------------------------------


def prefill(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    kv_cache: Tuple[jax.Array, jax.Array],
    token_ids: jax.Array,      # [T_pad] int32 (one sequence, padded)
    positions: jax.Array,      # [T_pad] int32, absolute positions
    block_table: jax.Array,    # [max_blocks] int32, physical block ids
    ctx_len: jax.Array,        # scalar int32: tokens already cached (prefix)
    true_len: jax.Array,       # scalar int32: valid tokens in token_ids
    lora_bank=None,            # stacked adapter bank (lora/bank.py)
    adapter_idx=None,          # scalar int32: this sequence's bank slot
):
    """Run the prompt (or a prefill chunk) through the model.

    Supports prefix-cache hits and chunked prefill uniformly: the new tokens
    attend to `ctx_len` cached tokens (read via the block table) plus
    themselves causally.  Writes the new tokens' K/V into the paged cache.
    Returns (logits_at_last_valid [vocab], updated kv_cache).
    """
    x = params["embedding"][token_ids].astype(cfg.dtype)  # [T, d]
    for li, layer in enumerate(params["layers"]):
        lctx = _lora_ctx(lora_bank, adapter_idx, li)
        h = rms_norm(x, layer["attn_norm"]["norm"], cfg.rms_eps)
        q, k, v = _qkv(layer, cfg, h, positions, lora=lctx)
        kv_cache = _write_kv(write_prompt_kv, kv_cache, li, k, v,
                             block_table, ctx_len, true_len)
        k_cache, v_cache, ks, vs = _unpack_kv(kv_cache)
        attn = paged_prefill_attention(
            q, k, v, k_cache, v_cache, li, block_table, ctx_len, true_len,
            k_scale=ks, v_scale=vs,
        )
        x = x + _attn_out(layer, attn.reshape(x.shape[0], cfg.q_dim),
                          lora=lctx)
        h = rms_norm(x, layer["mlp_norm"]["norm"], cfg.rms_eps)
        # padding tokens past true_len must not eat MoE expert capacity
        x = x + _ffn(layer, cfg, h,
                     valid=jnp.arange(x.shape[0]) < true_len)
    last = jnp.maximum(true_len - 1, 0)
    logits = _logits(params, cfg, x[last])
    return logits, kv_cache


def prefill_batched(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    kv_cache: Tuple[jax.Array, jax.Array],
    token_ids: jax.Array,      # [Bp, T_pad] int32 (chunk per sequence)
    positions: jax.Array,      # [Bp, T_pad] int32, absolute positions
    block_tables: jax.Array,   # [Bp, max_blocks] int32
    ctx_lens: jax.Array,       # [Bp] int32: tokens already cached per seq
    true_lens: jax.Array,      # [Bp] int32: valid tokens per row
    lora_bank=None,            # stacked adapter bank (lora/bank.py)
    adapter_idx=None,          # [Bp] int32: bank slot per sequence
):
    """Multi-sequence chunked prefill: Bp sequences' chunks in ONE program.

    The MXU-utilization answer to concurrent arrivals (round-2 verdict weak
    #3: one B=1 chunk per scheduler step collapses TTFT under queue depth):
    short prompts that would each waste most of the token budget fill it
    together instead.  Semantically identical to running `prefill` per row
    — KV writes are a flat scatter over disjoint block sets, attention is
    vmapped per sequence over the shared cache (reads are masked to each
    row's own ctx/table), and padding rows (true_len 0) write only the
    garbage block.  Returns (logits [Bp, vocab] at each row's last valid
    token, updated kv_cache).
    """
    Bp, T = token_ids.shape
    x = params["embedding"][token_ids].astype(cfg.dtype)  # [Bp, T, d]
    valid = jnp.arange(T)[None, :] < true_lens[:, None]   # [Bp, T]
    for li, layer in enumerate(params["layers"]):
        lctx = _lora_ctx(lora_bank, adapter_idx, li)
        h = rms_norm(x, layer["attn_norm"]["norm"], cfg.rms_eps)
        q, k, v = _qkv(layer, cfg, h, positions, lora=lctx)  # [Bp,T,nh,hd]
        kv_cache = _write_kv(write_prompt_kv_batched, kv_cache, li, k, v,
                             block_tables, ctx_lens, true_lens)
        k_cache, v_cache, ks, vs = _unpack_kv(kv_cache)
        attn = jax.vmap(
            lambda qb, kb, vb, tb, cl, tl: paged_prefill_attention(
                qb, kb, vb, k_cache, v_cache, li, tb, cl, tl,
                k_scale=ks, v_scale=vs,
            )
        )(q, k, v, block_tables, ctx_lens, true_lens)
        x = x + _attn_out(layer, attn.reshape(Bp, T, cfg.q_dim), lora=lctx)
        h = rms_norm(x, layer["mlp_norm"]["norm"], cfg.rms_eps)
        if cfg.n_experts > 0:
            # per-row dispatch: each sequence keeps its OWN expert-capacity
            # pool, matching the B=1 program — co-scheduled requests must
            # not capacity-drop each other's tokens
            x = x + jax.vmap(
                lambda hb, vb: _ffn(layer, cfg, hb, valid=vb)
            )(h, valid)
        else:
            x = x + _ffn(layer, cfg, h, valid=valid)
    last = jnp.maximum(true_lens - 1, 0)
    xl = x[jnp.arange(Bp), last]  # [Bp, d]
    logits = _logits(params, cfg, xl)
    return logits, kv_cache


def prefill_packed(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    kv_cache: Tuple[jax.Array, jax.Array],
    token_ids: jax.Array,      # [T] int32 packed stream (tail padded)
    positions: jax.Array,      # [T] int32 absolute position per token
    seg_ids: jax.Array,        # [T] int32 segment row per token
    block_tables: jax.Array,   # [S, mb] int32 per-segment block tables
    last_idx: jax.Array,       # [S] int32 packed index of each segment's
    #                            last token this chunk (0 for unused rows)
    valid: jax.Array,          # [T] bool: False on the padded tail
    lora_bank=None,            # stacked adapter bank (lora/bank.py)
    adapter_idx=None,          # [T] int32: bank slot PER TOKEN
    mesh=None,                 # required for the Pallas path under tp>1
):
    """Packed multi-sequence prefill: several prompts' chunks (or
    prefix-hit tails) run as ONE padding-free token stream with segment
    ids (ops/packed_prefill.py) — the MFU path that replaces the padded
    per-row batched program.  Semantically identical to running `prefill`
    per sequence: K/V scatter into each token's own blocks, attention is
    causal-within-segment over each segment's paged context.

    NOTE: capacity-dispatch MoE is NOT packed-safe (segments would share
    one expert-capacity pool and capacity-drop each other's tokens); the
    engine routes those configs to the per-row batched program instead.

    Returns (logits [S, vocab] at each segment's last packed token,
    updated kv_cache)."""
    x, kv_cache = _packed_forward(
        params, cfg, kv_cache, token_ids, positions, seg_ids,
        block_tables, valid, lora_bank, adapter_idx, mesh=mesh,
    )
    xl = x[last_idx]  # [S, d]
    logits = _logits(params, cfg, xl)
    return logits, kv_cache


def _packed_forward(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    kv_cache: Tuple[jax.Array, jax.Array],
    token_ids: jax.Array,      # [T] int32 packed stream (tail padded)
    positions: jax.Array,      # [T] int32 absolute position per token
    seg_ids: jax.Array,        # [T] int32 segment row per token
    block_tables: jax.Array,   # [S, mb] int32 per-segment block tables
    valid: jax.Array,          # [T] bool: False on the padded tail
    lora_bank=None,
    adapter_idx=None,
    mesh=None,                 # required for the Pallas path under tp>1
):
    """Shared packed-stream transformer body (prefill_packed and
    spec_verify_packed): K/V scatter into each token's own blocks, then
    causal-within-segment attention over each segment's paged context.
    Returns (final hidden states [T, d], updated kv_cache)."""
    T = token_ids.shape[0]
    x = params["embedding"][token_ids].astype(cfg.dtype)  # [T, d]
    for li, layer in enumerate(params["layers"]):
        lctx = _lora_ctx(lora_bank, adapter_idx, li)
        h = rms_norm(x, layer["attn_norm"]["norm"], cfg.rms_eps)
        q, k, v = _qkv(layer, cfg, h, positions, lora=lctx)  # [T, nh, hd]
        kv_cache = _write_kv(write_packed_kv, kv_cache, li, k, v,
                             block_tables, seg_ids, positions, valid)
        k_cache, v_cache, ks, vs = _unpack_kv(kv_cache)
        attn = packed_prefill_attention(
            q, k_cache, v_cache, li, block_tables, seg_ids, positions,
            valid, impl=cfg.packed_attn_impl, k_scale=ks, v_scale=vs,
            mesh=mesh,
        )
        x = x + _attn_out(layer, attn.reshape(T, cfg.q_dim), lora=lctx)
        h = rms_norm(x, layer["mlp_norm"]["norm"], cfg.rms_eps)
        x = x + _ffn(layer, cfg, h, valid=valid)
    return x, kv_cache


def spec_verify_packed(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    kv_cache: Tuple[jax.Array, jax.Array],
    token_ids: jax.Array,      # [T] int32 packed verify stream
    positions: jax.Array,      # [T] int32 absolute position per token
    seg_ids: jax.Array,        # [T] int32 segment row per token
    block_tables: jax.Array,   # [S, mb] int32 per-segment block tables
    valid: jax.Array,          # [T] bool: False on the padded tail
    mesh=None,                 # required for the Pallas path under tp>1
):
    """Speculative-decoding verification (spec/): each speculating
    sequence's row [last_token, d1..dk] runs through the SAME packed
    segment-id path as chunked prefill — K/V for every draft position is
    written in place (accepted prefixes keep theirs; rejected tails are
    overwritten when the sequence actually reaches those positions) —
    but logits come back for EVERY packed position, since verification
    needs the target's next-token distribution after each draft prefix.
    Returns (logits [T, vocab], updated kv_cache)."""
    x, kv_cache = _packed_forward(
        params, cfg, kv_cache, token_ids, positions, seg_ids,
        block_tables, valid, mesh=mesh,
    )
    return _logits(params, cfg, x), kv_cache


def embed_text(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    token_ids: jax.Array,   # [T_pad] int32
    true_len: jax.Array,    # scalar int32: valid tokens
) -> jax.Array:
    """Pooled text embedding: dense causal forward (no paging), final
    norm, mean-pool over valid positions, L2-normalize.  Serves the
    /v1/embeddings route (ref: the reference's embeddings route family,
    lib/llm/src/http/service/openai.rs) — any generative checkpoint
    doubles as a pooled embedder, vLLM's `embed` task semantics."""
    T = token_ids.shape[0]
    positions = jnp.arange(T)
    valid = positions < true_len
    x = params["embedding"][token_ids].astype(cfg.dtype)
    for layer in params["layers"]:
        h = rms_norm(x, layer["attn_norm"]["norm"], cfg.rms_eps)
        q, k, v = _qkv(layer, cfg, h, positions)
        group = cfg.n_heads // cfg.n_kv_heads
        kr = jnp.repeat(k, group, axis=1)
        vr = jnp.repeat(v, group, axis=1)
        s = jnp.einsum("ihd,jhd->hij", q.astype(jnp.float32),
                       kr.astype(jnp.float32)) / jnp.sqrt(
            jnp.float32(cfg.head_dim))
        causal = jnp.tril(jnp.ones((T, T), bool)) & valid[None, :]
        s = jnp.where(causal[None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("hij,jhd->ihd", p, vr.astype(jnp.float32))
        x = x + o.reshape(T, cfg.q_dim).astype(cfg.dtype) @ layer["wo"]
        h = rms_norm(x, layer["mlp_norm"]["norm"], cfg.rms_eps)
        x = x + _ffn(layer, cfg, h, valid=valid)
    x = rms_norm(x, params["final_norm"]["norm"], cfg.rms_eps)
    w = valid.astype(jnp.float32)[:, None]
    pooled = (x.astype(jnp.float32) * w).sum(0) / jnp.maximum(w.sum(), 1.0)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled), 1e-9)


# ---------------------------------------------------------------------------
# decode: one token per active slot, batched
# ---------------------------------------------------------------------------


def decode(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    kv_cache: Tuple[jax.Array, jax.Array],
    token_ids: jax.Array,      # [B] int32, last sampled token per slot
    positions: jax.Array,      # [B] int32
    block_tables: jax.Array,   # [B, max_blocks] int32
    ctx_lens: jax.Array,       # [B] int32, tokens in cache BEFORE this step
    valid: Optional[jax.Array] = None,  # [B] bool: active (non-padding) slots
    mesh=None,                 # required for the Pallas path under tp>1
    lora_bank=None,            # stacked adapter bank (lora/bank.py)
    adapter_idx=None,          # [B] int32: bank slot per slot
):
    """One decode step for B slots.  Writes each token's K/V, attends over
    the paged context, returns (logits [B, vocab], updated kv_cache)."""
    x, kv_cache = _decode_trunk(params, cfg, kv_cache, token_ids,
                                positions, block_tables, ctx_lens,
                                valid=valid, mesh=mesh,
                                lora_bank=lora_bank,
                                adapter_idx=adapter_idx)
    logits = _logits(params, cfg, x)  # [B, vocab]
    return logits, kv_cache


def _decode_trunk(params, cfg, kv_cache, token_ids, positions,
                  block_tables, ctx_lens, valid=None, mesh=None,
                  lora_bank=None, adapter_idx=None):
    """The decode layer stack shared by decode (-> _logits) and
    decode_hidden (-> final norm only, for the fused sampling epilogue).
    Returns (pre-final-norm hidden [B, d], updated kv_cache)."""
    x = params["embedding"][token_ids].astype(cfg.dtype)  # [B, d]
    pos1 = positions[:, None]  # [B, 1] for rope
    for li, layer in enumerate(params["layers"]):
        lctx = _lora_ctx(lora_bank, adapter_idx, li)
        h = rms_norm(x, layer["attn_norm"]["norm"], cfg.rms_eps)
        q, k, v = _qkv(layer, cfg, h[:, None, :], pos1, lora=lctx)
        kv_cache = _write_kv(write_token_kv, kv_cache, li, k[:, 0],
                             v[:, 0], block_tables, ctx_lens)
        k_cache, v_cache, ks, vs = _unpack_kv(kv_cache)
        attn = paged_attention_decode(
            q[:, 0], k_cache, v_cache, li, block_tables, ctx_lens + 1,
            impl=cfg.attn_impl, mesh=mesh, k_scale=ks, v_scale=vs,
        )  # [B, nh, hd]
        x = x + _attn_out(layer, attn.reshape(x.shape[0], cfg.q_dim),
                          lora=lctx)
        h = rms_norm(x, layer["mlp_norm"]["norm"], cfg.rms_eps)
        x = x + _ffn(layer, cfg, h, valid=valid)
    return x, kv_cache


def decode_hidden(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    kv_cache: Tuple[jax.Array, jax.Array],
    token_ids: jax.Array,      # [B] int32
    positions: jax.Array,      # [B] int32
    block_tables: jax.Array,   # [B, max_blocks] int32
    ctx_lens: jax.Array,       # [B] int32
    valid: Optional[jax.Array] = None,
    mesh=None,
    lora_bank=None,
    adapter_idx=None,
):
    """decode minus the final projection: returns (final-norm hidden
    [B, d] in cfg.dtype, updated kv_cache).  The fused sampling
    epilogue (ops/fused_sampling.py) contracts the hidden against
    unembed_weight tile-by-tile, so [B, vocab] logits never
    materialize in HBM; `_logits` is exactly
    `(this_hidden @ unembed_weight).astype(fp32)`, which is what the
    epilogue's byte-identity contract rides on."""
    x, kv_cache = _decode_trunk(params, cfg, kv_cache, token_ids,
                                positions, block_tables, ctx_lens,
                                valid=valid, mesh=mesh,
                                lora_bank=lora_bank,
                                adapter_idx=adapter_idx)
    return rms_norm(x, params["final_norm"]["norm"], cfg.rms_eps), kv_cache


def unembed_weight(params, cfg: LlamaConfig) -> jax.Array:
    """[d, vocab] final-projection matrix — the operand _logits
    contracts the final-norm hidden with (embedding.T when tied)."""
    if cfg.tie_embeddings:
        return params["embedding"].T
    return params["lm_head"]


def decode_multi(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    kv_cache: Tuple[jax.Array, jax.Array],
    token_ids: jax.Array,      # [B] int32
    positions: jax.Array,      # [B] int32
    block_tables: jax.Array,   # [B, max_blocks] int32
    ctx_lens: jax.Array,       # [B] int32
    num_steps: int,
    sample_fn=None,            # (logits [B,V], step_idx) -> tokens [B]
    valid: Optional[jax.Array] = None,  # [B] bool: active slots
    mesh=None,                 # required for the Pallas path under tp>1
    lora_bank=None,            # stacked adapter bank (lora/bank.py)
    adapter_idx=None,          # [B] int32: bank slot per slot
):
    """`num_steps` fused decode steps in ONE compiled program (lax.scan).

    The serving hot loop's dominant off-roofline cost on this platform is
    per-dispatch overhead (each jit call round-trips the host); fusing k
    steps amortizes it k-fold — the on-device generate loop every
    production TPU serving stack runs.  Sampled ids chain on device; block
    tables are fixed across the burst, so callers must pre-allocate blocks
    covering positions [ctx, ctx + num_steps).

    Returns (tokens [num_steps, B], updated kv_cache)."""
    if sample_fn is None:
        def sample_fn(logits, _):
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def body(carry, step_idx):
        tokens, kv, pos, cls = carry
        logits, kv = decode(params, cfg, kv, tokens, pos, block_tables, cls,
                            valid=valid, mesh=mesh, lora_bank=lora_bank,
                            adapter_idx=adapter_idx)
        nt = sample_fn(logits, step_idx).astype(jnp.int32)
        return (nt, kv, pos + 1, cls + 1), nt

    (_, kv_cache, _, _), toks = jax.lax.scan(
        body, (token_ids, kv_cache, positions, ctx_lens),
        jnp.arange(num_steps), length=num_steps,
    )
    return toks, kv_cache


def decode_multi_hidden(
    params: Dict[str, Any],
    cfg: LlamaConfig,
    kv_cache: Tuple[jax.Array, jax.Array],
    token_ids: jax.Array,      # [B] int32
    positions: jax.Array,      # [B] int32
    block_tables: jax.Array,   # [B, max_blocks] int32
    ctx_lens: jax.Array,       # [B] int32
    num_steps: int,
    sample_fn,                 # (hidden [B,d], step_idx) -> tokens [B]
    valid: Optional[jax.Array] = None,
    mesh=None,
    lora_bank=None,
    adapter_idx=None,
):
    """decode_multi with the fused sampling epilogue: the scan body
    hands `sample_fn` the final-norm HIDDEN state instead of logits, so
    no [B, vocab] tensor exists anywhere in the fused burst — the
    epilogue reduces each step's projection tile-by-tile
    (ops/fused_sampling.py).  Same chaining/position bookkeeping as
    decode_multi; callers pre-allocate blocks for [ctx, ctx+num_steps).

    Returns (tokens [num_steps, B], updated kv_cache)."""

    def body(carry, step_idx):
        tokens, kv, pos, cls = carry
        h, kv = decode_hidden(params, cfg, kv, tokens, pos, block_tables,
                              cls, valid=valid, mesh=mesh,
                              lora_bank=lora_bank,
                              adapter_idx=adapter_idx)
        nt = sample_fn(h, step_idx).astype(jnp.int32)
        return (nt, kv, pos + 1, cls + 1), nt

    (_, kv_cache, _, _), toks = jax.lax.scan(
        body, (token_ids, kv_cache, positions, ctx_lens),
        jnp.arange(num_steps), length=num_steps,
    )
    return toks, kv_cache
