"""HF checkpoint loading: safetensors -> sharded params pytree.

The TPU counterpart of the reference's model-resolution path — every
reference backend starts by fetching and loading real weights
(components/src/dynamo/vllm/main.py:114 fetch_model,
lib/llm/src/local_model/, hub/huggingface.rs).  Here a local HF model
directory (config.json + *.safetensors) is mapped onto the llama.py params
pytree and placed shard-by-shard with jax.device_put per
param_sharding_rules(), so a 70B checkpoint never needs to fit on one
chip's HBM as a whole: each weight goes host -> its tp shards directly.

Name mapping (HF Llama/Qwen3 -> ours; HF nn.Linear stores [out, in], our
matmuls are x @ W so projections transpose):

    model.embed_tokens.weight              embedding        [vocab, d]
    lm_head.weight                         lm_head          [d, vocab] (T)
    model.norm.weight                      final_norm.norm
    ...layers.N.self_attn.q_proj.weight    layers[N].wq     (T)
    ...layers.N.self_attn.{k,v}_proj       layers[N].wk/wv  (T)
    ...layers.N.self_attn.o_proj           layers[N].wo     (T)
    ...layers.N.self_attn.{q,k}_norm       layers[N].q_norm/k_norm (Qwen3)
    ...layers.N.input_layernorm            layers[N].attn_norm.norm
    ...layers.N.post_attention_layernorm   layers[N].mlp_norm.norm
    ...layers.N.mlp.{gate,up,down}_proj    layers[N].w_gate/w_up/w_down (T)
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import param_sharding_rules
from .llama import LlamaConfig

_ARCHS = {
    "LlamaForCausalLM": {},
    "MistralForCausalLM": {},
    "MixtralForCausalLM": {},  # MoE fields read from config.json below
    "Qwen2ForCausalLM": {},
    "Qwen3ForCausalLM": {"qk_norm": True},
}


def load_hf_config(model_path: str, dtype=jnp.bfloat16) -> LlamaConfig:
    """config.json -> LlamaConfig (dense Llama-family architectures)."""
    with open(os.path.join(model_path, "config.json")) as f:
        hf = json.load(f)
    arch = (hf.get("architectures") or ["LlamaForCausalLM"])[0]
    if arch not in _ARCHS:
        raise ValueError(
            f"unsupported architecture {arch!r}; have {sorted(_ARCHS)}"
        )
    n_heads = hf["num_attention_heads"]
    head_dim = hf.get("head_dim") or hf["hidden_size"] // n_heads
    eos = hf.get("eos_token_id", 2)
    eos_ids = tuple(int(e) for e in eos) if isinstance(eos, list) else (
        (int(eos),) if eos is not None else ()
    )
    return LlamaConfig(
        name=os.path.basename(os.path.abspath(model_path)) or hf.get(
            "model_type", "hf-model"),
        vocab_size=hf["vocab_size"],
        d_model=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=n_heads,
        n_kv_heads=hf.get("num_key_value_heads", n_heads),
        head_dim=head_dim,
        ffn_dim=hf["intermediate_size"],
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rms_eps=float(hf.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
        max_context=int(hf.get("max_position_embeddings", 8192)),
        dtype=dtype,
        eos_token_ids=eos_ids or (2,),
        n_experts=int(hf.get("num_local_experts", 0)),
        experts_per_token=int(hf.get("num_experts_per_tok", 2)),
        **_ARCHS[arch],
    )


def load_chat_template(model_path: str) -> Optional[str]:
    """The checkpoint's chat template (tokenizer_config.json or the
    standalone chat_template.jinja), if any."""
    jinja = os.path.join(model_path, "chat_template.jinja")
    if os.path.exists(jinja):
        with open(jinja) as f:
            return f.read()
    tc = os.path.join(model_path, "tokenizer_config.json")
    try:
        with open(tc) as f:
            tmpl = json.load(f).get("chat_template")
        return tmpl if isinstance(tmpl, str) else None
    except (OSError, json.JSONDecodeError):
        return None


_LAYER_RE = re.compile(r"^model\.layers\.(\d+)\.(.+)$")

# HF suffix -> (our key, transpose?)
_LAYER_MAP = {
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "self_attn.q_norm.weight": ("q_norm", False),
    "self_attn.k_norm.weight": ("k_norm", False),
    "input_layernorm.weight": ("attn_norm", False),
    "post_attention_layernorm.weight": ("mlp_norm", False),
    "mlp.gate_proj.weight": ("w_gate", True),
    "mlp.up_proj.weight": ("w_up", True),
    "mlp.down_proj.weight": ("w_down", True),
}

_NORM_KEYS = {"attn_norm", "mlp_norm", "q_norm", "k_norm"}

# Mixtral MoE layer tensors.  HF keeps one tensor per expert
# (...block_sparse_moe.experts.E.w{1,2,3}.weight); our pytree stacks them
# [n_experts, ...] so EP shards one array over the tp axis.  w1=gate,
# w3=up, w2=down (all HF Linear [out, in], transposed like the dense maps).
_MOE_GATE = "block_sparse_moe.gate.weight"
_MOE_EXPERT_RE = re.compile(
    r"^block_sparse_moe\.experts\.(\d+)\.(w1|w2|w3)\.weight$"
)
_MOE_W_MAP = {"w1": "moe_w_gate", "w3": "moe_w_up", "w2": "moe_w_down"}


def _iter_safetensors(model_path: str):
    from safetensors import safe_open

    files = sorted(
        f for f in os.listdir(model_path) if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no *.safetensors under {model_path}")
    for fname in files:
        with safe_open(os.path.join(model_path, fname), framework="np") as f:
            for name in f.keys():
                yield name, f.get_tensor(name)


def load_params(
    model_path: str,
    cfg: Optional[LlamaConfig] = None,
    mesh=None,
) -> Dict[str, Any]:
    """Load a HF checkpoint into the llama.py params pytree.

    With a mesh, each tensor is device_put directly to its NamedSharding
    (per-weight streaming: host RAM holds one tensor at a time beyond the
    checkpoint mmap).  Without, arrays stay as committed jax arrays on the
    default device.
    """
    from jax.sharding import NamedSharding

    cfg = cfg or load_hf_config(model_path)
    rules = param_sharding_rules()

    def put(name_key: str, arr: np.ndarray):
        arr = jnp.asarray(arr)
        if mesh is not None:
            return jax.device_put(
                arr, NamedSharding(mesh, rules.get(name_key, jax.sharding.PartitionSpec()))
            )
        return arr

    norm_dt = jnp.float32
    params: Dict[str, Any] = {
        "layers": [dict() for _ in range(cfg.n_layers)]
    }
    # per-layer expert tensors stream into ONE preallocated stacked array
    # (host RAM peak = one [E, ...] array per in-flight weight kind, not
    # E separate copies + a stack)
    moe_stage: Dict[int, Dict[str, Any]] = {}  # li -> w -> (buf, seen_set)
    for name, tensor in _iter_safetensors(model_path):
        m = _LAYER_RE.match(name)
        if m:
            li, suffix = int(m.group(1)), m.group(2)
            em = _MOE_EXPERT_RE.match(suffix)
            if em:
                e, w = int(em.group(1)), _MOE_W_MAP[em.group(2)]
                t = tensor.T
                stage = moe_stage.setdefault(li, {})
                if w not in stage:
                    stage[w] = (
                        np.empty((cfg.n_experts,) + t.shape, cfg.dtype),
                        set(),
                    )
                buf, got = stage[w]
                buf[e] = t
                got.add(e)
                if len(got) == cfg.n_experts:
                    params["layers"][li][w] = put(w, buf)
                    del stage[w]
                continue
            if suffix == _MOE_GATE:
                params["layers"][li]["moe_gate"] = put(
                    "moe_gate",
                    np.ascontiguousarray(tensor.T).astype(cfg.dtype),
                )
                continue
            if suffix not in _LAYER_MAP:
                raise ValueError(f"unmapped layer tensor {name!r}")
            key, transpose = _LAYER_MAP[suffix]
            t = tensor.T if transpose else tensor
            if key in _NORM_KEYS:
                params["layers"][li][key] = {
                    "norm": jnp.asarray(t).astype(norm_dt)
                }
            else:
                params["layers"][li][key] = put(
                    key, np.ascontiguousarray(t).astype(cfg.dtype)
                )
        elif name == "model.embed_tokens.weight":
            params["embedding"] = put(
                "embedding", tensor.astype(cfg.dtype))
        elif name == "lm_head.weight":
            params["lm_head"] = put(
                "lm_head", np.ascontiguousarray(tensor.T).astype(cfg.dtype))
        elif name == "model.norm.weight":
            params["final_norm"] = {
                "norm": jnp.asarray(tensor).astype(norm_dt)
            }
        else:
            raise ValueError(f"unmapped tensor {name!r}")

    if cfg.tie_embeddings:
        params.pop("lm_head", None)
    elif "lm_head" not in params:
        # some tied checkpoints omit lm_head but don't set the flag
        params["lm_head"] = put(
            "lm_head",
            np.ascontiguousarray(
                np.asarray(params["embedding"]).T).astype(cfg.dtype),
        )

    missing = []
    if "embedding" not in params:
        missing.append("model.embed_tokens.weight")
    if "final_norm" not in params:
        missing.append("model.norm.weight")
    want = set(_LAYER_MAP)
    if not cfg.qk_norm:
        want -= {"self_attn.q_norm.weight", "self_attn.k_norm.weight"}
    if cfg.n_experts > 0:
        # routed MLP replaces the dense one: gate tensor + 3 stacked
        # expert arrays instead of the 3 dense projections
        want -= {"mlp.gate_proj.weight", "mlp.up_proj.weight",
                 "mlp.down_proj.weight"}
        want |= {"moe_gate", "moe_w_gate", "moe_w_up", "moe_w_down"}
    missing.extend(
        f"model.layers.{li} expert tensors {sorted(parts)}"
        for li, parts in moe_stage.items() if parts
    )
    for li, layer in enumerate(params["layers"]):
        got = len(layer)
        if got != len(want):
            missing.append(f"model.layers.{li} ({got}/{len(want)} tensors)")
    if missing:
        raise ValueError(f"incomplete checkpoint {model_path}: missing "
                         f"{missing[:5]}")
    return params
