"""HF checkpoint loading: safetensors -> sharded params pytree.

The TPU counterpart of the reference's model-resolution path — every
reference backend starts by fetching and loading real weights
(components/src/dynamo/vllm/main.py:114 fetch_model,
lib/llm/src/local_model/, hub/huggingface.rs).  Here a local HF model
directory (config.json + *.safetensors) is mapped onto the llama.py params
pytree and placed shard-by-shard with jax.device_put per
param_sharding_rules(), so a 70B checkpoint never needs to fit on one
chip's HBM as a whole: each weight goes host -> its tp shards directly.

Name mapping (HF Llama/Qwen3 -> ours; HF nn.Linear stores [out, in], our
matmuls are x @ W so projections transpose):

    model.embed_tokens.weight              embedding        [vocab, d]
    lm_head.weight                         lm_head          [d, vocab] (T)
    model.norm.weight                      final_norm.norm
    ...layers.N.self_attn.q_proj.weight    layers[N].wq     (T)
    ...layers.N.self_attn.{k,v}_proj       layers[N].wk/wv  (T)
    ...layers.N.self_attn.o_proj           layers[N].wo     (T)
    ...layers.N.self_attn.{q,k}_norm       layers[N].q_norm/k_norm (Qwen3)
    ...layers.N.input_layernorm            layers[N].attn_norm.norm
    ...layers.N.post_attention_layernorm   layers[N].mlp_norm.norm
    ...layers.N.mlp.{gate,up,down}_proj    layers[N].w_gate/w_up/w_down (T)
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import param_sharding_rules
from .deepseek import DeepseekConfig
from .llama import LlamaConfig

_ARCHS = {
    "LlamaForCausalLM": {},
    "MistralForCausalLM": {},
    "MixtralForCausalLM": {},  # MoE fields read from config.json below
    "Qwen2ForCausalLM": {},
    "Qwen3ForCausalLM": {"qk_norm": True},
}

# MLA family (models/deepseek.py).  V3 routing is sigmoid+bias; V2
# declares scoring_func in its config.
_DS_ARCHS = {"DeepseekV2ForCausalLM": "v2", "DeepseekV3ForCausalLM": "v3"}


def _load_deepseek_config(hf: dict, lineage: str, name: str,
                          dtype) -> DeepseekConfig:
    eos = hf.get("eos_token_id", 2)
    eos_ids = tuple(int(e) for e in eos) if isinstance(eos, list) else (
        (int(eos),) if eos is not None else (2,))
    scoring = ("sigmoid" if lineage == "v3"
               else hf.get("scoring_func", "softmax"))
    return DeepseekConfig(
        name=name,
        vocab_size=hf["vocab_size"],
        d_model=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=hf["num_attention_heads"],
        q_lora_rank=int(hf.get("q_lora_rank") or 0),
        kv_lora_rank=int(hf["kv_lora_rank"]),
        qk_nope_head_dim=int(hf["qk_nope_head_dim"]),
        qk_rope_head_dim=int(hf["qk_rope_head_dim"]),
        v_head_dim=int(hf["v_head_dim"]),
        ffn_dim=hf["intermediate_size"],
        moe_ffn_dim=int(hf.get("moe_intermediate_size") or 0),
        n_experts=int(hf.get("n_routed_experts") or 0),
        experts_per_token=int(hf.get("num_experts_per_tok") or 2),
        n_shared_experts=int(hf.get("n_shared_experts") or 0),
        first_k_dense=int(hf.get("first_k_dense_replace") or 0),
        routed_scaling_factor=float(hf.get("routed_scaling_factor", 1.0)),
        moe_scoring=scoring,
        norm_topk_prob=bool(hf.get("norm_topk_prob", lineage == "v3")),
        n_group=int(hf.get("n_group") or 1),
        topk_group=int(hf.get("topk_group") or 1),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rms_eps=float(hf.get("rms_norm_eps", 1e-6)),
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
        max_context=int(hf.get("max_position_embeddings", 8192)),
        dtype=dtype,
        eos_token_ids=eos_ids,
    )


def load_hf_config(model_path: str, dtype=jnp.bfloat16):
    """config.json -> LlamaConfig / DeepseekConfig by architecture."""
    with open(os.path.join(model_path, "config.json")) as f:
        hf = json.load(f)
    arch = (hf.get("architectures") or ["LlamaForCausalLM"])[0]
    if arch in _DS_ARCHS:
        name = os.path.basename(os.path.abspath(model_path)) \
            or hf.get("model_type", "hf-model")
        return _load_deepseek_config(hf, _DS_ARCHS[arch], name, dtype)
    if arch not in _ARCHS:
        raise ValueError(
            f"unsupported architecture {arch!r}; have "
            f"{sorted(_ARCHS) + sorted(_DS_ARCHS)}"
        )
    n_heads = hf["num_attention_heads"]
    head_dim = hf.get("head_dim") or hf["hidden_size"] // n_heads
    eos = hf.get("eos_token_id", 2)
    eos_ids = tuple(int(e) for e in eos) if isinstance(eos, list) else (
        (int(eos),) if eos is not None else ()
    )
    return LlamaConfig(
        name=os.path.basename(os.path.abspath(model_path)) or hf.get(
            "model_type", "hf-model"),
        vocab_size=hf["vocab_size"],
        d_model=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=n_heads,
        n_kv_heads=hf.get("num_key_value_heads", n_heads),
        head_dim=head_dim,
        ffn_dim=hf["intermediate_size"],
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rms_eps=float(hf.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
        max_context=int(hf.get("max_position_embeddings", 8192)),
        dtype=dtype,
        eos_token_ids=eos_ids or (2,),
        n_experts=int(hf.get("num_local_experts", 0)),
        experts_per_token=int(hf.get("num_experts_per_tok", 2)),
        **_ARCHS[arch],
    )


def load_chat_template(model_path: str) -> Optional[str]:
    """The checkpoint's chat template (tokenizer_config.json or the
    standalone chat_template.jinja), if any."""
    jinja = os.path.join(model_path, "chat_template.jinja")
    if os.path.exists(jinja):
        with open(jinja) as f:
            return f.read()
    tc = os.path.join(model_path, "tokenizer_config.json")
    try:
        with open(tc) as f:
            tmpl = json.load(f).get("chat_template")
        return tmpl if isinstance(tmpl, str) else None
    except (OSError, json.JSONDecodeError):
        return None


_LAYER_RE = re.compile(r"^model\.layers\.(\d+)\.(.+)$")

# HF suffix -> (our key, transpose?)
_LAYER_MAP = {
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "self_attn.q_norm.weight": ("q_norm", False),
    "self_attn.k_norm.weight": ("k_norm", False),
    "input_layernorm.weight": ("attn_norm", False),
    "post_attention_layernorm.weight": ("mlp_norm", False),
    "mlp.gate_proj.weight": ("w_gate", True),
    "mlp.up_proj.weight": ("w_up", True),
    "mlp.down_proj.weight": ("w_down", True),
}

_NORM_KEYS = {"attn_norm", "mlp_norm", "q_norm", "k_norm"}

# Mixtral MoE layer tensors.  HF keeps one tensor per expert
# (...block_sparse_moe.experts.E.w{1,2,3}.weight); our pytree stacks them
# [n_experts, ...] so EP shards one array over the tp axis.  w1=gate,
# w3=up, w2=down (all HF Linear [out, in], transposed like the dense maps).
_MOE_GATE = "block_sparse_moe.gate.weight"
_MOE_EXPERT_RE = re.compile(
    r"^block_sparse_moe\.experts\.(\d+)\.(w1|w2|w3)\.weight$"
)
_MOE_W_MAP = {"w1": "moe_w_gate", "w3": "moe_w_up", "w2": "moe_w_down"}


def _iter_safetensors(model_path: str):
    from safetensors import safe_open

    files = sorted(
        f for f in os.listdir(model_path) if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no *.safetensors under {model_path}")
    for fname in files:
        with safe_open(os.path.join(model_path, fname), framework="np") as f:
            for name in f.keys():
                yield name, f.get_tensor(name)


class _ExpertStage:
    """Streams per-expert tensors into ONE preallocated stacked [E, ...]
    array per (layer, kind), flushing to `sink(li, key, buf)` when all
    experts arrived (host RAM peak = one stacked array per in-flight
    weight kind, not E copies + a stack).  Shared by the Mixtral and
    DeepSeek loader paths."""

    def __init__(self, n_experts: int, dtype, sink):
        self.n_experts = n_experts
        self.dtype = dtype
        self.sink = sink
        self._stage: Dict[int, Dict[str, Any]] = {}

    def feed(self, li: int, e: int, key: str, t: np.ndarray) -> None:
        stage = self._stage.setdefault(li, {})
        if key not in stage:
            stage[key] = (np.empty((self.n_experts,) + t.shape, self.dtype),
                          set())
        buf, got = stage[key]
        buf[e] = t
        got.add(e)
        if len(got) == self.n_experts:
            self.sink(li, key, buf)
            del stage[key]

    def pending(self):
        """(layer, unfinished keys) pairs for completeness reporting."""
        return [(li, sorted(parts)) for li, parts in self._stage.items()
                if parts]


def _deinterleave_rope_rows(w: np.ndarray, rope_dim: int) -> np.ndarray:
    """HF DeepSeek checkpoints store rope output rows INTERLEAVED
    (modeling's apply_rotary_pos_emb_interleave de-interleaves each head
    dim at runtime via view(d//2, 2).transpose).  Permuting the weight
    rows once at load time lets our half-split rope (llama.py) apply
    directly.  `w` is the rope-row block [rope_dim, ...]."""
    idx = np.concatenate([np.arange(0, rope_dim, 2),
                          np.arange(1, rope_dim, 2)])
    return w[idx]


def _load_deepseek_params(model_path: str, cfg: DeepseekConfig,
                          put) -> Dict[str, Any]:
    """DeepSeek V2/V3 checkpoint -> deepseek.py params pytree.

    Name mapping (HF Linear is [out, in]; our matmuls transpose):

        self_attn.q_proj | q_a_proj/q_a_layernorm/q_b_proj   wq | wq_a/...
        self_attn.kv_a_proj_with_mqa      wkv_a  (rope rows de-interleaved)
        self_attn.kv_a_layernorm          kv_a_norm
        self_attn.kv_b_proj               w_uk [nh,R,dn] + w_uv [nh,R,dv]
        self_attn.o_proj                  wo
        mlp.gate.weight / e_score_correction_bias   moe_gate / moe_gate_bias
        mlp.experts.E.{gate,up,down}_proj           moe_w_* (stacked [E,..])
        mlp.shared_experts.{gate,up,down}_proj      shared.w_*
    """
    with open(os.path.join(model_path, "config.json")) as f:
        interleaved = bool(json.load(f).get("rope_interleave", True))
    R, dr = cfg.kv_lora_rank, cfg.qk_rope_head_dim
    dn, dv = cfg.qk_nope_head_dim, cfg.v_head_dim
    nh = cfg.n_heads
    norm_dt = jnp.float32

    def perm_q(t: np.ndarray) -> np.ndarray:
        """q/q_b rows are [nh * (dn+dr), in]; de-interleave each head's
        rope block."""
        if not interleaved:
            return t
        t = t.reshape(nh, dn + dr, -1)
        rope_rows = _deinterleave_rope_rows(
            np.ascontiguousarray(t[:, dn:].swapaxes(0, 1)), dr)
        t = np.concatenate([t[:, :dn], rope_rows.swapaxes(0, 1)], axis=1)
        return t.reshape(nh * (dn + dr), -1)

    params: Dict[str, Any] = {
        "layers": [dict() for _ in range(cfg.n_layers)]
    }
    stage = _ExpertStage(
        cfg.n_experts, cfg.dtype,
        lambda li, key, buf: params["layers"][li].__setitem__(
            key, put(key, buf)))

    expert_re = re.compile(
        r"^mlp\.experts\.(\d+)\.(gate_proj|up_proj|down_proj)\.weight$")
    shared_re = re.compile(
        r"^mlp\.shared_experts\.(gate_proj|up_proj|down_proj)\.weight$")
    w_map = {"gate_proj": "w_gate", "up_proj": "w_up", "down_proj": "w_down"}

    for name, tensor in _iter_safetensors(model_path):
        m = _LAYER_RE.match(name)
        if m:
            li, suffix = int(m.group(1)), m.group(2)
            if li >= cfg.n_layers:
                # V3/R1 checkpoints carry the multi-token-prediction (MTP)
                # module as layer num_hidden_layers — not part of the
                # serving model; skip it
                continue
            layer = params["layers"][li]
            em = expert_re.match(suffix)
            if em:
                stage.feed(li, int(em.group(1)),
                           "moe_" + w_map[em.group(2)],
                           tensor.T.astype(cfg.dtype))
                continue
            sm = shared_re.match(suffix)
            if sm:
                layer.setdefault("shared", {})[w_map[sm.group(1)]] = put(
                    w_map[sm.group(1)],
                    np.ascontiguousarray(tensor.T).astype(cfg.dtype))
                continue
            if suffix == "mlp.gate.weight":
                layer["moe_gate"] = put("moe_gate", np.ascontiguousarray(
                    tensor.T).astype(cfg.dtype))
            elif suffix == "mlp.gate.e_score_correction_bias":
                layer["moe_gate_bias"] = jnp.asarray(tensor, jnp.float32)
            elif suffix == "self_attn.q_proj.weight":
                layer["wq"] = put("wq", np.ascontiguousarray(
                    perm_q(tensor).T).astype(cfg.dtype))
            elif suffix == "self_attn.q_a_proj.weight":
                layer["wq_a"] = put("wq_a", np.ascontiguousarray(
                    tensor.T).astype(cfg.dtype))
            elif suffix == "self_attn.q_a_layernorm.weight":
                layer["q_a_norm"] = {"norm": jnp.asarray(tensor, norm_dt)}
            elif suffix == "self_attn.q_b_proj.weight":
                layer["wq_b"] = put("wq_b", np.ascontiguousarray(
                    perm_q(tensor).T).astype(cfg.dtype))
            elif suffix == "self_attn.kv_a_proj_with_mqa.weight":
                t = tensor
                if interleaved:
                    t = np.concatenate(
                        [t[:R], _deinterleave_rope_rows(t[R:], dr)], axis=0)
                layer["wkv_a"] = put("wkv_a", np.ascontiguousarray(
                    t.T).astype(cfg.dtype))
            elif suffix == "self_attn.kv_a_layernorm.weight":
                layer["kv_a_norm"] = {"norm": jnp.asarray(tensor, norm_dt)}
            elif suffix == "self_attn.kv_b_proj.weight":
                # [nh*(dn+dv), R] -> per-head up-projections [nh, R, *]
                t = tensor.reshape(nh, dn + dv, R)
                layer["w_uk"] = put("w_uk", np.ascontiguousarray(
                    t[:, :dn].swapaxes(1, 2)).astype(cfg.dtype))
                layer["w_uv"] = put("w_uv", np.ascontiguousarray(
                    t[:, dn:].swapaxes(1, 2)).astype(cfg.dtype))
            elif suffix == "self_attn.o_proj.weight":
                layer["wo"] = put("wo", np.ascontiguousarray(
                    tensor.T).astype(cfg.dtype))
            elif suffix == "input_layernorm.weight":
                layer["attn_norm"] = {"norm": jnp.asarray(tensor, norm_dt)}
            elif suffix == "post_attention_layernorm.weight":
                layer["mlp_norm"] = {"norm": jnp.asarray(tensor, norm_dt)}
            elif suffix in ("mlp.gate_proj.weight", "mlp.up_proj.weight",
                            "mlp.down_proj.weight"):
                key = w_map[suffix.split(".")[1]]
                layer[key] = put(key, np.ascontiguousarray(
                    tensor.T).astype(cfg.dtype))
            else:
                raise ValueError(f"unmapped deepseek tensor {name!r}")
        elif name == "model.embed_tokens.weight":
            params["embedding"] = put("embedding", tensor.astype(cfg.dtype))
        elif name == "lm_head.weight":
            params["lm_head"] = put("lm_head", np.ascontiguousarray(
                tensor.T).astype(cfg.dtype))
        elif name == "model.norm.weight":
            params["final_norm"] = {"norm": jnp.asarray(tensor, norm_dt)}
        else:
            raise ValueError(f"unmapped deepseek tensor {name!r}")

    if cfg.tie_embeddings:
        params.pop("lm_head", None)
    elif "lm_head" not in params:
        params["lm_head"] = put("lm_head", np.ascontiguousarray(
            np.asarray(params["embedding"]).T).astype(cfg.dtype))

    # completeness: expected key count per layer from the config
    missing = [k for k in ("embedding", "final_norm") if k not in params]
    for li, layer in enumerate(params["layers"]):
        want = 7  # attn_norm, mlp_norm, wkv_a, kv_a_norm, w_uk, w_uv, wo
        want += 3 if cfg.q_lora_rank > 0 else 1
        if cfg._moe_layer(li):
            want += 4 + (1 if cfg.moe_scoring == "sigmoid" else 0) \
                + (1 if cfg.n_shared_experts > 0 else 0)
        else:
            want += 3
        if len(layer) != want:
            missing.append(
                f"model.layers.{li} ({len(layer)}/{want} tensors)")
    missing.extend(
        f"model.layers.{li} expert tensors {parts}"
        for li, parts in stage.pending()
    )
    if missing:
        raise ValueError(f"incomplete checkpoint {model_path}: missing "
                         f"{missing[:5]}")
    return params


def load_params(
    model_path: str,
    cfg=None,
    mesh=None,
    host_cache: bool = True,
) -> Dict[str, Any]:
    """Load a HF checkpoint into the matching family's params pytree.

    With a mesh, each tensor is device_put directly to its NamedSharding
    (per-weight streaming: host RAM holds one tensor at a time beyond the
    checkpoint mmap).  Without, arrays stay as committed jax arrays on the
    default device.

    host_cache: consult/populate the tmpfs weight cache
    (models/weight_cache.py) so a restarted worker skips the disk reload
    and every transform — the fast-restart path (the reference covers
    this with GMS/ModelExpress).  DYN_WEIGHT_CACHE=0 disables globally.
    """
    from jax.sharding import NamedSharding

    from .weight_cache import default_cache_dir, read_cache, write_cache

    cache_dir = default_cache_dir() if host_cache else None
    if cache_dir is not None:
        cached = read_cache(cache_dir, model_path, mesh=mesh)
        if cached is not None:
            return cached

    cfg = cfg or load_hf_config(model_path)
    rules = param_sharding_rules()

    def put(name_key: str, arr: np.ndarray):
        arr = jnp.asarray(arr)
        if mesh is not None:
            return jax.device_put(
                arr, NamedSharding(mesh, rules.get(name_key, jax.sharding.PartitionSpec()))
            )
        return arr

    if isinstance(cfg, DeepseekConfig):
        params = _load_deepseek_params(model_path, cfg, put)
        if cache_dir is not None:
            # MLA's de-interleaves/permutes are the most expensive
            # transforms in the repo — exactly what the cache amortizes
            write_cache(cache_dir, model_path, params)
        return params

    norm_dt = jnp.float32
    params: Dict[str, Any] = {
        "layers": [dict() for _ in range(cfg.n_layers)]
    }
    stage = _ExpertStage(
        cfg.n_experts, cfg.dtype,
        lambda li, key, buf: params["layers"][li].__setitem__(
            key, put(key, buf)))
    for name, tensor in _iter_safetensors(model_path):
        m = _LAYER_RE.match(name)
        if m:
            li, suffix = int(m.group(1)), m.group(2)
            em = _MOE_EXPERT_RE.match(suffix)
            if em:
                stage.feed(li, int(em.group(1)), _MOE_W_MAP[em.group(2)],
                           tensor.T)
                continue
            if suffix == _MOE_GATE:
                params["layers"][li]["moe_gate"] = put(
                    "moe_gate",
                    np.ascontiguousarray(tensor.T).astype(cfg.dtype),
                )
                continue
            if suffix not in _LAYER_MAP:
                raise ValueError(f"unmapped layer tensor {name!r}")
            key, transpose = _LAYER_MAP[suffix]
            t = tensor.T if transpose else tensor
            if key in _NORM_KEYS:
                params["layers"][li][key] = {
                    "norm": jnp.asarray(t).astype(norm_dt)
                }
            else:
                params["layers"][li][key] = put(
                    key, np.ascontiguousarray(t).astype(cfg.dtype)
                )
        elif name == "model.embed_tokens.weight":
            params["embedding"] = put(
                "embedding", tensor.astype(cfg.dtype))
        elif name == "lm_head.weight":
            params["lm_head"] = put(
                "lm_head", np.ascontiguousarray(tensor.T).astype(cfg.dtype))
        elif name == "model.norm.weight":
            params["final_norm"] = {
                "norm": jnp.asarray(tensor).astype(norm_dt)
            }
        else:
            raise ValueError(f"unmapped tensor {name!r}")

    if cfg.tie_embeddings:
        params.pop("lm_head", None)
    elif "lm_head" not in params:
        # some tied checkpoints omit lm_head but don't set the flag
        params["lm_head"] = put(
            "lm_head",
            np.ascontiguousarray(
                np.asarray(params["embedding"]).T).astype(cfg.dtype),
        )

    missing = []
    if "embedding" not in params:
        missing.append("model.embed_tokens.weight")
    if "final_norm" not in params:
        missing.append("model.norm.weight")
    want = set(_LAYER_MAP)
    if not cfg.qk_norm:
        want -= {"self_attn.q_norm.weight", "self_attn.k_norm.weight"}
    if cfg.n_experts > 0:
        # routed MLP replaces the dense one: gate tensor + 3 stacked
        # expert arrays instead of the 3 dense projections
        want -= {"mlp.gate_proj.weight", "mlp.up_proj.weight",
                 "mlp.down_proj.weight"}
        want |= {"moe_gate", "moe_w_gate", "moe_w_up", "moe_w_down"}
    missing.extend(
        f"model.layers.{li} expert tensors {parts}"
        for li, parts in stage.pending()
    )
    for li, layer in enumerate(params["layers"]):
        got = len(layer)
        if got != len(want):
            missing.append(f"model.layers.{li} ({got}/{len(want)} tensors)")
    if missing:
        raise ValueError(f"incomplete checkpoint {model_path}: missing "
                         f"{missing[:5]}")
    if cache_dir is not None:
        write_cache(cache_dir, model_path, params)
    return params
