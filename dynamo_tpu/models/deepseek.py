"""DeepSeek-family decoder: MLA attention + DeepSeekMoE, functional JAX
over the paged latent cache (BASELINE config 4: DeepSeek-R1 disagg).

Ref role: the reference serves DeepSeek-R1 via vLLM/SGLang recipes
(/root/reference/recipes/deepseek-r1/, docs/benchmarks/deepseek-v3-2-
wideep-routing.mdx); this module is the TPU-native model itself, same
functional contract as models/llama.py (prefill / prefill_batched /
decode / decode_multi over a paged cache) so the serving engine treats
both families uniformly through models.get_family().

Architecture (DeepSeek V2/V3 lineage):
  * MLA: queries optionally LoRA-compressed (q_lora_rank), KV compressed
    to a kv_lora_rank latent + a decoupled shared RoPE key; the paged
    cache stores (latent, rope-key) pairs — ops/mla_attention.py.
  * DeepSeekMoE: first_k_dense dense layers, then MoE layers with
    n_shared_experts always-on dense experts plus top-k routed experts
    (llama.py's dispatch machinery, scaled by routed_scaling_factor).

Decode runs the weight-absorbed MLA formulation (never materializes
per-head K/V); prefill up-projects per chunk.  YaRN long-context scaling
is not implemented (rope_theta covers the tested ranges).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.mla_attention import mla_decode_attention, mla_prefill_attention
from ..ops.paged_attention import (
    write_prompt_kv,
    write_prompt_kv_batched,
    write_token_kv,
)
from .llama import (
    _logits,
    _mlp,
    moe_dispatch_capacity,
    moe_dispatch_dense,
    rms_norm,
    rope,
)


@dataclass(frozen=True)
class DeepseekConfig:
    name: str = "tiny-mla"
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    # MLA dims
    q_lora_rank: int = 0          # 0 = full query projection (V2-Lite)
    kv_lora_rank: int = 64        # R: latent cache dim per token
    qk_nope_head_dim: int = 32
    qk_rope_head_dim: int = 16    # dr: shared rope key dim per token
    v_head_dim: int = 32
    # FFN / DeepSeekMoE
    ffn_dim: int = 1408           # dense layers
    moe_ffn_dim: int = 0          # per-expert hidden (0 -> ffn_dim)
    n_experts: int = 0            # 0 = all layers dense
    experts_per_token: int = 2
    n_shared_experts: int = 0     # always-on experts (hidden = n * moe_ffn)
    first_k_dense: int = 1        # leading dense layers before MoE starts
    routed_scaling_factor: float = 1.0
    moe_dispatch: str = "dense"   # llama.py semantics: dense | capacity
    moe_capacity_factor: float = 1.25
    # router semantics (HF DeepseekV3TopkRouter / V2 MoEGate):
    #   V2 lineage: softmax scores, plain top-k, no renorm
    #   V3 lineage: sigmoid scores + e_score_correction_bias for CHOICE
    #   (weights stay raw scores), group-limited top-k, renormalized
    moe_scoring: str = "softmax"  # "softmax" | "sigmoid"
    norm_topk_prob: bool = False
    n_group: int = 1              # expert groups for group-limited top-k
    topk_group: int = 1           # groups kept
    # misc
    rope_theta: float = 10000.0
    rms_eps: float = 1e-6
    tie_embeddings: bool = False
    max_context: int = 8192
    dtype: Any = jnp.bfloat16
    attn_impl: str = "jnp"        # MLA decode is jnp-only (absorbed path)
    eos_token_ids: Tuple[int, ...] = (2,)
    qk_norm: bool = False         # unused; uniform surface with LlamaConfig

    @property
    def qk_head_dim(self) -> int:
        return self.qk_nope_head_dim + self.qk_rope_head_dim

    @property
    def q_dim(self) -> int:
        return self.n_heads * self.qk_head_dim

    def _moe_layer(self, li: int) -> bool:
        return self.n_experts > 0 and li >= self.first_k_dense


# the absorbed-latent MLA decode path never consults cfg.attn_impl (no
# paged_attention_decode dispatch in this family), so an engine-level
# --attn-impl override of anything but "jnp" would be silently ignored;
# the engine rejects those loudly against this set
SUPPORTED_ATTN_IMPLS = ("jnp",)

PRESETS: Dict[str, DeepseekConfig] = {
    # test-scale
    "tiny-mla": DeepseekConfig(),
    "tiny-mla-moe": DeepseekConfig(
        name="tiny-mla-moe", vocab_size=256, d_model=64, n_layers=3,
        n_heads=4, kv_lora_rank=32, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16, ffn_dim=128, moe_ffn_dim=64,
        n_experts=4, experts_per_token=2, n_shared_experts=1,
        first_k_dense=1,
    ),
    # public architecture shapes
    "deepseek-v2-lite": DeepseekConfig(
        name="deepseek-v2-lite", vocab_size=102400, d_model=2048,
        n_layers=27, n_heads=16, q_lora_rank=0, kv_lora_rank=512,
        qk_nope_head_dim=128, qk_rope_head_dim=64, v_head_dim=128,
        ffn_dim=10944, moe_ffn_dim=1408, n_experts=64,
        experts_per_token=6, n_shared_experts=2, first_k_dense=1,
        routed_scaling_factor=1.0, rope_theta=10000.0,
        max_context=163840,
    ),
    # BASELINE config 4 (DeepSeek-R1 == V3 architecture)
    "deepseek-r1": DeepseekConfig(
        name="deepseek-r1", vocab_size=129280, d_model=7168,
        n_layers=61, n_heads=128, q_lora_rank=1536, kv_lora_rank=512,
        qk_nope_head_dim=128, qk_rope_head_dim=64, v_head_dim=128,
        ffn_dim=18432, moe_ffn_dim=2048, n_experts=256,
        experts_per_token=8, n_shared_experts=1, first_k_dense=3,
        routed_scaling_factor=2.5, moe_scoring="sigmoid",
        norm_topk_prob=True, n_group=8, topk_group=4,
        rope_theta=10000.0, max_context=163840,
    ),
}


# ---------------------------------------------------------------------------
# cache spec (consumed by the engine's _init_kv_cache via get_family)
# ---------------------------------------------------------------------------


def kv_cache_shapes(cfg: DeepseekConfig, num_blocks: int,
                    block_size: int) -> Tuple[tuple, tuple]:
    """(latent cache, rope-key cache) in the shared head-major layout with
    nkv=1 — every block op (scatter/gather/offload/transfer) reuses it.

    NOTE: this family deliberately has NO kv_cache_scale_shapes — the MLA
    latent is already a ~4x compression of the per-head K/V and the
    weight-absorbed decode consumes it inside matmuls where per-position
    int8 scales don't factor out cleanly, so `kv_cache_dtype="int8"`
    auto-falls back to bf16 here (engine/core.py, same precedent as the
    MLA packed-prefill and spec-decode fallbacks)."""
    return (
        (cfg.n_layers, 1, num_blocks, cfg.kv_lora_rank, block_size),
        (cfg.n_layers, 1, num_blocks, cfg.qk_rope_head_dim, block_size),
    )


def kv_cache_specs() -> Tuple[P, P]:
    """Latent caches are REPLICATED under tp (there is no kv-head axis to
    shard; heads shard via w_uk/w_uv/wq_b instead).  At R+dr bytes/token
    the replicated cache is still ~nkv*2*hd/(R+dr) smaller per chip than a
    sharded GQA cache for the big configs."""
    return (P(), P())


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def init_params(cfg: DeepseekConfig, key: jax.Array) -> Dict[str, Any]:
    def dense(key, shape, scale=None):
        scale = scale if scale is not None else 1.0 / math.sqrt(shape[0])
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(
            cfg.dtype
        )

    R, dr = cfg.kv_lora_rank, cfg.qk_rope_head_dim
    dn, dv = cfg.qk_nope_head_dim, cfg.v_head_dim
    keys = jax.random.split(key, cfg.n_layers + 3)
    params: Dict[str, Any] = {
        "embedding": dense(keys[0], (cfg.vocab_size, cfg.d_model),
                           scale=0.02),
        "final_norm": {"norm": jnp.ones((cfg.d_model,), jnp.float32)},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(keys[1], (cfg.d_model, cfg.vocab_size))
    layers = []
    for li in range(cfg.n_layers):
        k = jax.random.split(keys[2 + li], 13)
        layer: Dict[str, Any] = {
            "attn_norm": {"norm": jnp.ones((cfg.d_model,), jnp.float32)},
            "mlp_norm": {"norm": jnp.ones((cfg.d_model,), jnp.float32)},
            "wkv_a": dense(k[0], (cfg.d_model, R + dr)),
            "kv_a_norm": {"norm": jnp.ones((R,), jnp.float32)},
            "w_uk": dense(k[1], (cfg.n_heads, R, dn),
                          scale=1.0 / math.sqrt(R)),
            "w_uv": dense(k[2], (cfg.n_heads, R, dv),
                          scale=1.0 / math.sqrt(R)),
            "wo": dense(k[3], (cfg.n_heads * dv, cfg.d_model)),
        }
        if cfg.q_lora_rank > 0:
            layer["wq_a"] = dense(k[4], (cfg.d_model, cfg.q_lora_rank))
            layer["q_a_norm"] = {
                "norm": jnp.ones((cfg.q_lora_rank,), jnp.float32)}
            layer["wq_b"] = dense(k[5], (cfg.q_lora_rank, cfg.q_dim))
        else:
            layer["wq"] = dense(k[4], (cfg.d_model, cfg.q_dim))
        if cfg._moe_layer(li):
            E = cfg.n_experts
            f = cfg.moe_ffn_dim or cfg.ffn_dim
            layer["moe_gate"] = dense(k[6], (cfg.d_model, E))
            if cfg.moe_scoring == "sigmoid":
                # V3 lineage: choice-bias buffer (loaded from checkpoints)
                layer["moe_gate_bias"] = jnp.zeros((E,), jnp.float32)
            layer["moe_w_gate"] = dense(k[7], (E, cfg.d_model, f),
                                        scale=1.0 / math.sqrt(cfg.d_model))
            layer["moe_w_up"] = dense(k[8], (E, cfg.d_model, f),
                                      scale=1.0 / math.sqrt(cfg.d_model))
            layer["moe_w_down"] = dense(k[9], (E, f, cfg.d_model),
                                        scale=1.0 / math.sqrt(f))
            if cfg.n_shared_experts > 0:
                sf = cfg.n_shared_experts * f
                layer["shared"] = {
                    "w_gate": dense(k[10], (cfg.d_model, sf)),
                    "w_up": dense(k[11], (cfg.d_model, sf)),
                    "w_down": dense(k[12], (sf, cfg.d_model)),
                }
        else:
            layer["w_gate"] = dense(k[6], (cfg.d_model, cfg.ffn_dim))
            layer["w_up"] = dense(k[7], (cfg.d_model, cfg.ffn_dim))
            layer["w_down"] = dense(k[8], (cfg.ffn_dim, cfg.d_model))
        layers.append(layer)
    params["layers"] = layers
    return params


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------


def _q_proj(layer, cfg: DeepseekConfig, x: jax.Array,
            positions: jax.Array):
    """x [..., T, d] -> (q_nope [..., T, nh, dn], q_rope [..., T, nh, dr],
    rope applied to the rope part)."""
    *lead, T, _ = x.shape
    if cfg.q_lora_rank > 0:
        q = rms_norm(x @ layer["wq_a"], layer["q_a_norm"]["norm"],
                     cfg.rms_eps) @ layer["wq_b"]
    else:
        q = x @ layer["wq"]
    q = q.reshape(*lead, T, cfg.n_heads, cfg.qk_head_dim)
    q_nope = q[..., : cfg.qk_nope_head_dim]
    q_rope = rope(q[..., cfg.qk_nope_head_dim:], positions, cfg.rope_theta)
    return q_nope, q_rope


def _kv_latent(layer, cfg: DeepseekConfig, x: jax.Array,
               positions: jax.Array):
    """x [..., T, d] -> (c [..., T, R] normed latent, kr [..., T, dr]
    rope-applied shared key)."""
    R = cfg.kv_lora_rank
    kv = x @ layer["wkv_a"]                      # [..., T, R+dr]
    c = rms_norm(kv[..., :R], layer["kv_a_norm"]["norm"], cfg.rms_eps)
    kr = rope(kv[..., None, R:], positions, cfg.rope_theta)[..., 0, :]
    return c, kr


def _ds_router(layer, cfg: DeepseekConfig, x: jax.Array):
    """DeepSeek routing -> (weights [T, k], ids [T, k]).

    Mirrors HF DeepseekV3TopkRouter exactly: scores are sigmoid (V3) or
    softmax (V2); expert CHOICE adds e_score_correction_bias and applies
    group-limited top-k (per-group score = sum of that group's top-2),
    but combine WEIGHTS are the raw scores of the chosen experts,
    optionally renormalized, then scaled by routed_scaling_factor."""
    T = x.shape[0]
    E, k = cfg.n_experts, cfg.experts_per_token
    logits = x.astype(jnp.float32) @ layer["moe_gate"].astype(jnp.float32)
    if cfg.moe_scoring == "sigmoid":
        scores = jax.nn.sigmoid(logits)
    else:
        scores = jax.nn.softmax(logits, axis=-1)
    choice = scores + layer["moe_gate_bias"] if "moe_gate_bias" in layer \
        else scores
    if cfg.n_group > 1:
        g = choice.reshape(T, cfg.n_group, E // cfg.n_group)
        if cfg.moe_scoring == "sigmoid":
            # V3 lineage: group score = sum of the group's top-2
            group_scores = jax.lax.top_k(g, 2)[0].sum(-1)    # [T, n_group]
        else:
            # V2 lineage (group_limited_greedy): group score = group max
            group_scores = g.max(-1)
        _, keep = jax.lax.top_k(group_scores, cfg.topk_group)
        gmask = jnp.zeros((T, cfg.n_group), bool).at[
            jnp.arange(T)[:, None], keep].set(True)
        choice = jnp.where(
            jnp.repeat(gmask, E // cfg.n_group, axis=1), choice, 0.0)
    _, top_e = jax.lax.top_k(choice, k)                      # [T, k]
    top_w = jnp.take_along_axis(scores, top_e, axis=1)
    if cfg.norm_topk_prob:
        top_w = top_w / (top_w.sum(-1, keepdims=True) + 1e-20)
    return top_w * cfg.routed_scaling_factor, top_e


def _ds_ffn(layer, cfg: DeepseekConfig, x: jax.Array,
            valid: Optional[jax.Array] = None) -> jax.Array:
    """Dense layer, or DeepSeekMoE = shared experts + routed experts
    (DeepSeek routing + llama.py's dispatch over the moe_* keys)."""
    if "moe_gate" not in layer:
        return _mlp(layer, x)
    top_w, top_e = _ds_router(layer, cfg, x)
    dispatch = (moe_dispatch_capacity if cfg.moe_dispatch == "capacity"
                else moe_dispatch_dense)
    out = dispatch(layer, cfg, x, top_w, top_e, valid)
    if "shared" in layer:
        out = out + _mlp(layer["shared"], x)
    return out


def _absorb_q(layer, q_nope: jax.Array) -> jax.Array:
    """q_nope [..., nh, dn] @ w_uk^T -> absorbed query [..., nh, R]."""
    return jnp.einsum("...hd,hrd->...hr", q_nope.astype(jnp.float32),
                      layer["w_uk"].astype(jnp.float32)).astype(q_nope.dtype)


# ---------------------------------------------------------------------------
# prefill
# ---------------------------------------------------------------------------


def prefill(
    params: Dict[str, Any],
    cfg: DeepseekConfig,
    kv_cache: Tuple[jax.Array, jax.Array],
    token_ids: jax.Array,      # [T_pad] int32
    positions: jax.Array,      # [T_pad] int32
    block_table: jax.Array,    # [max_blocks] int32
    ctx_len: jax.Array,
    true_len: jax.Array,
):
    """Same contract as llama.prefill; cache pair = (latent, rope key)."""
    # dynlint: disable=DYN009 MLA latent cache is bf16-only by design (no int8 scale shapes); the engine forces the bf16 fallback for this family
    c_cache, kr_cache = kv_cache
    x = params["embedding"][token_ids].astype(cfg.dtype)  # [T, d]
    T = x.shape[0]
    for li, layer in enumerate(params["layers"]):
        h = rms_norm(x, layer["attn_norm"]["norm"], cfg.rms_eps)
        q_nope, q_rope = _q_proj(layer, cfg, h, positions)
        c, kr = _kv_latent(layer, cfg, h, positions)
        c_cache, kr_cache = write_prompt_kv(
            c_cache, kr_cache, li, c[:, None, :], kr[:, None, :],
            block_table, ctx_len, true_len,
        )
        attn = mla_prefill_attention(
            q_nope, q_rope, c, kr, c_cache, kr_cache, li,
            block_table, ctx_len, true_len,
            layer["w_uk"], layer["w_uv"],
        )
        x = x + attn.reshape(T, -1) @ layer["wo"]
        h = rms_norm(x, layer["mlp_norm"]["norm"], cfg.rms_eps)
        x = x + _ds_ffn(layer, cfg, h,
                        valid=jnp.arange(T) < true_len)
    last = jnp.maximum(true_len - 1, 0)
    return _logits(params, cfg, x[last]), (c_cache, kr_cache)


def prefill_batched(
    params: Dict[str, Any],
    cfg: DeepseekConfig,
    kv_cache: Tuple[jax.Array, jax.Array],
    token_ids: jax.Array,      # [Bp, T_pad]
    positions: jax.Array,      # [Bp, T_pad]
    block_tables: jax.Array,   # [Bp, max_blocks]
    ctx_lens: jax.Array,       # [Bp]
    true_lens: jax.Array,      # [Bp]
):
    """Multi-sequence chunked prefill (llama.prefill_batched contract)."""
    # dynlint: disable=DYN009 MLA latent cache is bf16-only by design (no int8 scale shapes); the engine forces the bf16 fallback for this family
    c_cache, kr_cache = kv_cache
    Bp, T = token_ids.shape
    x = params["embedding"][token_ids].astype(cfg.dtype)  # [Bp, T, d]
    valid = jnp.arange(T)[None, :] < true_lens[:, None]
    for li, layer in enumerate(params["layers"]):
        h = rms_norm(x, layer["attn_norm"]["norm"], cfg.rms_eps)
        q_nope, q_rope = _q_proj(layer, cfg, h, positions)
        c, kr = _kv_latent(layer, cfg, h, positions)
        c_cache, kr_cache = write_prompt_kv_batched(
            c_cache, kr_cache, li, c[:, :, None, :], kr[:, :, None, :],
            block_tables, ctx_lens, true_lens,
        )
        attn = jax.vmap(
            lambda qn, qr, cb, krb, tb, cl, tl: mla_prefill_attention(
                qn, qr, cb, krb, c_cache, kr_cache, li, tb, cl, tl,
                layer["w_uk"], layer["w_uv"],
            )
        )(q_nope, q_rope, c, kr, block_tables, ctx_lens, true_lens)
        x = x + attn.reshape(Bp, T, -1) @ layer["wo"]
        h = rms_norm(x, layer["mlp_norm"]["norm"], cfg.rms_eps)
        # per-row dispatch: co-batched sequences keep separate MoE
        # capacity pools (llama.prefill_batched rationale)
        x = x + jax.vmap(
            lambda hb, vb: _ds_ffn(layer, cfg, hb, valid=vb)
        )(h, valid)
    last = jnp.maximum(true_lens - 1, 0)
    xl = x[jnp.arange(Bp), last]
    return _logits(params, cfg, xl), (c_cache, kr_cache)


# ---------------------------------------------------------------------------
# decode (weight-absorbed)
# ---------------------------------------------------------------------------


def decode(
    params: Dict[str, Any],
    cfg: DeepseekConfig,
    kv_cache: Tuple[jax.Array, jax.Array],
    token_ids: jax.Array,      # [B]
    positions: jax.Array,      # [B]
    block_tables: jax.Array,   # [B, max_blocks]
    ctx_lens: jax.Array,       # [B]
    valid: Optional[jax.Array] = None,
    mesh=None,                 # uniform signature; MLA decode is pure jnp
):
    # dynlint: disable=DYN009 MLA latent cache is bf16-only by design (no int8 scale shapes); the engine forces the bf16 fallback for this family
    c_cache, kr_cache = kv_cache
    x = params["embedding"][token_ids].astype(cfg.dtype)  # [B, d]
    B = x.shape[0]
    pos1 = positions[:, None]
    scale = 1.0 / jnp.sqrt(jnp.float32(cfg.qk_head_dim))
    for li, layer in enumerate(params["layers"]):
        h = rms_norm(x, layer["attn_norm"]["norm"], cfg.rms_eps)
        q_nope, q_rope = _q_proj(layer, cfg, h[:, None, :], pos1)
        c, kr = _kv_latent(layer, cfg, h[:, None, :], pos1)
        c_cache, kr_cache = write_token_kv(
            c_cache, kr_cache, li, c[:, 0][:, None, :],
            kr[:, 0][:, None, :], block_tables, ctx_lens,
        )
        q_abs = _absorb_q(layer, q_nope[:, 0])           # [B, nh, R]
        attn = mla_decode_attention(
            q_abs, q_rope[:, 0], c_cache, kr_cache, li,
            block_tables, ctx_lens + 1, layer["w_uv"], scale,
        )                                                # [B, nh, dv]
        x = x + attn.reshape(B, -1) @ layer["wo"]
        h = rms_norm(x, layer["mlp_norm"]["norm"], cfg.rms_eps)
        x = x + _ds_ffn(layer, cfg, h, valid=valid)
    return _logits(params, cfg, x), (c_cache, kr_cache)


def decode_multi(
    params: Dict[str, Any],
    cfg: DeepseekConfig,
    kv_cache: Tuple[jax.Array, jax.Array],
    token_ids: jax.Array,
    positions: jax.Array,
    block_tables: jax.Array,
    ctx_lens: jax.Array,
    num_steps: int,
    sample_fn=None,
    valid: Optional[jax.Array] = None,
    mesh=None,
):
    """num_steps fused decode steps (llama.decode_multi contract)."""
    if sample_fn is None:
        def sample_fn(logits, _):
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def body(carry, step_idx):
        tokens, kv, pos, cls = carry
        logits, kv = decode(params, cfg, kv, tokens, pos, block_tables,
                            cls, valid=valid, mesh=mesh)
        nt = sample_fn(logits, step_idx).astype(jnp.int32)
        return (nt, kv, pos + 1, cls + 1), nt

    (_, kv_cache, _, _), toks = jax.lax.scan(
        body, (token_ids, kv_cache, positions, ctx_lens),
        jnp.arange(num_steps), length=num_steps,
    )
    return toks, kv_cache
