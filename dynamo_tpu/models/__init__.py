from .llama import LlamaConfig, init_params, PRESETS

__all__ = ["LlamaConfig", "init_params", "PRESETS"]
