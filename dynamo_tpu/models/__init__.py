"""Model families, uniform functional contract per family module:

    init_params(cfg, key)            parameter pytree
    prefill / prefill_batched        chunked prompt over the paged cache
    decode / decode_multi            batched token steps
    kv_cache_shapes(cfg, nb, bs)     (k-like, v-like) cache shapes
    kv_cache_specs()                 (k, v) PartitionSpecs under the mesh
    PRESETS                          name -> config

The engine binds a family once via get_family(cfg) and never branches on
architecture again — Llama/Qwen/Mixtral (llama.py, GQA cache) and the
DeepSeek MLA family (deepseek.py, latent cache) serve through identical
plumbing."""

from . import deepseek, llama
from .deepseek import DeepseekConfig
from .llama import LlamaConfig, init_params

PRESETS = {**llama.PRESETS, **deepseek.PRESETS}


def get_family(cfg):
    """Model-family module for a config instance."""
    if isinstance(cfg, DeepseekConfig):
        return deepseek
    if isinstance(cfg, LlamaConfig):
        return llama
    raise TypeError(f"unknown model config type: {type(cfg).__name__}")


__all__ = [
    "DeepseekConfig",
    "LlamaConfig",
    "PRESETS",
    "get_family",
    "init_params",
]
