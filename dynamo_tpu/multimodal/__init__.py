"""Multimodal encoder disaggregation (BASELINE config 5).

Ref: the reference's encode/prefill/decode disagg —
components/src/dynamo/vllm/multimodal_handlers/encode_worker_handler.py
(vision tower on a dedicated worker, embedding cache keyed by media hash,
embeddings shipped to the LLM worker) and
lib/llm/src/kv_router/encoder_router.rs (media-hash cache affinity).

TPU-native shape:
  * EncoderWorker serves an `encode` endpoint on the request plane: media
    in, embeddings out, LRU-cached by media hash (multimodal/worker.py).
  * The frontend preprocessor extracts image parts from OpenAI chat
    messages into media descriptors; the EncoderHop in the model pipeline
    encodes them (media-hash rendezvous routing for cache affinity) and
    splices `n_tokens` placeholder tokens per image into the prompt
    (multimodal/hop.py).
  * media hashes SALT the KV block hashing everywhere (tokens/hashing.py
    request_salt), so identical placeholder tokens with different media
    never alias in the prefix cache, KVBM, or the router index.

Engine-side embedding splicing (placeholder positions -> encoder output
instead of the embedding table) is the remaining seam: the serving
engines currently account for image tokens in scheduling, caching, and
routing, but compute over placeholder embeddings.
"""

from .encoder import (
    EmbeddingCache,
    MockVisionEncoder,
    VisionConfig,
    VitEncoder,
    media_hash,
)
from .hop import EncoderHop
from .worker import EncoderWorker

__all__ = [
    "EmbeddingCache",
    "EncoderHop",
    "EncoderWorker",
    "MockVisionEncoder",
    "VisionConfig",
    "VitEncoder",
    "media_hash",
]
