"""Vision encoders: a jittable JAX ViT tower + a CPU mock, with an
embedding cache keyed by media hash.

Ref role: encode_worker_handler.py loads a vision model (vLLM) and caches
embeddings by item key; here the tower is a functional JAX ViT — patchify
-> transformer blocks -> project to the LLM's embedding width — all
static shapes so XLA compiles one program per image-size bucket and the
matmuls land on the MXU.
"""

from __future__ import annotations

import base64
import hashlib
import io
import math
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np


def media_hash(data: bytes) -> str:
    """Stable content hash for a media item — the cache / routing /
    KV-salt key (ref encoder_router.rs: routing by media hash)."""
    return hashlib.sha256(data).hexdigest()[:32]


def decode_data_uri(uri: str) -> Tuple[bytes, str]:
    """data: URI -> (payload bytes, mime type)."""
    if not uri.startswith("data:"):
        raise ValueError("only data: URIs are supported (no egress)")
    head, _, b64 = uri.partition(",")
    mime = head[5:].split(";")[0] or "application/octet-stream"
    return base64.b64decode(b64), mime


def pixels_from_payload(data: bytes, mime: str,
                        image_size: int) -> np.ndarray:
    """Media payload -> [H, W, 3] float32 in [0, 1], resized to the
    encoder's square input.  `.npy` payloads pass through (tests, raw
    tensors); images decode via PIL when available."""
    if mime == "application/x-npy" or data[:6] == b"\x93NUMPY":
        arr = np.load(io.BytesIO(data))
        arr = np.asarray(arr, np.float32)
    else:
        try:
            from PIL import Image
        except ImportError as e:  # pragma: no cover
            raise ValueError(
                f"cannot decode {mime!r} media without PIL; send an .npy "
                "payload instead") from e
        img = Image.open(io.BytesIO(data)).convert("RGB")
        img = img.resize((image_size, image_size))
        arr = np.asarray(img, np.float32) / 255.0
    if arr.ndim == 2:
        arr = np.repeat(arr[..., None], 3, axis=-1)
    if arr.shape[:2] != (image_size, image_size):
        # nearest-neighbor resize without PIL (npy path)
        ys = (np.arange(image_size) * arr.shape[0] // image_size)
        xs = (np.arange(image_size) * arr.shape[1] // image_size)
        arr = arr[ys][:, xs]
    return np.ascontiguousarray(arr[..., :3], np.float32)


@dataclass(frozen=True)
class VisionConfig:
    image_size: int = 64
    patch_size: int = 16
    d_model: int = 128       # vision tower width
    n_layers: int = 2
    n_heads: int = 4
    out_dim: int = 512       # LLM embedding width (projection target)
    rms_eps: float = 1e-5
    dtype: Any = np.float32  # jnp dtype; np.float32 keeps CPU tests exact

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * 3


class VitEncoder:
    """Functional ViT tower.  encode([B, H, W, 3]) -> [B, n_patches,
    out_dim]; one jitted program per batch bucket."""

    def __init__(self, cfg: VisionConfig, seed: int = 0):
        import jax
        import jax.numpy as jnp

        self.cfg = cfg
        self._jnp = jnp
        key = jax.random.split(jax.random.PRNGKey(seed), 4 + cfg.n_layers)

        def dense(k, shape):
            scale = 1.0 / math.sqrt(shape[0])
            return (jax.random.normal(k, shape, jnp.float32) * scale
                    ).astype(cfg.dtype)

        self.params: Dict[str, Any] = {
            "patch_embed": dense(key[0], (cfg.patch_dim, cfg.d_model)),
            "pos_embed": dense(key[1], (cfg.n_patches, cfg.d_model)),
            "out_proj": dense(key[2], (cfg.d_model, cfg.out_dim)),
            "final_norm": jnp.ones((cfg.d_model,), jnp.float32),
            "layers": [],
        }
        for i in range(cfg.n_layers):
            k = jax.random.split(key[3 + i], 6)
            self.params["layers"].append({
                "norm1": jnp.ones((cfg.d_model,), jnp.float32),
                "norm2": jnp.ones((cfg.d_model,), jnp.float32),
                "wqkv": dense(k[0], (cfg.d_model, 3 * cfg.d_model)),
                "wo": dense(k[1], (cfg.d_model, cfg.d_model)),
                "w1": dense(k[2], (cfg.d_model, 4 * cfg.d_model)),
                "w2": dense(k[3], (4 * cfg.d_model, cfg.d_model)),
            })
        # dynlint: disable=DYN001 stub encoder worker outside the engine; no FPM/metrics plane to feed a CompileWatch yet
        self._jit = jax.jit(self._forward)

    @property
    def n_tokens(self) -> int:
        return self.cfg.n_patches

    def _norm(self, x, w):
        jnp = self._jnp
        xf = x.astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        return (xf * (1.0 / jnp.sqrt(var + self.cfg.rms_eps)) * w).astype(
            x.dtype)

    def _forward(self, params, pixels):
        jnp = self._jnp
        cfg = self.cfg
        B = pixels.shape[0]
        p = cfg.patch_size
        g = cfg.image_size // p
        # [B, H, W, 3] -> [B, n_patches, patch_dim]
        x = pixels.reshape(B, g, p, g, p, 3).transpose(0, 1, 3, 2, 4, 5)
        x = x.reshape(B, cfg.n_patches, cfg.patch_dim).astype(cfg.dtype)
        x = x @ params["patch_embed"] + params["pos_embed"]
        nh = cfg.n_heads
        hd = cfg.d_model // nh
        for layer in params["layers"]:
            h = self._norm(x, layer["norm1"])
            qkv = (h @ layer["wqkv"]).reshape(B, cfg.n_patches, 3, nh, hd)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            s = jnp.einsum("bihd,bjhd->bhij", q.astype(jnp.float32),
                           k.astype(jnp.float32)) / math.sqrt(hd)
            pattn = jnp.exp(s - s.max(-1, keepdims=True))
            pattn = pattn / pattn.sum(-1, keepdims=True)
            o = jnp.einsum("bhij,bjhd->bihd", pattn,
                           v.astype(jnp.float32)).astype(cfg.dtype)
            x = x + o.reshape(B, cfg.n_patches, cfg.d_model) @ layer["wo"]
            h = self._norm(x, layer["norm2"])
            x = x + jnp.maximum(h @ layer["w1"], 0.0) @ layer["w2"]
        x = self._norm(x, params["final_norm"])
        return (x @ params["out_proj"]).astype(cfg.dtype)

    def encode(self, pixels: np.ndarray) -> np.ndarray:
        """[B, H, W, 3] -> [B, n_patches, out_dim] numpy."""
        return np.asarray(self._jit(self.params, pixels))


class MockVisionEncoder:
    """Deterministic embeddings from the media bytes — the CPU test
    double (same contract as VitEncoder.encode on decoded payloads, but
    keyed on raw bytes so no pixel decoding is needed)."""

    def __init__(self, n_tokens: int = 4, out_dim: int = 16):
        self._n_tokens = n_tokens
        self.out_dim = out_dim

    @property
    def n_tokens(self) -> int:
        return self._n_tokens

    def encode_bytes(self, data: bytes) -> np.ndarray:
        seed = int.from_bytes(hashlib.sha256(data).digest()[:8], "big")
        rng = np.random.default_rng(seed)
        return rng.standard_normal(
            (self._n_tokens, self.out_dim)).astype(np.float32)


class EmbeddingCache:
    """LRU embeddings by media hash (ref: embedding_cache.py —
    re-encoding the same image for every turn of a session is the main
    encoder cost)."""

    def __init__(self, capacity: int = 32):
        self.capacity = capacity
        self._d: "OrderedDict[str, np.ndarray]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key: str) -> Optional[np.ndarray]:
        emb = self._d.get(key)
        if emb is None:
            self.misses += 1
            return None
        self._d.move_to_end(key)
        self.hits += 1
        return emb

    def put(self, key: str, emb: np.ndarray) -> None:
        self._d[key] = emb
        self._d.move_to_end(key)
        while len(self._d) > self.capacity:
            self._d.popitem(last=False)
