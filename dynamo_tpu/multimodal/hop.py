"""EncoderHop: the frontend's encode step for multimodal requests.

Ref: encoder_router.rs — encode requests route by MEDIA HASH so repeated
media (multi-turn vision chats, shared images) land on the encoder whose
embedding cache already holds them.  Here that is rendezvous hashing over
the live instance set: stable under fleet changes, no coordination.

The hop runs between preprocessing and generation (frontend/pipeline.py):
descriptors in `request.multimodal` are encoded (one call per unique
media item), `n_tokens` placeholder tokens per item are spliced into
`token_ids` at the recorded insert positions, and the items are replaced
with their embedding payloads for the engine.
"""

from __future__ import annotations

import hashlib
import logging
from dataclasses import replace
from typing import Dict, List, Optional

from ..protocols import PreprocessedRequest

logger = logging.getLogger(__name__)


def rendezvous_pick(instance_ids: List[int], key: str) -> Optional[int]:
    """Highest-random-weight choice: each (instance, key) pair scores
    independently, so fleet changes only remap the keys that scored
    highest on the departed instance."""
    best, best_score = None, b""
    for iid in instance_ids:
        score = hashlib.blake2b(
            f"{iid}:{key}".encode(), digest_size=8).digest()
        if best is None or score > best_score:
            best, best_score = iid, score
    return best


class EncoderHop:
    def __init__(self, client, image_token_id: int = 0):
        self.client = client  # `encode` endpoint client
        self.image_token_id = image_token_id

    async def encode_and_attach(
        self, request: PreprocessedRequest, token=None
    ) -> PreprocessedRequest:
        items = request.multimodal or []
        todo = [m for m in items if "data_uri" in m]
        if not todo:
            return request
        # one encode call per unique media item, routed for cache affinity
        # by the FIRST item's hash (a request's media usually shares a
        # session; per-item routing would fan one request across the fleet)
        uniq: Dict[str, dict] = {}
        for m in todo:
            uniq.setdefault(m["media_hash"], m)
        instance_id = rendezvous_pick(
            self.client.instance_ids, next(iter(uniq)))
        results: Dict[str, dict] = {}
        async for frame in self.client.generate(
            {"request_id": request.request_id,
             "items": [{"media_hash": h, "data_uri": m["data_uri"]}
                       for h, m in uniq.items()]},
            instance_id=instance_id, token=token,
        ):
            results[frame["media_hash"]] = frame
        missing = set(uniq) - set(results)
        if missing:
            raise RuntimeError(
                f"encoder returned no embedding for media {sorted(missing)}")

        # splice placeholders front-to-back with a running offset:
        # adjacent images sharing an insert_pos keep their user order
        # (a back-to-front splice would reverse them)
        token_ids = list(request.token_ids)
        resolved: List[dict] = []
        offset = 0
        for m in sorted(items, key=lambda m: m.get("insert_pos", 0)):
            r = results[m["media_hash"]]
            pos = min(m.get("insert_pos", len(token_ids)) + offset,
                      len(token_ids))
            token_ids[pos:pos] = [self.image_token_id] * r["n_tokens"]
            offset += r["n_tokens"]
            resolved.append({
                "media_hash": r["media_hash"],
                "n_tokens": r["n_tokens"],
                "shape": r["shape"],
                "dtype": r["dtype"],
                "embedding": r["embedding"],
            })
        return replace(request, token_ids=token_ids, multimodal=resolved)
