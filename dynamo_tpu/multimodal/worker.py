"""EncoderWorker: the encode side of encoder/decoder disaggregation.

Ref: encode_worker_handler.py — a dedicated worker owning the vision
tower, serving encode requests from the frontend, caching embeddings by
media hash, and publishing load metrics like every other fleet member (so
the planner can scale encoder fleets independently of prefill/decode —
the whole point of encoder disagg).

Endpoint contract (`encode`, request plane):
    request:  {"request_id": str,
               "items": [{"media_hash": str, "data_uri": str}, ...]}
    stream:   one frame per item:
              {"media_hash", "n_tokens", "shape", "dtype",
               "embedding": bytes, "cached": bool}

The MDC registers with runtime_config.role = "encoder", which the
frontend's ModelWatcher turns into an EncoderHop on the model pipeline.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

import numpy as np

from ..protocols.model_card import (
    ModelDeploymentCard,
    deregister_model,
    register_model,
)
from .encoder import (
    EmbeddingCache,
    MockVisionEncoder,
    VitEncoder,
    decode_data_uri,
    pixels_from_payload,
)

logger = logging.getLogger(__name__)

LOAD_SUBJECT_PREFIX = "load_metrics"


class EncoderWorker:
    def __init__(self, runtime, model_name: str, encoder=None,
                 namespace: str = "dynamo", component: str = "encoder",
                 cache_capacity: int = 32, image_token_id: int = 0):
        self.runtime = runtime
        self.model_name = model_name
        self.encoder = encoder or MockVisionEncoder()
        self.namespace = namespace
        self.component = component
        self.image_token_id = image_token_id
        self.cache = EmbeddingCache(cache_capacity)
        self.served = None
        self.card: Optional[ModelDeploymentCard] = None
        self._load_task: Optional[asyncio.Task] = None
        self.metrics = {"requests": 0, "items": 0, "cache_hits": 0,
                        "prompt_tokens": 0}
        self._active = 0

    async def start(self) -> "EncoderWorker":
        rt = self.runtime

        async def encode_handler(payload, ctx):
            self.metrics["requests"] += 1
            self._active += 1
            try:
                for item in payload.get("items", []):
                    yield await self._encode_item(item)
            finally:
                self._active -= 1

        comp = rt.namespace(self.namespace).component(self.component)
        self.served = await comp.endpoint("encode").serve_endpoint(
            encode_handler)
        self.card = ModelDeploymentCard(
            name=self.model_name,
            namespace=self.namespace,
            component=self.component,
            endpoint="encode",
            runtime_config={"role": "encoder",
                            "image_token_id": self.image_token_id},
        )
        await register_model(rt, self.card, self.served.instance_id)
        self._load_task = asyncio.create_task(self._load_loop())
        logger.info("encoder worker %d serving model %s (%s)",
                    self.served.instance_id, self.model_name,
                    type(self.encoder).__name__)
        return self

    async def _encode_item(self, item: dict) -> dict:
        key = item["media_hash"]
        emb = self.cache.get(key)
        cached = emb is not None
        if cached:
            self.metrics["cache_hits"] += 1
        else:
            data, mime = decode_data_uri(item["data_uri"])
            if isinstance(self.encoder, MockVisionEncoder):
                emb = self.encoder.encode_bytes(data)
            else:
                # the tower is blocking device compute (plus a multi-second
                # XLA compile on a new shape bucket): run off the event
                # loop so other streams and the load heartbeat stay live
                def run_tower():
                    pixels = pixels_from_payload(
                        data, mime, self.encoder.cfg.image_size)
                    return self.encoder.encode(pixels[None])[0]

                emb = await asyncio.to_thread(run_tower)
            self.cache.put(key, emb)
        self.metrics["items"] += 1
        self.metrics["prompt_tokens"] += int(emb.shape[0])
        return {
            "media_hash": key,
            "n_tokens": int(emb.shape[0]),
            "shape": list(emb.shape),
            "dtype": str(emb.dtype),
            "embedding": emb.tobytes(),
            "cached": cached,
        }

    # uniform worker surface for the planner's LoadObserver
    @property
    def engine(self):
        return self

    @property
    def num_active_seqs(self) -> int:
        return self._active

    def kv_usage(self) -> float:
        return 0.0

    itl_ema_s = 0.0

    async def _load_loop(self) -> None:
        subject = f"{LOAD_SUBJECT_PREFIX}.{self.namespace}.{self.component}"
        while True:
            await asyncio.sleep(0.5)
            if self.served is None:
                continue
            await self.runtime.event_plane.publish(subject, {
                "worker_id": self.served.instance_id,
                "active_seqs": self._active,
                "kv_usage": 0.0,
                "requests_total": self.metrics["requests"],
                # for an encoder fleet, "prompt tokens" = embedding tokens
                # produced (the unit of encode work the planner rates)
                "prompt_tokens_total": self.metrics["prompt_tokens"],
                "itl_ema_s": 0.0,
            })

    async def close(self) -> None:
        if self._load_task is not None:
            self._load_task.cancel()
        if self.served is not None and self.card is not None:
            await deregister_model(self.runtime, self.card,
                                   self.served.instance_id)
            await self.served.shutdown()
            self.served = None
