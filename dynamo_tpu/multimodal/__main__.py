"""`python -m dynamo_tpu.multimodal` — run an encoder worker.

The encode fleet of encoder/decoder disaggregation (BASELINE config 5);
pair with an LLM fleet serving the same --model-name:

    python -m dynamo_tpu.multimodal --model-name llava-x --encoder vit
    python -m dynamo_tpu.mocker --model-name llava-x
    python -m dynamo_tpu.frontend
"""

import argparse
import asyncio

from ..runtime import DistributedRuntime
from ..runtime.logging import setup_logging
from .encoder import MockVisionEncoder, VisionConfig, VitEncoder
from .worker import EncoderWorker


def build_args() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("dynamo_tpu.multimodal")
    p.add_argument("--model-name", required=True,
                   help="LLM model this encoder fleet serves")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="encoder")
    p.add_argument("--encoder", default="mock", choices=["mock", "vit"])
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--patch-size", type=int, default=16)
    p.add_argument("--vision-dim", type=int, default=128)
    p.add_argument("--vision-layers", type=int, default=2)
    p.add_argument("--out-dim", type=int, default=512,
                   help="LLM embedding width")
    p.add_argument("--cache-capacity", type=int, default=32)
    p.add_argument("--image-token-id", type=int, default=0,
                   help="placeholder token the frontend splices per "
                        "embedding position")
    return p


async def main() -> None:
    setup_logging()
    args = build_args().parse_args()
    if args.encoder == "vit":
        encoder = VitEncoder(VisionConfig(
            image_size=args.image_size, patch_size=args.patch_size,
            d_model=args.vision_dim, n_layers=args.vision_layers,
            out_dim=args.out_dim,
        ))
    else:
        encoder = MockVisionEncoder(out_dim=args.out_dim)
    rt = await DistributedRuntime.detached().start()
    worker = await EncoderWorker(
        rt, args.model_name, encoder=encoder,
        namespace=args.namespace, component=args.component,
        cache_capacity=args.cache_capacity,
        image_token_id=args.image_token_id,
    ).start()
    print(f"ready instance_id={worker.served.instance_id}", flush=True)
    try:
        await rt.root_token.wait_killed()
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    await worker.close()
    await rt.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
