"""dynlint baseline: grandfathered findings, checked in at the repo root.

Semantics (kept deliberately strict so the baseline shrinks and never
silently grows):

  * One line per grandfathered finding, ``RULE|path|stripped source
    line`` — the same key as :attr:`core.Finding.key`.  Keys are
    line-CONTENT based, so unrelated edits above a finding do not churn
    the file; editing the flagged line itself invalidates its entry
    (you fixed it or you changed it — either way, re-justify).
  * Multiset matching: a key appearing N times grandfathers at most N
    findings with that key.
  * **Stale entries fail the gate.**  When a baselined finding is fixed,
    its line must leave the file (tests/test_lint.py asserts this), so
    the baseline monotonically decreases and never hides a regression
    that happens to produce the same key later.

``python -m dynamo_tpu.lint --write-baseline`` regenerates the file from
the current findings; review the diff like any other code change.
"""

from __future__ import annotations

from collections import Counter
from typing import Iterable, List, Tuple

from .core import SUPPRESS_NO_REASON, Finding

HEADER = (
    "# dynlint baseline — grandfathered findings (see README 'Static "
    "analysis').\n"
    "# One `RULE|path|source line` per finding; stale entries fail the "
    "gate.\n")


def load(path: str) -> Counter:
    keys: Counter = Counter()
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line and not line.startswith("#"):
                    keys[line] += 1
    except FileNotFoundError:
        pass
    return keys


def apply(findings: Iterable[Finding], baseline: Counter
          ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """Split findings into (new, baselined); the third element is the
    stale baseline keys no current finding matched."""
    remaining = Counter(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        if f.rule == SUPPRESS_NO_REASON:
            # suppression hygiene is not baselineable (see render())
            new.append(f)
            continue
        if remaining.get(f.key, 0) > 0:
            remaining[f.key] -= 1
            old.append(f)
        else:
            new.append(f)
    stale = sorted(k for k, n in remaining.items() if n > 0 for _ in range(n))
    return new, old, stale


def key_path(key: str) -> str:
    """The path component of a `RULE|path|snippet` baseline key."""
    parts = key.split("|", 2)
    return parts[1] if len(parts) >= 2 else ""


def render(findings: Iterable[Finding]) -> str:
    """Baseline text for `findings`.  DYN000 (suppression hygiene) is
    never written: a reasonless or dead disable is fixed by editing the
    comment, not grandfathered — baselining it would launder the
    'reason mandatory' contract."""
    body = "".join(sorted(f.key + "\n" for f in findings
                          if f.rule != SUPPRESS_NO_REASON))
    return HEADER + body
