import sys

from .cli import main

try:
    sys.exit(main())
except BrokenPipeError:
    # stdout went away mid-report (`... | head`): suppress the
    # traceback, but the gate's verdict was NOT delivered — exit
    # non-zero so a pipefail CI step never reads a truncated report as
    # a clean run (128+SIGPIPE, the conventional code)
    sys.exit(141)
