"""dynlint core: AST module loading, rule registry, suppressions, results.

The framework half of the lint (rules live in rules.py): parse a file
once into a :class:`Module` with parent links, run every registered rule
whose path predicate matches, then fold in the two escape hatches —
per-line ``# dynlint: disable=DYNxxx <reason>`` suppressions (reason
mandatory, its absence is itself a finding) and the checked-in baseline
of grandfathered findings (baseline.py).

A finding's identity is ``rule|path|stripped-source-line`` rather than a
line NUMBER, so baselines and suppressions survive unrelated edits above
the flagged line; the path is canonicalized to the repo-relative form
(``dynamo_tpu/...`` / ``tests/...``) so the same baseline works from any
invocation directory.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# the meta-rule for suppression hygiene: a disable with no reason, or a
# disable no finding matched (both mean the comment lies about the
# code).  Not itself suppressible or baselineable — the whole point is
# that every disable carries its why and earns its keep.
SUPPRESS_NO_REASON = "DYN000"

_SUPPRESS_RE = re.compile(
    r"#\s*dynlint:\s*disable=([A-Za-z0-9,\s]+?)(?:\s+(\S.*))?$")


def canon_path(path: str) -> str:
    """Repo-relative posix path: cut everything before the last
    ``dynamo_tpu/`` or ``tests/`` segment so absolute and relative
    invocations produce identical finding keys."""
    p = str(path).replace("\\", "/")
    while p.startswith("./"):
        p = p[2:]
    for seg in ("dynamo_tpu/", "tests/", "benchmarks/"):
        i = p.rfind("/" + seg)
        if i >= 0:
            return p[i + 1:]
        if p.startswith(seg):
            return p
        # the marker directory itself (a root argument like
        # `/repo/dynamo_tpu`): canonical form is the bare segment
        bare = seg[:-1]
        if p == bare or p.endswith("/" + bare):
            return bare
    return p


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str        # canonical repo-relative path
    line: int        # 1-based
    message: str
    snippet: str     # stripped source line (part of the baseline key)

    @property
    def key(self) -> str:
        return f"{self.rule}|{self.path}|{self.snippet}"

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "snippet": self.snippet}

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass(frozen=True)
class LintRule:
    """One registered rule.  `check(module)` yields findings; `applies`
    gates by canonical path (rules are scoped — e.g. DYN010 does not
    police prints in CLI entrypoints or tests)."""

    rule_id: str
    title: str
    bug: str  # the shipped bug this rule encodes (README table)
    check: Callable[["Module"], Iterable[Finding]]
    applies: Callable[[str], bool]


RULES: Dict[str, LintRule] = {}


def register(rule_id: str, title: str, bug: str,
             applies: Optional[Callable[[str], bool]] = None):
    """Decorator adding a rule to the registry.  Adding a rule is:
    write the checker here, register it, add fixture tests, and run the
    sweep (README "Static analysis" walks through it)."""

    def deco(fn: Callable[["Module"], Iterable[Finding]]):
        if rule_id in RULES:
            raise ValueError(f"duplicate lint rule {rule_id}")
        RULES[rule_id] = LintRule(rule_id=rule_id, title=title, bug=bug,
                                  check=fn, applies=applies or (lambda p: True))
        return fn

    return deco


class Module:
    """One parsed source file plus the helpers rules need: parent links,
    enclosing-scope lookups, dotted-name resolution."""

    def __init__(self, source: str, path: str):
        self.source = source
        self.path = canon_path(path)
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=self.path)
        self._parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    # -- tree navigation --------------------------------------------------
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        n = self._parents.get(node)
        while n is not None:
            yield n
            n = self._parents.get(n)

    def enclosing_function(self, node: ast.AST):
        """Nearest enclosing (async or sync) function def, else None."""
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return a
        return None

    def in_async_def(self, node: ast.AST) -> bool:
        """True when the nearest enclosing function is ``async def`` —
        i.e. the node runs on the event loop (a nested sync def is
        somebody's callback/executor target, judged separately)."""
        return isinstance(self.enclosing_function(node),
                          ast.AsyncFunctionDef)

    # -- emission ---------------------------------------------------------
    def finding(self, rule_id: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        snippet = (self.lines[line - 1].strip()
                   if 0 < line <= len(self.lines) else "")
        return Finding(rule=rule_id, path=self.path, line=line,
                       message=message, snippet=snippet)


# -- dotted-name helpers (shared by most rules) ------------------------------

def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None (calls, subscripts
    and other computed bases have no stable dotted form)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base is not None else None
    return None


def terminal(node: ast.AST) -> Optional[str]:
    """The last path segment: ``c`` for ``a.b.c``, ``x`` for ``x``,
    ``attr`` for ``<anything>.attr``."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def str_arg(call: ast.Call, i: int = 0) -> Optional[str]:
    """The i-th positional argument when it is a string literal."""
    if len(call.args) > i:
        a = call.args[i]
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value
    return None


# -- suppressions ------------------------------------------------------------

@dataclass
class _Suppression:
    rules: Tuple[str, ...]
    reason: str
    line: int          # line the suppression applies to
    comment_line: int  # line the comment itself sits on
    snippet: str
    used: bool = False


def _stmt_span(tree: ast.AST, line: int) -> Tuple[int, int]:
    """The line range of the innermost SIMPLE statement containing
    `line` (a multiline `x = jax.jit(\\n ...)` is one logical unit — a
    suppression anywhere on it covers findings anywhere on it).
    Compound statements don't count: a comment above a `def` must not
    blanket the whole body.  Falls back to the line itself."""
    best: Optional[Tuple[int, int]] = None
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt) \
                or isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef, ast.If, ast.For,
                                     ast.While, ast.With, ast.Try,
                                     ast.AsyncFor, ast.AsyncWith)):
            continue
        lo, hi = node.lineno, getattr(node, "end_lineno", node.lineno)
        if lo <= line <= hi and (best is None
                                 or hi - lo < best[1] - best[0]):
            best = (lo, hi)
    return best or (line, line)


def parse_suppressions(source: str, path: str,
                       tree: Optional[ast.AST] = None
                       ) -> Tuple[Dict[int, List[_Suppression]],
                                  List[Finding]]:
    """``# dynlint: disable=DYN001[,DYN004] <reason>`` — on the flagged
    statement, or standalone on the line(s) above it (stacked
    standalone disables all target the next code line).  A suppression
    covers the whole logical statement its target line belongs to, so
    trailing comments on continuation lines of a multiline expression
    work.  A missing reason is a DYN000 finding (not suppressible).
    Parsed from real COMMENT tokens (``tokenize``), so
    suppression-shaped text inside string literals — lint-test
    fixtures, docs — is never mistaken for one."""
    by_line: Dict[int, List[_Suppression]] = {}
    errors: List[Finding] = []
    lines = source.splitlines()
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [t for t in tokens if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, SyntaxError):  # pragma: no cover
        comments = []  # ast parsed it, so this is near-unreachable
    for tok in comments:
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        lineno, col = tok.start
        raw = lines[lineno - 1] if lineno <= len(lines) else tok.string
        rules = tuple(r.strip().upper() for r in m.group(1).split(",")
                      if r.strip())
        reason = (m.group(2) or "").strip()
        if not reason:
            errors.append(Finding(
                rule=SUPPRESS_NO_REASON, path=canon_path(path), line=lineno,
                message="dynlint suppression without a reason: write "
                        "`# dynlint: disable=DYNxxx <why this is safe>`",
                snippet=raw.strip()))
            continue
        standalone = raw[:col].strip() == ""
        target = lineno
        if standalone:
            # skip past further comment/blank lines: stacked standalone
            # disables all anchor on the next CODE line
            target += 1
            while target <= len(lines):
                nxt = lines[target - 1].strip()
                if nxt == "" or nxt.startswith("#"):
                    target += 1
                else:
                    break
        lo, hi = _stmt_span(tree, target) if tree is not None \
            else (target, target)
        sup = _Suppression(rules=rules, reason=reason, line=target,
                           comment_line=lineno, snippet=raw.strip())
        for covered in range(lo, hi + 1):
            by_line.setdefault(covered, []).append(sup)
    return by_line, errors


# -- run ---------------------------------------------------------------------

@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)   # actionable
    baselined: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    stale_baseline: List[str] = field(default_factory=list)  # unmatched keys
    errors: List[str] = field(default_factory=list)          # parse failures
    files: int = 0
    # what this run covered, for scope-aware baseline handling: the
    # canonical paths linted, and the canonical dir prefixes the given
    # roots enclose (stale detection and --write-baseline merging must
    # not touch entries outside them)
    linted: set = field(default_factory=set)
    scope_roots: Tuple[str, ...] = ()

    def in_scope(self, key_path: str) -> bool:
        return key_path in self.linted \
            or key_path.startswith(self.scope_roots)

    @property
    def ok(self) -> bool:
        return not self.findings and not self.stale_baseline \
            and not self.errors

    def to_dict(self) -> Dict[str, object]:
        return {
            "ok": self.ok,
            "files": self.files,
            "findings": [f.to_dict() for f in self.findings],
            "baselined": [f.to_dict() for f in self.baselined],
            "suppressed": len(self.suppressed),
            "stale_baseline": list(self.stale_baseline),
            "errors": list(self.errors),
        }


def check_module(mod: Module,
                 rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """All raw findings for one module (suppressions applied, baseline
    NOT applied — that is a run-level concern).  With the FULL rule set
    (rules=None), a suppression no finding matched is itself a DYN000
    finding — dead disables must not accumulate and silently mask a
    later reintroduction (the suppression analogue of the baseline's
    stale-entry rule).  Rule-restricted runs skip that check: most
    suppressions legitimately target unselected rules there."""
    from . import rules as _rules  # noqa: F401  (registers on import)

    selected = [RULES[r] for r in rules] if rules else list(RULES.values())
    raw: List[Finding] = []
    for rule in selected:
        if not rule.applies(mod.path):
            continue
        raw.extend(rule.check(mod))
    sup, sup_errors = parse_suppressions(mod.source, mod.path, mod.tree)
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in sorted(raw, key=lambda f: (f.line, f.rule)):
        hits = [s for s in sup.get(f.line, ()) if f.rule in s.rules]
        if hits:
            for s in hits:
                s.used = True
            suppressed.append(f)
        else:
            kept.append(f)
    kept.extend(sup_errors)
    if rules is None:
        seen_sups: List[_Suppression] = []
        for sups in sup.values():
            for s in sups:
                # one suppression covers a statement's whole line range
                # and is registered per covered line: judge it once
                if any(s is x for x in seen_sups):
                    continue
                seen_sups.append(s)
                if not s.used:
                    kept.append(Finding(
                        rule=SUPPRESS_NO_REASON, path=mod.path,
                        line=s.comment_line,
                        message="unused dynlint suppression: no "
                                f"{'/'.join(s.rules)} finding on its "
                                "target line — the code changed, delete "
                                "the comment (or re-point it)",
                        snippet=s.snippet))
    mod.suppressed_findings = suppressed  # type: ignore[attr-defined]
    return kept
