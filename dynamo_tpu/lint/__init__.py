"""dynlint — AST-based project lint enforcing this repo's shipped-bug
invariants.

Three of the worst bugs in this repo's history were invariant violations
invisible to pytest: a raw ``jax.jit`` in guided decoding that bypassed
the compile watchdog (PR 7), builtin ``hash()`` used for cross-process
token-replay identity (PR 4 — PYTHONHASHSEED broke migration), and
drain-marker literals duplicated across engines (PR 4 — a reword would
silently break real-engine migration while mocker tests stayed green).
The reference stack leans on rustc + clippy for this class of hot-path
contract enforcement; dynlint is the Python/JAX rebuild's equivalent —
each rule (DYN001–DYN010, rules.py) is distilled from a bug that
actually shipped, and the tier-1 gate (tests/test_lint.py) fails on any
new unsuppressed finding repo-wide.

Layout:
  core.py     — Module/Finding/registry, per-line suppression comments
                (``dynlint: disable=DYNxxx`` + a mandatory reason)
  rules.py    — the rule set
  baseline.py — grandfathered findings (stale entries fail the gate)
  cli.py      — ``python -m dynamo_tpu.lint [paths] [--json]``

Pure stdlib (``ast``); importing this package never imports jax, so the
lint runs anywhere the repo checks out.
"""

from .baseline import apply as apply_baseline
from .baseline import load as load_baseline
from .baseline import render as render_baseline
from .core import (
    RULES,
    Finding,
    LintResult,
    Module,
    check_module,
)
from .cli import main, run_paths
from . import rules as _rules  # noqa: F401  — populate RULES at import


def run_source(source: str, path: str = "dynamo_tpu/snippet.py",
               rules=None):
    """Lint one source string as if it lived at `path` (rule scoping is
    path-based) — the fixture-test entrypoint."""
    return check_module(Module(source, path), rules)


__all__ = [
    "RULES",
    "Finding",
    "LintResult",
    "Module",
    "apply_baseline",
    "check_module",
    "load_baseline",
    "main",
    "render_baseline",
    "run_paths",
    "run_source",
]
