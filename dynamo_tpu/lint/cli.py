"""dynlint CLI: ``python -m dynamo_tpu.lint [paths...] [--json]``.

Exit codes: 0 clean (suppressed/baselined findings are clean), 1 when
any new finding, reasonless suppression, stale baseline entry, or parse
failure exists, 2 on usage errors.  ``--json`` emits the machine form
(tests/test_lint.py smoke-tests it; CI diffing tools consume it).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Sequence

from . import baseline as baseline_mod
from .core import RULES, LintResult, Module, canon_path, check_module

DEFAULT_BASELINE = "dynlint.baseline"


def iter_py_files(paths: Sequence[str],
                  errors: Optional[List[str]] = None) -> List[str]:
    out: List[str] = []
    seen: set = set()  # realpaths: overlapping args (`. dynamo_tpu`)
    #                    must not lint a file twice — a duplicate
    #                    finding would escape the baseline's multiset
    for p in paths:
        if os.path.isfile(p):
            found = [p]
        else:
            found = []
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                found.extend(os.path.join(root, f) for f in sorted(files)
                             if f.endswith(".py"))
            if not found and errors is not None:
                # a typo'd or since-renamed path must not read as a
                # green gate: linting nothing is an error, not a clean
                # run
                errors.append(f"{p}: no Python files found "
                              "(missing or empty path)")
        for f in found:
            rp = os.path.realpath(f)
            if rp not in seen:
                seen.add(rp)
                out.append(f)
    return out


_NAMESPACES = ("dynamo_tpu/", "tests/", "benchmarks/")


def _scope_roots(paths: Sequence[str], linted: set) -> tuple:
    """The canonical dir prefixes this run's directory arguments
    enclose.  A marker-bearing argument (`dynamo_tpu/mocker`) covers
    exactly its own subtree; an unmarked enclosing root (`.`, an
    absolute repo path) covers every canonical namespace its walk
    actually produced files in — so `dynlint .` and `dynlint
    dynamo_tpu tests` make identical stale-baseline verdicts, while a
    subset run never declares out-of-subtree entries stale."""
    roots = []
    for p in paths:
        if os.path.isfile(p):
            continue
        c = canon_path(p).rstrip("/") + "/"
        if c.startswith(_NAMESPACES):
            roots.append(c)
        else:
            roots.extend(ns for ns in _NAMESPACES
                         if any(l.startswith(ns) for l in linted))
    return tuple(dict.fromkeys(roots))


def run_paths(paths: Sequence[str],
              baseline_path: Optional[str] = None,
              rules: Optional[Sequence[str]] = None) -> LintResult:
    """Lint every .py under `paths`; the library entrypoint the tier-1
    gate (tests/test_lint.py) and the CLI share."""
    res = LintResult()
    findings = []
    linted: set = set()
    for path in iter_py_files(paths, res.errors):
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
            mod = Module(src, path)
        except (OSError, SyntaxError, ValueError) as e:
            res.errors.append(f"{path}: {e}")
            continue
        res.files += 1
        linted.add(mod.path)
        findings.extend(check_module(mod, rules))
        res.suppressed.extend(getattr(mod, "suppressed_findings", ()))
    res.linted = linted
    res.scope_roots = _scope_roots(paths, linted)
    base = baseline_mod.load(baseline_path) if baseline_path else None
    if base:
        new, old, stale = baseline_mod.apply(findings, base)
        # stale detection only makes sense for entries this run could
        # have re-produced: a rule-restricted run emits only the
        # selected rules' findings, and a path-subset run only the
        # linted files' — flagging the rest "stale" would instruct the
        # developer to delete still-valid entries.  An entry is in
        # scope when its file was linted OR lives UNDER one of the
        # covered roots (a deleted file's entry must still go stale —
        # otherwise it lingers to grandfather a later regression in a
        # re-created file)
        if rules is not None:
            stale = []
        else:
            stale = [k for k in stale
                     if res.in_scope(baseline_mod.key_path(k))]
        res.findings, res.baselined, res.stale_baseline = new, old, stale
    else:
        res.findings = findings
        # a configured-but-empty baseline has nothing to go stale
    res.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return res


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.lint",
        description="dynlint: AST lint enforcing this repo's "
                    "shipped-bug invariants (DYN001-DYN010)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: dynamo_tpu tests, "
                         "when present in the cwd)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: ./{DEFAULT_BASELINE} "
                         "when it exists)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from current findings "
                         "and exit 0")
    ap.add_argument("--rule", action="append", dest="rules",
                    help="run only this rule id (repeatable)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule registry and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        from . import rules as _r  # noqa: F401

        for rid in sorted(RULES):
            r = RULES[rid]
            print(f"{rid}  {r.title}\n       bug: {r.bug}")
        return 0

    paths = args.paths or [p for p in ("dynamo_tpu", "tests")
                           if os.path.isdir(p)]
    if not paths:
        ap.error("no paths given and no dynamo_tpu/ or tests/ in cwd")
    if args.rules:
        from . import rules as _r  # noqa: F401

        args.rules = [r.upper() for r in args.rules]
        unknown = [r for r in args.rules if r not in RULES]
        if unknown:
            ap.error(f"unknown rule id(s) {unknown}; "
                     f"known: {sorted(RULES)}")
    baseline_path = args.baseline
    if baseline_path is None and os.path.exists(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE

    if args.write_baseline:
        if args.rules:
            # a baseline is a full-rule-set artifact: regenerating it
            # from a rule subset would silently delete every other
            # rule's grandfathered entries
            ap.error("--write-baseline cannot be combined with --rule")
        res = run_paths(paths, baseline_path=None)
        target = args.baseline or DEFAULT_BASELINE
        # merge, don't overwrite: entries OUTSIDE this run's scope (a
        # path-subset invocation) are preserved verbatim — only the
        # covered subtree's entries are regenerated
        from .core import SUPPRESS_NO_REASON

        existing = baseline_mod.load(target)
        kept = [k for k, n in sorted(existing.items())
                for _ in range(n)
                if not res.in_scope(baseline_mod.key_path(k))]
        new = [f.key for f in res.findings
               if f.rule != SUPPRESS_NO_REASON]
        with open(target, "w") as f:
            f.write(baseline_mod.HEADER
                    + "".join(k + "\n" for k in sorted(new + kept)))
        print(f"dynlint: wrote {len(new)} baseline entries to {target}"
              + (f" (kept {len(kept)} out-of-scope entries)"
                 if kept else ""))
        return 0

    res = run_paths(paths, baseline_path=baseline_path, rules=args.rules)
    if args.as_json:
        json.dump(res.to_dict(), sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        for f in res.findings:
            print(f.render())
        for key in res.stale_baseline:
            print(f"stale baseline entry (fixed? delete its line): {key}")
        for e in res.errors:
            print(f"parse error: {e}")
        print(f"dynlint: {len(res.findings)} finding(s) in {res.files} "
              f"file(s); {len(res.suppressed)} suppressed, "
              f"{len(res.baselined)} baselined, "
              f"{len(res.stale_baseline)} stale baseline entr(ies)")
    return 0 if res.ok else 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
