"""dynlint rules DYN001–DYN014: each one encodes a bug this repo really
shipped (the PR it came from is named per rule), turning a
found-late-by-review-or-live-fleet failure into a permanently-enforced
invariant.  The README "Static analysis" table is generated from the
``bug`` strings below.

Scoping: rules carry a path predicate.  ``dynamo_tpu/`` is library code
under full enforcement; ``tests/`` gets the rules whose bug class lives
in tests too (task leaks, seam/span typos, marker literals, swallowed
cancellation); CLI entrypoints (``__main__.py``, report/profiler) are
exempt from the print rule because printing is their job.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import Finding, Module, dotted, register, str_arg, terminal


def _in_pkg(path: str) -> bool:
    return path.startswith("dynamo_tpu/")


def _in_pkg_or_tests(path: str) -> bool:
    return path.startswith(("dynamo_tpu/", "tests/"))


def _walk_async_body(fn: ast.AsyncFunctionDef) -> Iterable[ast.AST]:
    """Nodes that execute ON THE EVENT LOOP inside this async def:
    descends expressions and control flow but not nested function defs
    (those are callbacks/executor targets, judged where they run)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# DYN001 — raw jax.jit / pjit outside the compile watchdog
# ---------------------------------------------------------------------------

_JIT_BASES = {"jax.jit", "jit", "pjit", "jax.experimental.pjit.pjit"}


@register(
    "DYN001",
    "raw jax.jit/pjit outside compile-watch wrapping",
    "PR 7: guided decoding's duplicate lazy top-k init went through a raw "
    "jax.jit that bypassed the compile watchdog — the measured 8-14s "
    "mid-serving guided-fork stall would have stayed invisible",
    applies=lambda p: _in_pkg(p) and p != "dynamo_tpu/obs/compile_watch.py"
    and not p.startswith("dynamo_tpu/lint/"))
def raw_jit(mod: Module) -> Iterable[Finding]:
    for node in ast.walk(mod.tree):
        d = dotted(node)
        if d not in _JIT_BASES:
            continue
        # bare-name matches must actually come from jax; `jit`/`pjit`
        # defined locally (a helper named jit) is not our business
        if isinstance(node, ast.Name) and not _imported_from_jax(mod,
                                                                 node.id):
            continue
        # references that are themselves the attr of a longer chain
        # (e.g. the `jax.jit` inside `jax.jit.lower`) are covered by the
        # outer node; only judge the full chain
        parent = mod.parent(node)
        if isinstance(parent, ast.Attribute):
            continue
        if _under_wrap_call(mod, node):
            continue
        yield mod.finding(
            "DYN001", node,
            "raw jax.jit/pjit: route it through "
            "obs/compile_watch.CompileWatch.wrap(...) so a mid-serving "
            "compile is observed (the PR 7 guided-topk blind spot)")


def _imported_from_jax(mod: Module, name: str) -> bool:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.split(".")[0] == "jax":
            if any(a.asname == name or (a.asname is None and a.name == name)
                   for a in node.names):
                return True
    return False


def _under_wrap_call(mod: Module, node: ast.AST) -> bool:
    """True when the jit reference is an argument (at any depth) of a
    ``<watch>.wrap(...)`` call — the sanctioned way to create one."""
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.Call) and terminal(anc.func) == "wrap":
            return True
    return False


# ---------------------------------------------------------------------------
# DYN002 — builtin hash() for identity
# ---------------------------------------------------------------------------

@register(
    "DYN002",
    "builtin hash() used for identity",
    "PR 4: the mocker's position-addressed token stream seeded from "
    "hash(request_id) — PYTHONHASHSEED randomizes it per process, so "
    "cross-process token-replay migration regenerated a different suffix; "
    "fixed to zlib.crc32",
    applies=_in_pkg)
def builtin_hash(mod: Module) -> Iterable[Finding]:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "hash":
            yield mod.finding(
                "DYN002", node,
                "builtin hash() is PYTHONHASHSEED-randomized per process "
                "— any value that crosses a process boundary (seeds, "
                "replay identity, cache keys) must use zlib.crc32 or "
                "tokens/hashing instead")


# ---------------------------------------------------------------------------
# DYN003 — metric family without the dynamo_ prefix
# ---------------------------------------------------------------------------

_METRIC_METHODS = {"counter", "gauge", "histogram", "inc", "observe",
                   "set", "set_gauge"}
_METRIC_CTORS = {"Counter", "Gauge", "Histogram", "Summary"}


@register(
    "DYN003",
    "metric family defined without the dynamo_ prefix",
    "PR 7: the scrape-contract test asserts every exported family is "
    "dynamo_-prefixed at runtime; this is its static twin, catching the "
    "definition site before a worker ever serves /metrics (PR 10 widened "
    "it to MetricsHierarchy.set so the fleet aggregator's dynamo_fleet_* "
    "gauge definitions are in scope)",
    applies=_in_pkg_or_tests)
def metric_prefix(mod: Module) -> Iterable[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = None
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _METRIC_METHODS:
            name = str_arg(node)
        elif terminal(node.func) in _METRIC_CTORS:
            name = str_arg(node)
        if name is None:
            continue
        # only judge strings that are plausibly prometheus family names
        # (.observe()/.inc() on non-metric objects take arbitrary args)
        if not name.replace("_", "").islower() or " " in name \
                or not name[:1].isalpha():
            continue
        if not name.startswith("dynamo_"):
            yield mod.finding(
                "DYN003", node,
                f"metric family {name!r} must carry the dynamo_ prefix "
                "(scrape contract: every exported family aggregates "
                "under one namespace)")


# ---------------------------------------------------------------------------
# DYN004 — blocking call lexically inside async def
# ---------------------------------------------------------------------------

_BLOCKING_DOTTED = {
    "time.sleep", "os.system",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
}


@register(
    "DYN004",
    "blocking call inside async def",
    "PR 7 class: the engine moved every device wait behind "
    "asyncio.to_thread because one synchronous fetch on the event loop "
    "stalls every live stream's frame egress at once",
    applies=_in_pkg)
def blocking_in_async(mod: Module) -> Iterable[Finding]:
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        for node in _walk_async_body(fn):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            t = terminal(node.func)
            msg = None
            if d in _BLOCKING_DOTTED:
                msg = f"{d}() blocks the event loop"
            elif isinstance(node.func, ast.Name) and node.func.id == "open":
                msg = ("sync file I/O on the event loop: open/read/write "
                       "via run_in_executor (or aiofiles-style helpers)")
            elif t == "block_until_ready":
                msg = ("block_until_ready() parks the loop on a device "
                       "sync; fetch via asyncio.to_thread")
            elif t == "result" and isinstance(node.func, ast.Attribute) \
                    and not node.args and not node.keywords:
                msg = (".result() on a future blocks (or raises "
                       "InvalidState); await it, or suppress with the "
                       "reason the future is known-done")
            if msg:
                yield mod.finding(
                    "DYN004", node,
                    f"{msg} — inside `async def {fn.name}` every "
                    "concurrent request stalls behind it")


# ---------------------------------------------------------------------------
# DYN005 — fire-and-forget task
# ---------------------------------------------------------------------------

@register(
    "DYN005",
    "asyncio task created and discarded",
    "PR 4: leaked tasks are how wedged-worker bugs hide — the conftest "
    "gate catches them at runtime per test; this catches the discarded "
    "reference at the creation site, library-wide",
    applies=_in_pkg_or_tests)
def discarded_task(mod: Module) -> Iterable[Finding]:
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        t = terminal(call.func)
        if t not in ("create_task", "ensure_future"):
            continue
        yield mod.finding(
            "DYN005", call,
            f"{t}(...) result discarded: the event loop holds only a "
            "weak reference — the task can be garbage-collected "
            "mid-flight and its exceptions are never observed; keep a "
            "reference (owner set + done-callback discard) or await it")


# ---------------------------------------------------------------------------
# DYN006 — seam / span-kind literal not in the central registry
# ---------------------------------------------------------------------------

def _registries():
    from .. import chaos, obs

    return chaos.SEAMS, set(chaos.ACTIONS), obs.SPAN_KINDS


@register(
    "DYN006",
    "chaos-seam / span-kind literal not in the central registry",
    "PR 4/6 class: a typo'd seam name is a chaos rule that silently never "
    "fires and a typo'd span kind is an orphan timeline row; "
    "chaos.SEAMS / obs.SPAN_KINDS are the single source of truth",
    applies=_in_pkg_or_tests)
def registry_literals(mod: Module) -> Iterable[Finding]:
    seams, actions, span_kinds = _registries()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        t = terminal(node.func)
        if d in ("chaos.hit", "chaos.ahit"):
            seam = str_arg(node)
            if seam is not None and seam not in seams:
                yield mod.finding(
                    "DYN006", node,
                    f"seam {seam!r} is not in chaos.SEAMS — this hit() "
                    "can never be targeted by a rule; register the seam "
                    "or fix the typo")
        elif t == "rule":
            seam, action = str_arg(node, 0), str_arg(node, 1)
            if seam is not None and action in actions \
                    and seam not in seams:
                yield mod.finding(
                    "DYN006", node,
                    f"seam {seam!r} is not in chaos.SEAMS — a rule on an "
                    "unregistered seam silently never fires")
        elif d in ("obs.span", "obs.end"):
            kind = str_arg(node)
            if kind is not None and kind not in span_kinds:
                yield mod.finding(
                    "DYN006", node,
                    f"span kind {kind!r} is not in obs.SPAN_KINDS — the "
                    "report and dashboards join on the registered "
                    "taxonomy; add the kind there or fix the typo")


# ---------------------------------------------------------------------------
# DYN007 — protocol marker literal written inline
# ---------------------------------------------------------------------------

def _drain_markers():
    from ..protocols import llm

    return {llm.DRAIN_REJECT: "protocols.DRAIN_REJECT",
            llm.DRAIN_ABORT: "protocols.DRAIN_ABORT"}


@register(
    "DYN007",
    "protocol marker literal inlined instead of imported",
    "PR 4: the drain markers were duplicated as string literals in both "
    "engines — a reword in one would silently break real-engine "
    "token-replay migration while mocker tests stayed green",
    applies=lambda p: _in_pkg_or_tests(p)
    and p != "dynamo_tpu/protocols/llm.py")
def inline_marker(mod: Module) -> Iterable[Finding]:
    markers = _drain_markers()
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Constant)
                and isinstance(node.value, str)):
            continue
        v = node.value
        name = markers.get(v)
        # dynlint: disable=DYN007 the rule's own prefix check, not an inline marker
        if name is None and v.startswith("worker draining:"):
            name = "protocols.DRAIN_REJECT/DRAIN_ABORT"
        if name is not None:
            yield mod.finding(
                "DYN007", node,
                f"inline copy of a protocol marker: import {name} — "
                "migratable-error classification substring-matches the "
                "canonical text, a reworded copy breaks it silently")


# ---------------------------------------------------------------------------
# DYN008 — swallowing cancellation in async code
# ---------------------------------------------------------------------------

@register(
    "DYN008",
    "bare except / except BaseException in async def without re-raise",
    "PR 4 class: a handler that eats CancelledError turns cooperative "
    "cancellation into a wedged task — exactly the shutdown/drain hangs "
    "the chaos suite exists to catch",
    applies=_in_pkg_or_tests)
def swallowed_cancellation(mod: Module) -> Iterable[Finding]:
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        for node in _walk_async_body(fn):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _catches_base_exception(node.type):
                continue
            if any(isinstance(n, ast.Raise)
                   for b in node.body for n in ast.walk(b)):
                continue
            what = ("bare `except:`" if node.type is None
                    else "`except BaseException`")
            yield mod.finding(
                "DYN008", node,
                f"{what} inside `async def {fn.name}` swallows "
                "CancelledError: the task can no longer be cancelled "
                "(wedged drains/shutdowns); re-raise, or catch Exception")


def _catches_base_exception(type_node) -> bool:
    """True for bare ``except:``, ``except BaseException`` and a tuple
    clause containing it (``except (OSError, BaseException)`` swallows
    CancelledError just the same)."""
    if type_node is None:
        return True
    if isinstance(type_node, ast.Tuple):
        return any(terminal(e) == "BaseException" for e in type_node.elts)
    return terminal(type_node) == "BaseException"


# ---------------------------------------------------------------------------
# DYN009 — KV tuple destructured at fixed arity 2
# ---------------------------------------------------------------------------

_KV_NAMES = {"kv", "kv_cache", "kv_pages", "kv_tuple"}


def _kv_name(node: ast.AST):
    t = terminal(node)
    if t is None:
        return None
    if t in _KV_NAMES or t.endswith("_kv"):
        return t
    return None


@register(
    "DYN009",
    "KV cache tuple destructured at fixed arity 2",
    "PR 3: the int8 cache rides as a (k, v, k_scale, v_scale) 4-tuple "
    "through the same pytree as the bf16 (k, v) 2-tuple; an unguarded "
    "`k, v = kv` silently drops the scale planes (or raises) the first "
    "time an int8 cache reaches it",
    applies=lambda p: p.startswith((
        "dynamo_tpu/engine/", "dynamo_tpu/ops/", "dynamo_tpu/models/",
        "dynamo_tpu/kvbm/", "dynamo_tpu/disagg/", "dynamo_tpu/quant/",
        "dynamo_tpu/mocker/", "dynamo_tpu/spec/")))
def kv_fixed_arity(mod: Module) -> Iterable[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Tuple) and len(tgt.elts) == 2
                and all(isinstance(e, ast.Name) for e in tgt.elts)):
            continue
        name = _kv_name(node.value)
        if name is None:
            continue
        fn = mod.enclosing_function(node)
        if fn is not None and _has_len_guard(fn, name):
            continue
        yield mod.finding(
            "DYN009", node,
            f"`{tgt.elts[0].id}, {tgt.elts[1].id} = {name}` assumes the "
            "bf16 2-tuple: int8 caches are (k, v, k_scale, v_scale) — "
            "guard on len() (quant/kv.py unpack_kv) or handle both "
            "arities")


def _has_len_guard(fn: ast.AST, name: str) -> bool:
    """The enclosing function tests len(<name>) somewhere — the
    quant/kv.py unpack idiom — so the 2-arity branch is deliberate."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "len" and len(node.args) == 1 \
                and terminal(node.args[0]) == name:
            return True
    return False


# ---------------------------------------------------------------------------
# DYN011 — blocking device sync in the scheduler hot path outside a
# device_wait span
# ---------------------------------------------------------------------------

# the scheduler hot path: every function in engine/core.py except the
# ones that run before serving or replay a leader's lockstep stream
_DYN011_EXEMPT_FNS = {"warmup_decode", "_init_kv_cache", "apply_step"}


def _dyn011_candidates(mod: Module):
    """Calls that force a host<->device synchronization: np.asarray(...)
    (the engine's canonical fetch), .block_until_ready(), .item()."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        t = terminal(node.func)
        if d in ("np.asarray", "numpy.asarray"):
            yield node, "np.asarray(...)"
        elif t == "block_until_ready":
            yield node, ".block_until_ready()"
        elif t == "item" and isinstance(node.func, ast.Attribute) \
                and not node.args and not node.keywords:
            yield node, ".item()"


def _stmt_of(mod: Module, node: ast.AST) -> ast.stmt:
    """The innermost statement containing `node`."""
    stmt = node
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.stmt):
            stmt = anc
            break
    return stmt


def _body_of(mod: Module, stmt: ast.stmt):
    """The statement list `stmt` sits in (its parent's matching block)."""
    parent = mod.parent(stmt)
    if parent is None:
        return None
    for field in ("body", "orelse", "finalbody"):
        block = getattr(parent, field, None)
        if isinstance(block, list) and stmt in block:
            return block
    return None


def _in_device_wait_span(mod: Module, node: ast.AST) -> bool:
    """True when the call follows the sanctioned idiom in its OWN
    statement block:

        t = obs.begin()
        <the blocking fetch>
        obs.end("device_wait", t, ...)

    i.e. an obs.begin() assignment somewhere before it and an
    obs.end("device_wait", ...) somewhere after it, both at the same
    block depth — so the fetch's wall time is attributed to the
    device_wait phase the gap report scores."""
    stmt = _stmt_of(mod, node)
    block = _body_of(mod, stmt)
    if block is None:
        return False
    idx = block.index(stmt)
    begin_before = any(
        isinstance(s, ast.Assign) and isinstance(s.value, ast.Call)
        and dotted(s.value.func) == "obs.begin"
        for s in block[:idx])
    end_after = any(
        isinstance(s, ast.Expr) and isinstance(s.value, ast.Call)
        and dotted(s.value.func) == "obs.end"
        and str_arg(s.value) == "device_wait"
        for s in block[idx + 1:])
    return begin_before and end_after


@register(
    "DYN011",
    "blocking device sync in the scheduler hot path outside a "
    "device_wait span",
    "PR 11 class: the overlapped scheduler only works if the hot path's "
    "sole blocking points are the deliberate, span-attributed readbacks "
    "— one stray np.asarray/.item()/block_until_ready silently "
    "re-serializes host and device AND the stall is invisible to the "
    "gap report that exists to catch it",
    applies=lambda p: p == "dynamo_tpu/engine/core.py")
def blocking_sync_in_hot_path(mod: Module) -> Iterable[Finding]:
    for node, what in _dyn011_candidates(mod):
        fn = mod.enclosing_function(node)
        if fn is not None and fn.name in _DYN011_EXEMPT_FNS:
            continue
        if _in_device_wait_span(mod, node):
            continue
        yield mod.finding(
            "DYN011", node,
            f"{what} in the scheduler hot path forces a device sync "
            "outside a device_wait span: wrap it in the t=obs.begin() / "
            "obs.end(\"device_wait\", t, ...) idiom so the stall is "
            "attributed (and deliberate), or move the readback behind "
            "the overlap machinery (_pending_first / _inflight)")


# ---------------------------------------------------------------------------
# DYN010 — print() in library code
# ---------------------------------------------------------------------------

_PRINT_OK = (
    "__main__.py",                 # CLI entrypoints print by design
    "dynamo_tpu/obs/report.py",    # report CLIs
    "dynamo_tpu/obs/fleet.py",     # fleet snapshot CLI
    "dynamo_tpu/profiler/",
    "dynamo_tpu/loadgen/",
    "dynamo_tpu/lint/cli.py",      # the lint's own CLI output
)


def _print_applies(path: str) -> bool:
    if not _in_pkg(path):
        return False
    return not any(path.endswith(s) or path.startswith(s)
                   for s in _PRINT_OK)


@register(
    "DYN010",
    "print() in library code",
    "observability-plane class: a print bypasses runtime/logging — no "
    "level, no trace_id stamp (PR 7's log<->span join), invisible to "
    "log-based alerting; workers' stdout is not a log pipeline",
    applies=_print_applies)
def print_in_library(mod: Module) -> Iterable[Finding]:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "print":
            yield mod.finding(
                "DYN010", node,
                "print() in library code: use runtime/logging (levels, "
                "TraceIdFilter correlation) — stdout is not scraped")


# ---------------------------------------------------------------------------
# DYN012 — forensics hop-kind literal not in the central registry
# ---------------------------------------------------------------------------

def _hop_kinds():
    from .. import obs

    return obs.HOP_KINDS


@register(
    "DYN012",
    "forensics hop-kind literal not in obs.HOP_KINDS",
    "forensics-plane twin of DYN006: a typo'd hop name would be an orphan "
    "timeline row the phase partition and the tail autopsy silently never "
    "join on; obs.HOP_KINDS is the single source of truth",
    applies=_in_pkg_or_tests)
def hop_literals(mod: Module) -> Iterable[Finding]:
    kinds = _hop_kinds()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or terminal(node.func) != "hop":
            continue
        kind = str_arg(node)
        if kind is not None and kind not in kinds:
            yield mod.finding(
                "DYN012", node,
                f"hop kind {kind!r} is not in obs.HOP_KINDS — the exact "
                "phase partition and the tail autopsy join on the "
                "registered taxonomy; register the kind (and its "
                "docstring-table row) or fix the typo")


# ---------------------------------------------------------------------------
# DYN013 — allocator/pool book mutation outside the defining module
# ---------------------------------------------------------------------------

# the ledgered private books: BlockAllocator's refcount/free-list/hash
# maps, the KVBM pools' manifests, and the mocker sim's hash books —
# each mutable ONLY inside its defining module, where every transition
# is mirrored onto the KV ledger (obs/kv_ledger.py)
_BOOK_ATTRS = {
    "_block_ref", "_hash_to_block", "_block_hash", "_seq_blocks",
    "_free", "_lru",            # engine/block_allocator.py
    "_blocks", "_order",        # kvbm/pools.py
    "_ref", "_seq_full", "_seq_partial",  # mocker/kv_cache_sim.py
}
_BOOK_MODULES = (
    "dynamo_tpu/engine/block_allocator.py",
    "dynamo_tpu/kvbm/pools.py",
    "dynamo_tpu/mocker/kv_cache_sim.py",
)
_MUTATORS = {
    "append", "pop", "popitem", "clear", "insert", "extend", "remove",
    "update", "setdefault", "move_to_end", "add", "discard",
}


def _book_attr(node: ast.AST):
    """The `x._book` Attribute inside `node` being written through, if
    any: the node itself, or the value of a Subscript store target."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr in _BOOK_ATTRS:
        return node
    return None


@register(
    "DYN013",
    "allocator/pool book mutated outside its defining module",
    "kv-ledger plane (obs/kv_ledger.py): the ledger mirrors every "
    "BlockAllocator/pool/sim book transition at its definition site — a "
    "mutation anywhere else is invisible to the books and IS the silent "
    "leak/double-free/orphan class the auditor exists to catch",
    applies=lambda p: _in_pkg_or_tests(p) and p not in _BOOK_MODULES)
def book_mutation(mod: Module) -> Iterable[Finding]:
    def _flag(attr_node: ast.AST, how: str):
        return mod.finding(
            "DYN013", attr_node,
            f"{how} of `{attr_node.attr}` outside its defining module: "
            "the KV ledger mirrors these books at their definition "
            "sites only (engine/block_allocator.py, kvbm/pools.py, "
            "mocker/kv_cache_sim.py) — mutate through the owning "
            "class's API, or the accounting drifts silently")

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                a = _book_attr(t)
                if a is not None:
                    yield _flag(a, "assignment")
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                a = _book_attr(t)
                if a is not None:
                    yield _flag(a, "del")
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            a = _book_attr(node.func.value)
            if a is not None:
                yield _flag(a, f".{node.func.attr}()")


# ---------------------------------------------------------------------------
# DYN014 — raw np.load/np.savez of KV block payloads
# ---------------------------------------------------------------------------

_NPZ_CALLS = {
    "np.load", "numpy.load", "np.savez", "numpy.savez",
    "np.savez_compressed", "numpy.savez_compressed",
}
# the sanctioned readers/writers live in kvbm/pools.py (_save_block /
# _load_block / read_block_file, the only code allowed to touch the npz
# layer directly — it is what stamps and verifies the crc32 footer);
# multimodal/encoder.py decodes MEDIA tensors from the wire, not KV
# block payloads, so the checksummed-block contract does not apply
_NPZ_EXEMPT = (
    "dynamo_tpu/kvbm/pools.py",
    "dynamo_tpu/multimodal/encoder.py",
)


@register(
    "DYN014",
    "raw np.load/np.savez outside the checksummed block helpers",
    "PR 20: persisted/transferred KV blocks carry a crc32 footer verified "
    "at every tier-crossing consume — a direct np.load/np.savez of a "
    "block payload bypasses both the stamp and the verify, re-creating "
    "the unchecksummed blobs the integrity plane exists to retire",
    applies=lambda p: _in_pkg(p) and p not in _NPZ_EXEMPT)
def raw_npz(mod: Module) -> Iterable[Finding]:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if d not in _NPZ_CALLS:
            continue
        yield mod.finding(
            "DYN014", node,
            f"direct {d}() of a block payload bypasses the crc32 "
            "stamp/verify: persist through kvbm/pools._save_block and "
            "consume through _load_block/read_block_file (+verify_block) "
            "so a corrupt blob quarantines instead of serving bytes")
