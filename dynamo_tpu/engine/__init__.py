from .block_allocator import BlockAllocator
from .config import EngineConfig
from .core import JaxEngine

__all__ = ["BlockAllocator", "EngineConfig", "JaxEngine"]
