"""On-device token sampling: greedy / temperature / top-k / top-p.

Runs inside the jitted decode/prefill step so only the sampled token ids
cross back to the host.  All parameters are per-slot arrays so one compiled
program serves heterogeneous batches (mixing greedy and sampled requests).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def sample_tokens(
    logits: jax.Array,        # [B, vocab] fp32
    seeds: jax.Array,         # [B] int32 per-request seed
    steps: jax.Array,         # [B] int32 decode step counter (rng stream)
    temperature: jax.Array,   # [B] fp32; <=0 means greedy
    top_k: jax.Array,         # [B] int32; 0 disables
    top_p: jax.Array,         # [B] fp32; >=1 disables
) -> jax.Array:
    """Returns sampled token ids [B]."""
    B, V = logits.shape

    def one(lg, seed, step, temp, tk, tp):
        greedy = jnp.argmax(lg)

        def do_sample(_):
            key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
            scaled = lg / jnp.maximum(temp, 1e-6)
            # sort once; both top-k and top-p masks come from the sorted view
            sorted_lg = jnp.sort(scaled)[::-1]
            ranks = jnp.argsort(jnp.argsort(-scaled))  # rank of each token
            # top-k mask
            k_eff = jnp.where(tk > 0, tk, V)
            keep_k = ranks < k_eff
            # top-p (nucleus) mask over the sorted distribution
            probs_sorted = jax.nn.softmax(sorted_lg)
            cum = jnp.cumsum(probs_sorted)
            # keep the smallest set with cumulative prob >= top_p; the first
            # token is always kept
            keep_sorted = jnp.concatenate(
                [jnp.array([True]), cum[:-1] < tp]
            )
            keep_p = keep_sorted[ranks]
            masked = jnp.where(keep_k & keep_p, scaled, NEG_INF)
            return jax.random.categorical(key, masked)

        return jax.lax.cond(temp <= 0.0, lambda _: greedy, do_sample,
                            operand=None)

    return jax.vmap(one)(logits, seeds, steps, temperature, top_k, top_p)


def apply_penalties(
    logits: jax.Array,          # [B, vocab]
    token_counts: jax.Array,    # [B, vocab] int32: counts in generated output
    frequency_penalty: jax.Array,  # [B]
    presence_penalty: jax.Array,   # [B]
) -> jax.Array:
    lf = logits
    lf = lf - frequency_penalty[:, None] * token_counts.astype(jnp.float32)
    lf = lf - presence_penalty[:, None] * (token_counts > 0).astype(jnp.float32)
    return lf
