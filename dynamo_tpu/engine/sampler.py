"""On-device token sampling: greedy / temperature / top-k / top-p.

Runs inside the jitted decode/prefill step so only the sampled token ids
cross back to the host.  All parameters are per-slot arrays so one compiled
program serves heterogeneous batches (mixing greedy and sampled requests).

Candidate-capped design: sampling is restricted to the CAP (64) highest
logits per slot.  A full-vocab sort per token (3 sorts of 128k on Llama-3
vocab) measured ~40% of the whole decode burst on v5e; lax.top_k over a
64-candidate window costs ~nothing and is the standard serving
approximation (requested top_k is clamped to CAP; top-p nucleus mass is
computed against the TRUE full softmax via logsumexp, truncated to the
window, so small-p nuclei are exact and only a pathological p over a
near-uniform distribution feels the cap).
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30

#: sampling candidate window (max effective top-k)
CAP = 64


def sample_tokens(
    logits: jax.Array,        # [B, vocab] fp32
    seeds: jax.Array,         # [B] int32 per-request seed
    steps: jax.Array,         # [B] int32 decode step counter (rng stream)
    temperature: jax.Array,   # [B] fp32; <=0 means greedy
    top_k: jax.Array,         # [B] int32; 0 disables
    top_p: jax.Array,         # [B] fp32; >=1 disables
) -> jax.Array:
    """Returns sampled token ids [B]."""

    def one(lg, seed, step, temp, tk, tp):
        greedy = jnp.argmax(lg)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        scaled = lg / jnp.maximum(temp, 1e-6)
        vals, idx = jax.lax.top_k(scaled, CAP)     # sorted descending
        k_eff = jnp.clip(jnp.where(tk > 0, tk, CAP), 1, CAP)
        keep_k = jnp.arange(CAP) < k_eff
        # nucleus mass against the TRUE distribution (full-vocab logsumexp,
        # no sort); first candidate always kept
        probs = jnp.exp(vals - jax.scipy.special.logsumexp(scaled))
        cum = jnp.cumsum(probs)
        keep_p = jnp.concatenate([jnp.array([True]), cum[:-1] < tp])
        masked = jnp.where(keep_k & keep_p, vals, NEG_INF)
        sampled = idx[jax.random.categorical(key, masked)]
        return jnp.where(temp <= 0.0, greedy, sampled)

    return jax.vmap(one)(logits, seeds, steps, temperature, top_k, top_p)


def greedy_tokens(logits: jax.Array) -> jax.Array:
    """Argmax-only fast path: the engine dispatches this specialization when
    every slot in the batch is greedy (temperature <= 0), skipping the
    sampling machinery entirely."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# speculative decoding: host-side rejection sampling (spec/)
#
# The verify program (engine/core.py _spec_verify_impl) returns, per packed
# position, the top-CAP candidate ids + temperature-scaled logits and the
# full-vocab logsumexp of the scaled logits.  From those three arrays the
# host reconstructs EXACTLY the masked-window categorical `sample_tokens`
# draws from (same CAP window, same top-k clamp, same true-softmax top-p
# nucleus), so acceptance decisions are made against the real target
# distribution, not an approximation of it.
#
# Proposals are point masses (greedy n-gram / greedy draft model), so the
# Leviathan rejection rule specializes to: accept draft d with probability
# p(d); on rejection, sample from p with d's mass removed, renormalized.
# The emitted marginal is p(d)*1[x=d] + (1-p(d)) * p(x)*1[x!=d]/(1-p(d))
# = p(x) — the target distribution exactly, per position.  Greedy
# (temperature <= 0) degenerates to exact argmax-prefix matching, so the
# speculative stream is token-identical to plain greedy decode.
# ---------------------------------------------------------------------------


def spec_window_weights(vals: np.ndarray, lse: float, top_k: int,
                        top_p: float) -> np.ndarray:
    """Normalized target weights over the CAP candidate window — the same
    masking sample_tokens applies on device.  vals: [CAP] scaled logits
    sorted descending; lse: logsumexp of the full scaled logits."""
    probs = np.exp(vals.astype(np.float64) - float(lse))
    k_eff = int(np.clip(top_k if top_k > 0 else CAP, 1, CAP))
    keep = np.arange(CAP) < k_eff
    cum = np.cumsum(probs)
    keep &= np.concatenate(([True], cum[:-1] < top_p))
    w = np.where(keep, probs, 0.0)
    s = w.sum()
    if s <= 0.0:  # fp underflow corner: the argmax candidate stands alone
        w = np.zeros(CAP)
        w[0] = 1.0
        return w
    return w / s


def spec_accept_tokens(
    ids: np.ndarray,      # [n, CAP] candidate ids per position, sorted
    vals: np.ndarray,     # [n, CAP] scaled logits per position
    lse: np.ndarray,      # [n] full-vocab logsumexp of scaled logits
    drafts: List[int],    # k point-mass proposals (n == k + 1)
    *,
    greedy: bool,
    top_k: int,
    top_p: float,
    rng: np.random.Generator,
) -> Tuple[int, List[int]]:
    """Verify k drafted tokens against the target's per-position window
    distributions.  Returns (accepted_count, emitted_tokens): the
    accepted draft prefix plus exactly ONE more token — the corrected
    sample at the first rejection, or the bonus token from the position
    after the last draft when everything was accepted."""
    emitted: List[int] = []
    for i, d in enumerate(drafts):
        if greedy:
            t = int(ids[i, 0])
            if t == d:
                emitted.append(d)
                continue
            emitted.append(t)
            return i, emitted
        w = spec_window_weights(vals[i], lse[i], top_k, top_p)
        j = np.nonzero(ids[i] == d)[0]
        p_d = float(w[j[0]]) if len(j) else 0.0
        if rng.random() < p_d:
            emitted.append(d)
            continue
        if len(j):
            w[j[0]] = 0.0
        s = w.sum()
        if s <= 0.0:
            # the target was itself a point mass at d and the float
            # comparison still rejected: d IS the sample
            emitted.append(d)
            continue
        emitted.append(int(ids[i, rng.choice(CAP, p=w / s)]))
        return i, emitted
    # every draft accepted: bonus token from the last scored position
    i = len(drafts)
    if greedy:
        emitted.append(int(ids[i, 0]))
    else:
        w = spec_window_weights(vals[i], lse[i], top_k, top_p)
        emitted.append(int(ids[i, rng.choice(CAP, p=w)]))
    return len(drafts), emitted


def apply_penalties(
    logits: jax.Array,          # [B, vocab]
    token_counts: jax.Array,    # [B, vocab] int32: counts in generated output
    frequency_penalty: jax.Array,  # [B]
    presence_penalty: jax.Array,   # [B]
) -> jax.Array:
    lf = logits
    lf = lf - frequency_penalty[:, None] * token_counts.astype(jnp.float32)
    lf = lf - presence_penalty[:, None] * (token_counts > 0).astype(jnp.float32)
    return lf
