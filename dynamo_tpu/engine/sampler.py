"""On-device token sampling: greedy / temperature / top-k / top-p.

Runs inside the jitted decode/prefill step so only the sampled token ids
cross back to the host.  All parameters are per-slot arrays so one compiled
program serves heterogeneous batches (mixing greedy and sampled requests).

Candidate-capped design: sampling is restricted to the CAP (64) highest
logits per slot.  A full-vocab sort per token (3 sorts of 128k on Llama-3
vocab) measured ~40% of the whole decode burst on v5e; lax.top_k over a
64-candidate window costs ~nothing and is the standard serving
approximation (requested top_k is clamped to CAP; top-p nucleus mass is
computed against the TRUE full softmax via logsumexp, truncated to the
window, so small-p nuclei are exact and only a pathological p over a
near-uniform distribution feels the cap).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30

#: sampling candidate window (max effective top-k)
CAP = 64


def sample_tokens(
    logits: jax.Array,        # [B, vocab] fp32
    seeds: jax.Array,         # [B] int32 per-request seed
    steps: jax.Array,         # [B] int32 decode step counter (rng stream)
    temperature: jax.Array,   # [B] fp32; <=0 means greedy
    top_k: jax.Array,         # [B] int32; 0 disables
    top_p: jax.Array,         # [B] fp32; >=1 disables
) -> jax.Array:
    """Returns sampled token ids [B]."""

    def one(lg, seed, step, temp, tk, tp):
        greedy = jnp.argmax(lg)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        scaled = lg / jnp.maximum(temp, 1e-6)
        vals, idx = jax.lax.top_k(scaled, CAP)     # sorted descending
        k_eff = jnp.clip(jnp.where(tk > 0, tk, CAP), 1, CAP)
        keep_k = jnp.arange(CAP) < k_eff
        # nucleus mass against the TRUE distribution (full-vocab logsumexp,
        # no sort); first candidate always kept
        probs = jnp.exp(vals - jax.scipy.special.logsumexp(scaled))
        cum = jnp.cumsum(probs)
        keep_p = jnp.concatenate([jnp.array([True]), cum[:-1] < tp])
        masked = jnp.where(keep_k & keep_p, vals, NEG_INF)
        sampled = idx[jax.random.categorical(key, masked)]
        return jnp.where(temp <= 0.0, greedy, sampled)

    return jax.vmap(one)(logits, seeds, steps, temperature, top_k, top_p)


def greedy_tokens(logits: jax.Array) -> jax.Array:
    """Argmax-only fast path: the engine dispatches this specialization when
    every slot in the batch is greedy (temperature <= 0), skipping the
    sampling machinery entirely."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def apply_penalties(
    logits: jax.Array,          # [B, vocab]
    token_counts: jax.Array,    # [B, vocab] int32: counts in generated output
    frequency_penalty: jax.Array,  # [B]
    presence_penalty: jax.Array,   # [B]
) -> jax.Array:
    lf = logits
    lf = lf - frequency_penalty[:, None] * token_counts.astype(jnp.float32)
    lf = lf - presence_penalty[:, None] * (token_counts > 0).astype(jnp.float32)
    return lf
