"""Engine configuration."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

from ..disagg.transfer import DEFAULT_CHUNK_BYTES
from ..models import PRESETS
from ..parallel.mesh import MeshConfig


@dataclass
class EngineConfig:
    model: str = "tiny"  # preset name (models.PRESETS, all families)
    model_config: Optional[object] = None  # LlamaConfig | DeepseekConfig
    model_name: str = ""  # served model name; defaults to preset name
    # local HF checkpoint dir (config.json + *.safetensors + tokenizer);
    # when set it overrides `model` and the engine serves real weights
    model_path: str = ""

    # paged KV cache.  Default block_size is 128 (lane-aligned) so the
    # Pallas decode kernel's auto-dispatch engages on TPU; CPU/test configs
    # pass smaller blocks and take the jnp path.
    block_size: int = 128         # tokens per block == PLH hashing block size
    num_blocks: int = 128         # physical blocks (id 0 is garbage)
    max_blocks_per_seq: int = 64  # max context = block_size * this
    enable_prefix_caching: bool = True
    # KV cache storage dtype (quant/kv.py): "bf16" stores the model dtype
    # (the pre-quantization behavior, byte-identical); "int8" stores
    # symmetric per-(layer, kv_head, block, position) quantized K/V with
    # fp32 scale planes riding as sibling arrays — roughly half the HBM
    # bytes per token, so the decode read streams half the traffic and a
    # fixed budget holds ~1.9x the blocks.  Families without a quantized
    # path (MLA) auto-fall back to bf16 with a warning, following the
    # MLA/MoE fallback precedent; the worker MDC advertises the EFFECTIVE
    # dtype.  Quantized payloads ride disagg transfer and the KVBM tiers
    # as int8 + scales (half the wire/host bytes too).
    kv_cache_dtype: str = "bf16"
    # KV HBM budget in GB: when > 0, num_blocks is DERIVED from the
    # bytes-per-block of the resolved model at the effective
    # kv_cache_dtype (quant/kv.py blocks_for_hbm_budget), so switching
    # bf16 -> int8 at a fixed budget yields ~2x blocks instead of the
    # same block count at half the memory.  0 keeps num_blocks as given.
    kv_hbm_gb: float = 0.0
    # KV block-lifecycle ledger + invariant auditor (obs/kv_ledger.py):
    # None = follow DYN_KV_LEDGER (always-on by default, "0" disables);
    # True/False pins the plane per engine — bench_serving's
    # --kv-ledger ab uses this to A/B the overhead in one invocation.
    kv_ledger: Optional[bool] = None

    # batching
    max_num_seqs: int = 8

    # decode burst: fuse this many decode steps into ONE compiled program
    # (lax.scan) when no prefill/admission work is pending.  Dispatch
    # overhead dominates the single-step hot loop on this platform; fusing
    # amortizes it k-fold at the cost of k-token output bursts and up to
    # k-1 wasted steps when a sequence finishes mid-burst.  1 disables.
    decode_fused_steps: int = 8
    # decode output pipelining: keep up to depth-1 dispatched bursts
    # UNREAD while the next one runs, chaining sampled ids on device — the
    # host fetch of burst N then overlaps bursts N+1..N+depth-1's compute
    # instead of stalling on device/tunnel sync every burst.  Emission and
    # stop detection lag by up to (depth-1)*decode_fused_steps tokens
    # (overshoot is discarded, same as a mid-burst finish).  1 = fetch
    # synchronously every burst.  Depth d gives the async device->host
    # copy d-1 burst intervals to land before the host reads it; measured
    # on the tunneled v5e, served throughput plateaus at depth 4 (~80% of
    # the raw on-device loop).  Latency-sensitive deployments can trade
    # throughput for (d-1)*decode_fused_steps fewer tokens of stream lag.
    # Only effective with overlap_scheduling on; sync mode is lockstep
    # (depth 1 and drain-after-dispatch) regardless of this value.
    decode_pipeline_depth: int = 4
    # overlapped scheduler (the ROADMAP item-3 refactor): while step N's
    # programs execute on device, the host schedules and enqueues step
    # N+1 — decode bursts pipeline to decode_pipeline_depth, a completing
    # prefill chunk's first-token readback is DEFERRED one step (the
    # device_wait then pays only for the previous step's work, and
    # streaming emission is one step late for exactly that first token),
    # and host scheduling done while the device is busy is attributed to
    # the `enqueue_ahead` span instead of `sched` (obs/report.py keeps
    # the wall partition exact; sched_overhead_frac counts only host
    # time the device actually waited on).  False = lockstep reference
    # mode: schedule -> dispatch -> block on device -> emit, greedy
    # byte-identical to overlapped mode by construction (the test matrix
    # in tests/test_overlap.py asserts it, including cancellation, chaos
    # and drain).
    overlap_scheduling: bool = True
    # adaptive decode fusion: in a decode-only stretch the burst size
    # ramps INTERLEAVE_BURST -> 2x -> ... -> decode_fused_steps (one
    # compiled variant per ladder rung, all warmed by warmup_decode) and
    # de-fuses back to the interleave burst the step a new arrival,
    # cancellation, or pending prefill chunk appears — so steady-state
    # throughput gets the full fusion while TTFT under arrivals is
    # bounded by a short burst.  False = the pre-adaptive policy (full
    # decode_fused_steps whenever no prefill/admission work is pending).
    decode_fuse_adaptive: bool = True
    # SLA-aware admission (closes the PR 1 mixed-scheduling loop against
    # the PR 7 SLO plane): when the frontend-published error-budget burn
    # rate (obs/slo.py; worst window, fed to the engine by the worker's
    # slo_metrics subscription) exceeds this threshold while decodes are
    # active, the per-step prefill chunk budget is scaled down by
    # threshold/burn (floored at the smallest prefill bucket) — prefill
    # chunks yield to decode until ITL recovers.  0 disables.
    slo_yield_burn: float = 1.0
    # a burn signal older than this is ignored (frontend gone / SLO
    # plane off must not keep throttling prefill forever)
    slo_burn_stale_s: float = 10.0
    prefill_buckets: Tuple[int, ...] = (32, 64, 128, 256, 512, 1024, 2048)
    # per-scheduler-step token budget: one prefill chunk is capped to
    # max_batch_tokens minus one token per decoding slot, so decode ITL is
    # bounded by a single chunk's compute (vLLM chunked-prefill semantics)
    max_batch_tokens: int = 2048
    # concurrent-arrival prefill: up to this many prefilling sequences run
    # their chunks in ONE batched program per scheduler step (the token
    # budget is split across them).  Short prompts that would each waste
    # most of max_batch_tokens fill it together, so TTFT under queue depth
    # does not serialize.  1 disables batching (always the B=1 program).
    max_prefill_seqs: int = 4
    # packed chunked prefill (engine/prefill.py + ops/packed_prefill.py):
    # co-scheduled prompts/chunks concatenate into one padding-free token
    # stream with segment ids instead of padding each row to a bucket.
    # Auto-falls back to the padded paths for families without
    # prefill_packed (MLA) and for capacity-dispatch MoE (segments must
    # not share an expert-capacity pool).
    prefill_packed: bool = True
    # chunk budget for one packed prefill dispatch (the chunk-budget knob:
    # bounds how long a prefill program can hold decode back, so decode
    # ITL during a prefill burst is capped by one chunk's compute).
    # 0 = use max_batch_tokens.
    prefill_chunk_tokens: int = 0
    # decode attention impl override ("" = keep the model family's
    # default): "auto" | "pallas" | "pallas_interpret" | "jnp" |
    # "jnp_bf16" — the ops/paged_attention.py dispatch.  Every choice
    # accepts int8 caches (the Pallas kernel dequantizes in-kernel);
    # "pallas_interpret" exists for CPU testing.  Replaces the resolved
    # model config's attn_impl field, so a preset model can take the
    # kernel per worker without a custom model_config.
    attn_impl: str = ""
    # packed-prefill attention impl override ("" = family default):
    # "auto"/"xla" (the masked XLA reference, S-fold attention FLOPs)
    # | "pallas"/"pallas_interpret" (the tile-skip kernel,
    # ops/pallas_packed_prefill.py).  Also selects the kernel for
    # spec_verify, which rides the same packed path.
    packed_attn_impl: str = ""
    # fused sampling/top-k epilogue (ops/fused_sampling.py): "fused"
    # streams the decode final projection in vocab tiles and emits only
    # sampled token ids — the [B, vocab] fp32 logits tensor never
    # round-trips HBM on the decode / fused-decode-ladder paths (byte-
    # identical at greedy, distribution-identical seeded sampling).
    # "off" keeps the reference path (materialized logits ->
    # engine/sampler.py), which remains the fallback for families
    # without a hidden-state decode surface (MLA) — those fall back
    # with a warning, like the int8-KV precedent, and the worker MDC
    # advertises the EFFECTIVE mode.
    sampling_epilogue: str = "off"
    # accelerator peak (dense bf16) TFLOP/s, for prefill-phase MFU in the
    # FPM stream (v5e: 197).  0 = unknown; MFU omitted from records.
    peak_tflops: float = 0.0
    # accelerator peak HBM bandwidth in GB/s, for the roofline plane's
    # memory-bandwidth-utilization gauges (v5e: 819).  The cost-analysis
    # bytes-accessed of each compiled program (obs/compile_watch.py)
    # over the dispatch gap gives MBU — the binding axis for decode,
    # which MFU alone cannot show.  0 = unknown; MBU gauges omitted.
    peak_hbm_gbps: float = 0.0

    # speculative decoding (spec/): emit more than one ACCEPTED token per
    # weight/KV pass once decode is memory-bandwidth-bound.  "ngram" is
    # the zero-weight prompt-lookup proposer (drafts from the sequence's
    # own history; free when it doesn't match); "draft" runs a second,
    # smaller model on the same mesh (greedy k-step drafts via fused
    # decode_multi; single-host slices only in v1).  Verification scores
    # all speculating sequences' drafts in ONE packed segment-id program
    # (spec_verify, reusing ops/packed_prefill.py attention) and accepts
    # via rejection sampling that provably preserves the decode sampler's
    # distribution — greedy output is token-identical to plain decode.
    # Guided/JSON-constrained requests, LoRA sequences, and MLA families
    # always fall back to plain decode.  "off" disables.
    spec_decode: str = "off"
    # max draft tokens per speculation round.  The effective per-sequence
    # draft length adapts BELOW this via an acceptance-rate EMA — down to
    # 0 (= plain pipelined decode) when speculation stops paying, with a
    # probe every spec_probe_interval generated tokens to re-engage.
    spec_k: int = 4
    # n-gram proposer: suffix lengths tried for the history match,
    # longest (strongest signal) first
    spec_ngram_max: int = 3
    spec_ngram_min: int = 1
    # draft model, first match wins: explicit config object (tests) >
    # HF checkpoint dir > preset name.  Vocab must equal the target's.
    spec_draft_config: Optional[object] = None
    spec_draft_model_path: str = ""
    spec_draft_model: str = ""
    # acceptance EMA below this collapses the sequence to plain decode
    spec_accept_min: float = 0.15
    # MAX probe distance (generated tokens) for collapsed/missing slots:
    # failed probes back off exponentially from 8 up to this cap.  Each
    # probe on a pipelined slot costs one pipeline drain + one proposer
    # attempt, so the cap bounds the near-zero-acceptance regression
    # (< 2%) while mid-stream repetition is still discovered quickly.
    spec_probe_interval: int = 64

    # KVBM tiers (kvbm/): 0 disables the G2 host cache.  When enabled, the
    # scheduler offloads the coldest evictable HBM blocks to host DRAM once
    # free blocks fall below offload_watermark_blocks (one batched
    # device→host gather per step), and onboards G2/G3 prefix hits at
    # admission instead of recomputing prefill.
    host_cache_blocks: int = 0
    disk_cache_dir: Optional[str] = None   # G3; needs disk_cache_blocks > 0
    disk_cache_blocks: int = 0
    # G4 cluster-shared object store (kvbm/object_store.py): demotions
    # that would otherwise drop spill here; any worker onboards them
    object_store_dir: Optional[str] = None
    object_store_ttl_s: Optional[float] = None
    # cross-worker G2 pull (kvbm/remote.py): prefetch missing prefix
    # blocks from a peer's host cache at admission time
    kvbm_remote: bool = True
    kvbm_remote_max_blocks: int = 64
    offload_watermark_blocks: int = 0      # 0 = num_blocks // 4
    offload_batch: int = 16                # max blocks gathered per step
    # KV integrity / degraded modes (kvbm/object_io.py, kvbm/breaker.py):
    # every G4 op the serving path issues is awaited at most
    # kv_io_deadline_s on a dedicated I/O thread; kv_breaker_threshold
    # consecutive per-tier failures trip that tier's circuit breaker
    # open (priced as recompute in the advertised kv_tier_costs) until a
    # half-open probe succeeds after kv_breaker_cooldown_s
    kv_io_deadline_s: float = 0.25
    kv_breaker_threshold: int = 3
    kv_breaker_cooldown_s: float = 30.0

    # disagg KV transfer: bound on one wire frame's K+V payload bytes
    # (disagg/transfer.py chunk sizing)
    transfer_chunk_bytes: int = DEFAULT_CHUNK_BYTES

    # LoRA serving (lora/): 0 disables.  max_adapters counts usable slots
    # (slot 0 is reserved for "no adapter"); adapters load lazily from
    # lora_dir (shared PEFT checkpoint tree) on first request and evict
    # LRU.  Ranks are padded to lora_rank; larger ranks are rejected.
    lora_max_adapters: int = 0
    lora_rank: int = 16
    lora_dir: Optional[str] = None

    # parallelism.  sp > 1 enables sequence-parallel ring-attention
    # prefill for prompts beyond the largest prefill bucket (the
    # long-context path; ops/ring_attention.py) — dp*tp*sp must divide
    # the device count
    dp: int = 1
    tp: int = 1
    sp: int = 1

    # disaggregation role: "both" serves agg traffic; "prefill" workers run
    # prefill-only hops and park KV; "decode" workers pull and decode
    role: str = "both"

    # compile every decode-program variant before serving traffic
    # (core.py warmup_decode) — on by the CLI worker/bench; default off so
    # short-lived test engines skip the extra compiles
    warmup: bool = False

    # None = resolve from the checkpoint's config.json (model_path) or 2
    eos_token_id: Optional[int] = None
    # output parsing advertised in the MDC: frontends split <think> spans
    # into reasoning_content when set (e.g. "deepseek_r1")
    reasoning_parser: str = ""
    seed: int = 0

    def resolve_model(self):
        if self.model_config is not None:
            return self.model_config
        if self.model_path:
            from .loader_cache import cached_hf_config

            return cached_hf_config(self.model_path)
        if self.model not in PRESETS:
            raise ValueError(
                f"unknown model preset {self.model!r}; have {sorted(PRESETS)}"
            )
        return PRESETS[self.model]

    @property
    def served_name(self) -> str:
        return self.model_name or self.resolve_model().name

    @property
    def max_context(self) -> int:
        return self.block_size * self.max_blocks_per_seq

    @property
    def chunk_budget(self) -> int:
        """Effective per-step prefill token budget."""
        return self.prefill_chunk_tokens or self.max_batch_tokens

    def resolve_eos_ids(self) -> Tuple[int, ...]:
        """Stop-token set: explicit override > checkpoint config > default.
        The checkpoint path reuses cached_hf_config (one config.json parse
        per path, same error surface as resolve_model)."""
        if self.eos_token_id is not None:
            return (self.eos_token_id,)
        return self.resolve_model().eos_token_ids
