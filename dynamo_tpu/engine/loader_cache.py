"""Memoized HF config resolution (EngineConfig.resolve_model is called on
every card build / scheduler decision; reparse config.json once)."""

from __future__ import annotations

from functools import lru_cache

from ..models.llama import LlamaConfig


@lru_cache(maxsize=32)
def cached_hf_config(model_path: str) -> LlamaConfig:
    from ..models.loader import load_hf_config

    return load_hf_config(model_path)
