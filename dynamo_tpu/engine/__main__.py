"""`python -m dynamo_tpu.engine` — run a JAX engine worker.

The TPU-native equivalent of `python -m dynamo.vllm`
(ref: components/src/dynamo/vllm/main.py:114).
"""

import argparse
import asyncio
import os

if os.environ.get("DYN_JAX_PLATFORM"):
    # this image's TPU plugin prepends itself to jax_platforms regardless of
    # JAX_PLATFORMS; DYN_JAX_PLATFORM=cpu forces the backend explicitly
    # (virtual-mesh testing on a TPU-attached host, same recipe as
    # tests/conftest.py)
    import jax

    jax.config.update("jax_platforms", os.environ["DYN_JAX_PLATFORM"])

from .. import obs
from ..runtime import DistributedRuntime
from ..runtime.logging import setup_logging
from .config import EngineConfig
from .worker import JaxEngineWorker


def build_args() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("dynamo_tpu.engine")
    p.add_argument("--model", default="tiny", help="model preset name")
    p.add_argument("--model-path", default="",
                   help="local HF checkpoint dir (overrides --model)")
    p.add_argument("--model-name", default="", help="served model name")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="backend")
    p.add_argument("--block-size", type=int, default=128)
    p.add_argument("--num-blocks", type=int, default=128)
    p.add_argument("--max-blocks-per-seq", type=int, default=64)
    p.add_argument("--max-num-seqs", type=int, default=8)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--dp", type=int, default=1)
    p.add_argument("--no-prefix-caching", action="store_true")
    p.add_argument("--kv-cache-dtype", default="bf16",
                   choices=["bf16", "int8"],
                   help="KV storage dtype (quant/kv.py): int8 halves KV "
                        "bytes/token and ~doubles blocks per HBM budget; "
                        "MLA families fall back to bf16")
    p.add_argument("--kv-hbm-gb", type=float, default=0.0,
                   help="KV HBM budget in GB: derive --num-blocks from "
                        "bytes-per-block at the effective kv dtype "
                        "(0 = use --num-blocks as given)")
    p.add_argument("--prefill-chunk-tokens", type=int, default=0,
                   help="chunked-prefill token budget per scheduler step "
                        "(bounds decode ITL during prefill bursts); "
                        "0 = max_batch_tokens")
    from ..ops.packed_prefill import PACKED_IMPLS
    from ..ops.paged_attention import DECODE_IMPLS

    p.add_argument("--attn-impl", default="",
                   choices=["", *DECODE_IMPLS],
                   help="decode attention impl (ops/paged_attention.py):"
                        " pallas = hand-tiled DMA kernel (int8 caches "
                        "dequantize in-kernel), jnp/jnp_bf16 = XLA "
                        "gather paths; default keeps the model family's "
                        "choice")
    p.add_argument("--packed-attn-impl", default="",
                   choices=["", *PACKED_IMPLS],
                   help="packed-prefill attention impl "
                        "(ops/pallas_packed_prefill.py): pallas = "
                        "segment-aware tile-skip kernel (no S-fold "
                        "attention overhead), xla = masked reference; "
                        "default keeps the model family's choice")
    from ..ops.fused_sampling import EPILOGUE_MODES

    p.add_argument("--sampling-epilogue", default="off",
                   choices=list(EPILOGUE_MODES),
                   help="fused sampling/top-k epilogue "
                        "(ops/fused_sampling.py): fused = stream the "
                        "decode final projection in vocab tiles and "
                        "emit only token ids (no [B, vocab] logits in "
                        "HBM; byte-identical at greedy); off = the "
                        "reference materialize-then-sample path; "
                        "families without a hidden-state decode "
                        "surface (MLA) fall back to off")
    p.add_argument("--no-packed-prefill", action="store_true",
                   help="disable packed chunked prefill (use the padded "
                        "per-row programs)")
    p.add_argument("--peak-tflops", type=float,
                   default=float(os.environ.get("DYN_PEAK_TFLOPS", "0")),
                   help="accelerator dense-bf16 peak, for prefill-phase "
                        "MFU in the FPM stream (v5e: 197); 0 = unknown")
    p.add_argument("--peak-hbm-gbps", type=float,
                   default=float(os.environ.get("DYN_PEAK_HBM_GBPS", "0")),
                   help="accelerator peak HBM bandwidth GB/s, for the "
                        "roofline MBU gauges (v5e: 819); 0 = unknown")
    p.add_argument("--host-cache-blocks", type=int, default=0,
                   help="G2 host-DRAM KV cache capacity (blocks); 0 off")
    p.add_argument("--offload-watermark-blocks", type=int, default=0,
                   help="offload coldest HBM blocks to G2 once free blocks "
                        "fall below this (0 = num_blocks/4); raise toward "
                        "num_blocks so allocation bursts can't evict a "
                        "block before the offload pass copies it")
    p.add_argument("--disk-cache-dir", default="",
                   help="G3 disk KV cache directory")
    p.add_argument("--disk-cache-blocks", type=int, default=0)
    p.add_argument("--object-store-dir",
                   default=os.environ.get("DYN_KVBM_OBJECT_DIR", ""),
                   help="G4 cluster-shared object store (shared FS path; "
                        "defaults to $DYN_KVBM_OBJECT_DIR)")
    p.add_argument("--kv-io-deadline-s", type=float, default=0.25,
                   help="per-op deadline for shared-FS (G4) KV I/O on the "
                        "dedicated I/O thread; a wedged mount is a bounded "
                        "timeout off the scheduler path")
    p.add_argument("--kv-breaker-threshold", type=int, default=3,
                   help="consecutive tier failures that trip the tier's "
                        "circuit breaker open (tier skipped and priced at "
                        "recompute until a half-open probe succeeds)")
    p.add_argument("--kv-breaker-cooldown-s", type=float, default=30.0,
                   help="seconds an open tier breaker waits before "
                        "admitting one half-open probe op")
    p.add_argument("--no-kvbm-remote", action="store_true",
                   help="disable cross-worker G2 pull")
    p.add_argument("--migration-limit", type=int, default=3)
    p.add_argument("--no-warmup", action="store_true",
                   help="skip decode-variant precompilation at startup")
    p.add_argument("--role", default="both",
                   choices=["both", "prefill", "decode"])
    p.add_argument("--reasoning-parser", default="",
                   help="advertise a reasoning parser (e.g. deepseek_r1) "
                        "so frontends split <think> spans")
    p.add_argument("--lora-dir", default=os.environ.get("DYN_LORA_PATH", ""),
                   help="PEFT adapter tree (lora/source.py); empty = off")
    p.add_argument("--lora-max-adapters", type=int, default=4)
    p.add_argument("--lora-rank", type=int, default=16)
    p.add_argument("--spec-decode", default="off",
                   choices=["off", "ngram", "draft"],
                   help="speculative decoding proposer (spec/): ngram = "
                        "zero-weight prompt lookup; draft = a second "
                        "model on the same mesh (single-host v1)")
    p.add_argument("--spec-k", type=int, default=4,
                   help="max draft tokens per speculation round "
                        "(per-sequence acceptance EMA adapts below this)")
    p.add_argument("--spec-draft-model", default="",
                   help="draft model preset for --spec-decode draft")
    p.add_argument("--spec-draft-model-path", default="",
                   help="draft HF checkpoint dir (overrides the preset)")
    p.add_argument("--drain-deadline-s", type=float, default=5.0,
                   help="SIGTERM grace: in-flight requests get this long "
                        "to finish before the rest error with the "
                        "migratable 'worker draining' marker and replay "
                        "on surviving workers")
    p.add_argument("--no-overlap-scheduling", action="store_true",
                   help="lockstep reference scheduler (schedule -> "
                        "dispatch -> block -> emit) instead of the "
                        "overlapped default; greedy output is "
                        "byte-identical, served throughput is not")
    p.add_argument("--no-adaptive-fusion", action="store_true",
                   help="always dispatch full decode_fused_steps bursts "
                        "when no prefill is pending, instead of ramping "
                        "the burst size up a decode-only stretch")
    p.add_argument("--slo-yield-burn", type=float, default=1.0,
                   help="SLA-aware admission: prefill chunks yield "
                        "budget to decode while the frontend-published "
                        "SLO burn rate exceeds this (0 disables)")
    return p


async def main() -> None:
    setup_logging()
    # timeline tracing (obs/): DYN_TRACE=1 installs the process
    # tracer; DYN_TRACE_OUT gets a Chrome trace dump at exit
    obs.install_from_env()
    args = build_args().parse_args()
    config = EngineConfig(
        model=args.model,
        model_path=args.model_path,
        model_name=args.model_name,
        block_size=args.block_size,
        num_blocks=args.num_blocks,
        max_blocks_per_seq=args.max_blocks_per_seq,
        max_num_seqs=args.max_num_seqs,
        tp=args.tp,
        dp=args.dp,
        enable_prefix_caching=not args.no_prefix_caching,
        kv_cache_dtype=args.kv_cache_dtype,
        kv_hbm_gb=args.kv_hbm_gb,
        prefill_chunk_tokens=args.prefill_chunk_tokens,
        prefill_packed=not args.no_packed_prefill,
        attn_impl=args.attn_impl,
        packed_attn_impl=args.packed_attn_impl,
        sampling_epilogue=args.sampling_epilogue,
        peak_tflops=args.peak_tflops,
        peak_hbm_gbps=args.peak_hbm_gbps,
        host_cache_blocks=args.host_cache_blocks,
        offload_watermark_blocks=args.offload_watermark_blocks,
        disk_cache_dir=args.disk_cache_dir or None,
        disk_cache_blocks=args.disk_cache_blocks,
        object_store_dir=args.object_store_dir or None,
        kv_io_deadline_s=args.kv_io_deadline_s,
        kv_breaker_threshold=args.kv_breaker_threshold,
        kv_breaker_cooldown_s=args.kv_breaker_cooldown_s,
        kvbm_remote=not args.no_kvbm_remote,
        role=args.role,
        warmup=not args.no_warmup,
        reasoning_parser=args.reasoning_parser,
        lora_dir=args.lora_dir or None,
        lora_max_adapters=(args.lora_max_adapters if args.lora_dir else 0),
        lora_rank=args.lora_rank,
        spec_decode=args.spec_decode,
        spec_k=args.spec_k,
        spec_draft_model=args.spec_draft_model,
        spec_draft_model_path=args.spec_draft_model_path,
        overlap_scheduling=not args.no_overlap_scheduling,
        decode_fuse_adaptive=not args.no_adaptive_fusion,
        slo_yield_burn=args.slo_yield_burn,
    )
    rt = await DistributedRuntime.detached().start()
    worker = await JaxEngineWorker(
        rt, config, namespace=args.namespace, component=args.component,
        migration_limit=args.migration_limit,
    ).start()

    async def drain_worker() -> None:
        # graceful SIGTERM: withdraw the lease, finish/migrate in-flight
        # requests (engine/worker.py drain()), then exit — even if a
        # drain step fails, the process must still come down
        try:
            await worker.drain(args.drain_deadline_s)
        finally:
            rt.root_token.kill()

    from ..runtime.aio import install_drain_handler

    install_drain_handler(drain_worker)
    if worker.served is not None:
        print(f"ready instance_id={worker.served.instance_id}", flush=True)
    else:  # multihost follower: no routing identity, replay only
        print(f"ready follower rank={worker.mh.rank}/{worker.mh.world}",
              flush=True)
    try:
        await rt.root_token.wait_killed()
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    await worker.close()
    await rt.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
