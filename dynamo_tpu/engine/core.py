"""The JAX engine core: continuous batching over a paged KV cache.

This is the component the reference does NOT have — it delegates token
generation to vLLM/SGLang/TRT-LLM (SURVEY.md §7 scope delta).  Design, for
XLA's compile-once/execute-many model:

  * two jitted programs: `prefill` (per padded-length bucket, one sequence)
    and `decode` (fixed batch = max_num_seqs, inactive slots masked to the
    garbage block).  No data-dependent shapes ever reach XLA.
  * the KV cache is donated through every step, so updates are in-place in
    HBM; only sampled token ids (B int32) cross back to the host per step.
  * host-side scheduler (this file) admits requests, manages the block
    allocator and PLH bookkeeping, streams tokens, and publishes KV events —
    mirroring the vLLM-scheduler behaviors the mocker simulates.
  * prefix-cache hits skip prefill compute for matched blocks: the prefill
    program attends to cached context through the block table (unified
    chunked-prefill/prefix-reuse path, ops/paged_attention.py).
"""

from __future__ import annotations

import asyncio
import dataclasses
import inspect
import logging
import zlib
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from types import SimpleNamespace
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import chaos, obs
from ..models import get_family
from ..parallel.mesh import MeshConfig, make_mesh, shard_params
from ..protocols import (
    DRAIN_ABORT,
    DRAIN_REJECT,
    LLMEngineOutput,
    PreprocessedRequest,
)
from ..quant.kv import is_quantized
from ..runtime.retry import PULL_POLICY, call_with_retry
from ..tokens import TokenBlockSequence, request_salt
from .block_allocator import BlockAllocator
from .config import EngineConfig
from ..ops.fused_sampling import fused_greedy_tokens, fused_sample_tokens
from .sampler import greedy_tokens, sample_tokens

logger = logging.getLogger(__name__)


def _set_result_safe(fut: asyncio.Future, value) -> None:
    if not fut.done():
        fut.set_result(value)


def _pow2_len(n: int) -> int:
    """Next power of two >= n (shape-bucketing for jit)."""
    b = 1
    while b < n:
        b *= 2
    return b


def _pow2_ids(block_ids) -> np.ndarray:
    """Block ids zero-padded to _pow2_len: bounds the number of distinct
    shapes reaching jit (one recompile per bucket), and padded ids target
    the reserved garbage block 0, so gathers read junk the host slices off
    and scatters write harmlessly."""
    n = len(block_ids)
    out = np.zeros(_pow2_len(n), np.int32)
    out[:n] = block_ids
    return out


@dataclass
class _Slot:
    index: int
    request: PreprocessedRequest
    seq: TokenBlockSequence
    out_q: asyncio.Queue
    block_table: np.ndarray  # [max_blocks_per_seq] int32
    ctx_len: int = 0         # tokens materialized in the cache
    prompt_len: int = 0      # fixed at admit (seq grows as tokens append)
    prefill_pos: int = 0     # next prompt position to compute (< prompt_len
    #                          while the slot is still prefilling)
    last_token: int = 0
    generated: int = 0
    committed_blocks: int = 0
    sampling_seed: int = 0
    finished: bool = False
    cancel_requested: bool = False
    cached_tokens: int = 0   # prefix-cache reuse (for metrics)
    lora_idx: int = 0        # adapter bank slot (0 = no adapter)
    enqueued_t: float = 0.0
    # forensics plane (obs/forensics.py): waiting-queue position at
    # enqueue and prefill chunk count, stamped back to the frontend on
    # the stream's first-token/finish frames (`forensic` metrics block)
    queue_pos: int = 0
    prefill_chunks: int = 0
    first_token_t: float = 0.0
    last_push_t: float = 0.0  # previous streamed-token time (ITL EMA)

    @property
    def prefilling(self) -> bool:
        return self.prefill_pos < self.prompt_len
    # disaggregation
    disagg_prefill: bool = False       # prefill-only; park KV for pulling
    # decode side of a disagg pull: the slot sits admitted-but-idle while
    # the pull task streams chunk injects into its blocks (prefill and
    # decode skip it until the pull finalizes or falls back)
    pulling: bool = False
    admitted: Optional[asyncio.Event] = None  # set (loop thread) on admit
    # decode pipelining (decode_pipeline_depth): tokens the device has
    # already decoded for this slot but the host has not yet read back
    inflight: int = 0
    # bumped on preemption so stale in-flight bursts are discarded
    epoch: int = 0
    # overlapped scheduling: the prompt is fully prefilled but the first
    # sampled token is still riding _pending_first (deferred readback) —
    # decode/spec skip the slot until the next step's flush emits it
    awaiting_first: bool = False
    # guided decoding (guided/json_prefix.py): constrained slots step
    # one token at a time through the top-M candidate path instead of
    # joining fused batch bursts
    guide: Optional[Any] = None
    guided_out: List[int] = field(default_factory=list)
    # speculative decoding (spec/): adaptive draft length (-1 = take the
    # engine default on first attempt; 0 = collapsed to plain decode),
    # acceptance-rate EMA (seeded with a neutral 0.5 prior on first
    # attempt), the generated-token count at which a collapsed/pipelined
    # slot next probes, and the number of leading positions whose
    # DRAFT-model KV matches the real sequence
    spec_k_cur: int = -1
    spec_accept_ema: float = -1.0
    spec_probe_at: int = 0
    spec_backoff: int = 0
    draft_pos: int = 0


@dataclass
class _Parked:
    """A finished disagg prefill whose KV awaits pulling by decode."""

    seq_id: str
    block_ids: list
    prompt_len: int
    expires_t: float


class JaxEngine:
    def __init__(self, config: EngineConfig, params=None, mesh=None,
                 kv_event_sink=None, kv_pull_fn=None, step_sink=None):
        """kv_event_sink: optional callable(stored, removed) -> awaitable,
        invoked with PLH batches as the cache mutates.
        kv_pull_fn: optional async callable(disaggregated_params) ->
        (k, v, prompt_len) pulling a remote prefill's KV blocks (set by the
        worker; the engine stays transport-agnostic).
        step_sink: optional callable(kind, {name: np.ndarray}) invoked with
        every compute step's host inputs BEFORE the jit call — the
        multi-host leader broadcasts these so follower processes replay an
        identical jit sequence (parallel/multihost.py).  Covers prefill
        (single/batched/packed/ring), decode (full/multi/continuation),
        guided top-M, spec_verify, gather/inject, lora_write, and embed;
        followers require kvbm/disagg off and the n-gram proposer only
        (draft-model speculation is single-host in v1)."""
        self.config = config
        self.model_cfg = config.resolve_model()
        self.family = get_family(self.model_cfg)
        # attention-impl overrides (ops/paged_attention.py +
        # ops/pallas_packed_prefill.py): the engine-level knobs replace
        # the resolved model config's fields so deployments pick the
        # kernel per worker (--attn-impl/--packed-attn-impl) without a
        # custom model_config.  "" keeps the family's default.  A knob
        # the family would silently ignore is a loud config error — the
        # MDC advertises the EFFECTIVE impl and must never claim a
        # kernel the worker doesn't run: MLA consults neither
        # attn_impl beyond "jnp" (family SUPPORTED_ATTN_IMPLS) nor
        # packed_attn_impl (no packed path / field).
        from ..ops.packed_prefill import PACKED_IMPLS
        from ..ops.paged_attention import DECODE_IMPLS

        impl_over = {}
        if config.attn_impl:
            supported = getattr(self.family, "SUPPORTED_ATTN_IMPLS",
                                DECODE_IMPLS)
            if config.attn_impl not in supported:
                raise ValueError(
                    f"attn_impl for model family "
                    f"{type(self.model_cfg).__name__} must be one of "
                    f"{' | '.join(supported)}, got {config.attn_impl!r}")
            impl_over["attn_impl"] = config.attn_impl
        if config.packed_attn_impl:
            if config.packed_attn_impl not in PACKED_IMPLS:
                raise ValueError(
                    f"packed_attn_impl must be "
                    f"{' | '.join(PACKED_IMPLS)}, "
                    f"got {config.packed_attn_impl!r}")
            if "packed_attn_impl" not in {
                    f.name for f in dataclasses.fields(self.model_cfg)}:
                raise ValueError(
                    f"model family {type(self.model_cfg).__name__} has "
                    f"no packed_attn_impl knob (MLA has no packed "
                    f"prefill path)")
            impl_over["packed_attn_impl"] = config.packed_attn_impl
        if impl_over:
            self.model_cfg = dataclasses.replace(self.model_cfg,
                                                 **impl_over)
        # fused sampling/top-k epilogue (ops/fused_sampling.py): resolve
        # the EFFECTIVE mode like the attn impls and kv dtype — families
        # without the hidden-state decode surface (MLA) fall back to
        # "off" with a warning instead of failing the worker, and the
        # MDC advertises the effective mode so a worker never claims an
        # epilogue it does not run
        from ..ops.fused_sampling import EPILOGUE_MODES
        if config.sampling_epilogue not in EPILOGUE_MODES:
            raise ValueError(
                f"sampling_epilogue must be "
                f"{' | '.join(EPILOGUE_MODES)}, "
                f"got {config.sampling_epilogue!r}")
        self.sampling_epilogue = config.sampling_epilogue
        if self.sampling_epilogue == "fused" and not (
                hasattr(self.family, "decode_hidden")
                and hasattr(self.family, "unembed_weight")
                and hasattr(self.family, "decode_multi_hidden")):
            logger.warning(
                "model family %r has no hidden-state decode surface; "
                "sampling_epilogue falls back to off",
                type(self.model_cfg).__name__)
            self.sampling_epilogue = "off"
        self.mesh = mesh if mesh is not None else make_mesh(
            MeshConfig(dp=config.dp, tp=config.tp, sp=config.sp)
        )
        self.kv_event_sink = kv_event_sink
        self._sink_takes_tier = False
        if kv_event_sink is not None:
            try:
                sink_params = list(
                    inspect.signature(kv_event_sink).parameters.values()
                )
                kinds = inspect.Parameter
                self._sink_takes_tier = (
                    sum(p.kind in (kinds.POSITIONAL_ONLY,
                                   kinds.POSITIONAL_OR_KEYWORD)
                        for p in sink_params) >= 3
                    or any(p.kind == kinds.VAR_POSITIONAL
                           for p in sink_params)
                )
            except (TypeError, ValueError):
                pass
        self.kv_pull_fn = kv_pull_fn
        self.step_sink = step_sink
        self.eos_ids = frozenset(config.resolve_eos_ids())
        # KV-cache quantization (quant/kv.py): resolve the EFFECTIVE
        # dtype — families without a quantized path (MLA) fall back to
        # bf16, the same precedent as the MLA packed-prefill/spec
        # fallbacks — then size the block pool: with a kv_hbm_gb budget
        # the block count derives from bytes-per-block, so int8 yields
        # ~2x blocks for the same HBM instead of the same count at half
        # the memory.  config.num_blocks is updated in place so the
        # allocator, block tables, MDC, and load metrics all agree.
        if config.kv_cache_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"kv_cache_dtype must be 'bf16' | 'int8', "
                f"got {config.kv_cache_dtype!r}")
        self.kv_dtype = config.kv_cache_dtype
        if self.kv_dtype == "int8" \
                and not hasattr(self.family, "kv_cache_scale_shapes"):
            logger.warning(
                "model family %r has no quantized KV path; "
                "kv_cache_dtype falls back to bf16", self.model_cfg.name)
            self.kv_dtype = "bf16"
        if config.kv_hbm_gb > 0:
            from ..quant.kv import blocks_for_hbm_budget

            config.num_blocks = blocks_for_hbm_budget(
                self.family, self.model_cfg, config.block_size,
                self.kv_dtype, int(config.kv_hbm_gb * 1e9))
        # KV block-lifecycle ledger (obs/kv_ledger.py): an independent
        # set of books recorded at the allocator's own mutation sites,
        # reconciled by the invariant auditor on request finish / idle
        # tick / on demand (/debug/kv).  None when DYN_KV_LEDGER=0 (or
        # config.kv_ledger=False) — every hook is then one pointer
        # compare, the obs-plane zero-cost-off contract.
        from ..obs.kv_ledger import KvLedger, ledger_enabled

        self.kv_ledger: Optional[KvLedger] = (
            KvLedger() if ledger_enabled(config.kv_ledger) else None)
        self.allocator = BlockAllocator(
            config.num_blocks, config.enable_prefix_caching,
            ledger=self.kv_ledger,
        )
        # KVBM tiers: router-visible events for ALL tiers are netted through
        # the consolidator, so a block offloaded to G2 survives G1 eviction
        # in the router's view (kvbm/consolidator.py)
        from ..kvbm import KvEventConsolidator, TieredKvManager

        self._consolidator = KvEventConsolidator()
        self.kvbm: Optional[TieredKvManager] = None
        if config.disk_cache_dir and config.host_cache_blocks <= 0:
            raise ValueError(
                "disk_cache_dir (G3) requires host_cache_blocks > 0: the "
                "disk tier is fed only by demotion from the host tier"
            )
        if config.disk_cache_dir and config.disk_cache_blocks <= 0:
            raise ValueError(
                "disk_cache_dir (G3) requires disk_cache_blocks > 0"
            )
        if config.object_store_dir and config.host_cache_blocks <= 0:
            raise ValueError(
                "object_store_dir (G4) requires host_cache_blocks > 0: the "
                "object tier is fed by demotion down the tier ladder")
        if config.host_cache_blocks > 0:
            self.kvbm = TieredKvManager(
                config.host_cache_blocks,
                disk_dir=config.disk_cache_dir,
                disk_blocks=config.disk_cache_blocks,
                object_dir=config.object_store_dir,
                object_ttl_s=config.object_store_ttl_s,
                io_deadline_s=config.kv_io_deadline_s,
                breaker_threshold=config.kv_breaker_threshold,
                breaker_cooldown_s=config.kv_breaker_cooldown_s,
            )
            self.kvbm.on_corruption = self._note_kv_corruption
        # (tier, action) -> count for
        # dynamo_kv_integrity_failures_total; quarantines land here via
        # _note_kv_corruption (g3/g4/remote/disagg), timeouts/errors are
        # merged in from the manager's I/O stats at export time
        self.kv_integrity: Dict[Tuple[str, str], int] = {}
        # cross-worker G2 pull (kvbm/remote.py): installed by the worker;
        # async callable(hashes) -> [(h, k, v), ...]
        self.remote_kvbm_fetch = None
        self._offload_watermark = (
            config.offload_watermark_blocks or config.num_blocks // 4
        )

        # LoRA: stacked adapter bank + name->slot registry (lora/bank.py).
        # Slot 0 is the all-zeros no-adapter slot; adapters load lazily
        # from lora_dir on first request and evict LRU among slots not
        # referenced by active sequences.
        self.lora_bank = None
        self._lora_slots: Dict[str, int] = {}   # name -> bank slot (>=1)
        self._lora_lru: List[str] = []          # LRU order, oldest first
        self._lora_pins: Dict[int, int] = {}    # slot -> resolved-not-
        #                                         yet-enqueued requests
        self._lora_source = None
        if config.lora_max_adapters > 0:
            if "lora_bank" not in inspect.signature(
                    self.family.prefill).parameters:
                raise ValueError(
                    f"model family {self.model_cfg.name!r} does not "
                    "support LoRA serving")
            from ..lora.bank import empty_bank
            from ..lora.source import LocalLoraSource

            mc = self.model_cfg
            self.lora_bank = empty_bank(
                mc.n_layers, config.lora_max_adapters + 1,
                config.lora_rank, mc.d_model, mc.q_dim, mc.kv_dim,
                dtype=mc.dtype)
            if config.lora_dir:
                self._lora_source = LocalLoraSource(config.lora_dir)

        with self.mesh:
            if params is None and config.model_path:
                from ..models.loader import load_params

                # already placed shard-by-shard onto the mesh
                self.params = load_params(
                    config.model_path, self.model_cfg, mesh=self.mesh
                )
            else:
                if params is None:
                    params = self.family.init_params(
                        self.model_cfg, jax.random.PRNGKey(config.seed)
                    )
                self.params = shard_params(params, self.mesh)
            self.kv = self._init_kv_cache()

        # pinned output shardings for every KV-returning program: XLA is
        # otherwise free to pick a DIFFERENT (equivalent) sharding for a
        # program's kv output than the cache was initialized with, and the
        # C++ dispatch cache keys on input sharding — so the next program
        # that consumed the drifted kv forked its executable (the
        # committed-vs-uncommitted packed-prefill fork the PR 7 watchdog
        # measured at 8-14s mid-serving on TPU).  Pinning the kv outputs
        # to the canonical cache shardings (and the small host-bound
        # outputs to replicated) makes every program's kv round-trip
        # sharding-stable: one executable per shape, period.
        self._rep_sharding = NamedSharding(self.mesh, P())
        kv_specs = list(self.family.kv_cache_specs())
        if is_quantized(self.kv):
            kv_specs += list(self.family.kv_cache_scale_specs())
        self._kv_shardings = tuple(
            NamedSharding(self.mesh, spec) for spec in kv_specs)

        # compile watchdog + roofline (obs/compile_watch.py) is
        # constructed FIRST so every jit below is a WatchedProgram from
        # the moment it exists — a compile (warmup or the mid-serving
        # kind the guided fork measured at 8-14s) is counted, timed,
        # span-recorded, and costed with XLA's own cost_analysis
        # (per-program FLOPs/bytes feed the decode/spec-verify/
        # packed-prefill MFU+MBU gauges).  Wrap-at-definition is the
        # DYN001 lint invariant: a raw jax.jit that dispatches unwatched
        # cannot be written here without a suppression.  Wrapper
        # overhead per dispatch is two C++ cache-size reads.
        from ..obs.compile_watch import CompileWatch

        # timeline tracing (obs/): steps run on whatever pool thread
        # asyncio.to_thread picked, but the step lock serializes them —
        # pin every step-phase span (and compile spans) to ONE logical
        # track per engine so the report's innermost-span attribution
        # sees a well-nested timeline (co-resident engines in one
        # process stay distinct)
        self._obs_track = f"sched:{id(self):x}"
        self.compile_watch = CompileWatch(
            sink=lambda rec: self.fpm.append(rec),
            track=self._obs_track,
            serving=lambda: any(s is not None for s in self._slots),
        )
        w = self.compile_watch
        _toks2 = lambda a: a[2].shape[-1]           # noqa: E731
        _toks2_total = lambda a: int(               # noqa: E731
            np.prod(a[2].shape))
        # out_shardings pytrees: kv pinned canonical, everything else
        # replicated (token/descriptor outputs are [B]-sized and host
        # bound — see the _kv_shardings rationale above)
        rep = self._rep_sharding
        kvsh = self._kv_shardings
        _decode_out = (rep, kvsh, rep, rep, rep)
        _prefill_out = (rep, kvsh)
        # decode variants: {greedy: jitted} — an all-greedy batch takes the
        # argmax specialization (sampling machinery measurably costs on
        # large vocabs even top-k-capped)
        # donate kv + the advancing descriptor arrays (positions/ctx/steps
        # are returned advanced for the next burst's continuation)
        # the sampling epilogue is a static, init-time property of the
        # decode programs (identical on every host — followers replay
        # the leader's step stream through the same partials), NOT a
        # per-dispatch key: the (greedy, k) program families and their
        # pinned out_shardings are unchanged, so the zero-recompile
        # steady state carries over
        _ep = self.sampling_epilogue == "fused"
        self._jit_decode = {
            g: w.wrap(jax.jit(
                partial(self._decode_impl, self.family, self.model_cfg,
                        self.mesh, g, _ep),
                donate_argnums=(1, 5, 7, 9),
                out_shardings=_decode_out,
            ), "decode")
            for g in (False, True)
        }
        self._jit_prefill = w.wrap(jax.jit(
            partial(self._prefill_impl, self.family, self.model_cfg),
            donate_argnums=(1,),
            out_shardings=_prefill_out,
        ), "prefill", _toks2)
        self._jit_prefill_batched = w.wrap(jax.jit(
            partial(self._prefill_batched_impl, self.family, self.model_cfg),
            donate_argnums=(1,),
            out_shardings=_prefill_out,
        ), "prefill_batched", _toks2_total)
        # packed chunked prefill (engine/prefill.py planner +
        # ops/packed_prefill.py): the padding-free multi-sequence path.
        # Gated off for families without prefill_packed (MLA) and for
        # capacity-dispatch MoE, whose per-sequence expert-capacity pools
        # a packed stream would merge (the batched path vmaps per row).
        self._packed_prefill_ok = (
            config.prefill_packed
            and hasattr(self.family, "prefill_packed")
            and not (getattr(self.model_cfg, "n_experts", 0) > 0
                     and getattr(self.model_cfg, "moe_dispatch", "dense")
                     == "capacity")
        )
        # the jit must exist whenever the FAMILY supports packing, even
        # with packing config-disabled on this worker: a multi-host
        # follower replays whatever step kinds its leader broadcasts,
        # including prefill_packed
        self._jit_prefill_packed = None
        if hasattr(self.family, "prefill_packed"):
            self._jit_prefill_packed = w.wrap(jax.jit(
                partial(self._prefill_packed_impl, self.family,
                        self.model_cfg, self.mesh),
                donate_argnums=(1,),
                out_shardings=_prefill_out,
            ), "prefill_packed", _toks2)
        # speculative decoding (spec/): like prefill_packed, the verify
        # jit exists whenever the FAMILY supports it — a multi-host
        # follower replays whatever step kinds its leader broadcasts,
        # spec_verify included, regardless of this worker's own config
        self._jit_spec_verify = None
        if hasattr(self.family, "spec_verify_packed"):
            self._jit_spec_verify = w.wrap(jax.jit(
                partial(self._spec_verify_impl, self.family,
                        self.model_cfg, self.mesh),
                donate_argnums=(1,),
                out_shardings=(rep, rep, rep, kvsh),
            ), "spec_verify", _toks2)
        self.proposer = None
        self._spec_ok = False
        if config.spec_decode != "off":
            if config.spec_decode not in ("ngram", "draft"):
                raise ValueError(
                    f"spec_decode must be 'off' | 'ngram' | 'draft', "
                    f"got {config.spec_decode!r}")
            if self._jit_spec_verify is None:
                # MLA families have no packed verify path in v1: serve
                # plain decode instead of failing the worker
                logger.warning(
                    "model family %r has no spec_verify_packed; "
                    "speculative decoding disabled (plain decode)",
                    self.model_cfg.name)
            else:
                if config.spec_decode == "draft" and step_sink is not None:
                    raise ValueError(
                        "draft-model speculation is single-host in v1 "
                        "(draft programs do not ride the step stream); "
                        "use spec_decode='ngram' on multi-host slices")
                from ..spec import make_proposer

                # the draft model's own prefill/propose programs are jit
                # dispatch sites like any other: watched, so a draft
                # recompile mid-serving is as visible as a target one
                self.proposer = make_proposer(config, self.mesh,
                                              compile_watch=w)
                self._spec_ok = True
        # slot indexes that speculated this scheduler step (they emitted
        # synchronously and must skip the pipelined decode dispatch)
        self._specced: frozenset = frozenset()
        self._fpm_last_spec_t = 0.0
        # prefill-phase MFU bookkeeping for the FPM stream: dense matmul
        # FLOPs per prompt token ~ 2 x params, excluding the embedding
        # (a lookup) and an untied lm_head (logits run only on the few
        # last-token rows, not the whole stream).  Attention FLOPs are
        # also excluded — a lower bound that understates long-context
        # chunks.
        n_params = sum(int(np.prod(x.shape))
                       for x in jax.tree_util.tree_leaves(self.params))
        skip = (sum(int(np.prod(self.params[k].shape))
                    for k in ("embedding", "lm_head")
                    if k in self.params)
                if isinstance(self.params, dict) else 0)
        self._flops_per_token = 2.0 * max(n_params - skip, 1)
        # sequence-parallel ring prefill: long-context path for prompts
        # beyond the largest bucket when the mesh has an sp axis
        self._jit_prefill_ring = None
        if config.sp > 1 and hasattr(self.family, "prefill_ring"):
            self._jit_prefill_ring = w.wrap(jax.jit(
                partial(self._prefill_ring_impl, self.family,
                        self.model_cfg, self.mesh),
                donate_argnums=(1,),
                out_shardings=_prefill_out,
            ), "prefill_ring", _toks2)
        self._jit_inject = w.wrap(
            jax.jit(self._inject_impl, donate_argnums=(0,),
                    out_shardings=kvsh), "inject",
            lambda a: a[3].shape[0])
        self._jit_gather = w.wrap(
            jax.jit(self._gather_impl), "gather", lambda a: a[1].shape[0])
        # fused decode: one compiled variant per (greedy, k) ladder rung
        # (adaptive fusion ramps k through _fuse_ladder; a fixed
        # num_steps program dispatched at a smaller accounting k would
        # waste (num_steps - k)/num_steps of every interleave burst's
        # decode compute).  All rungs are warmed by warmup_decode.
        self._jit_decode_multi = None
        if config.decode_fused_steps > 1:
            self._jit_decode_multi = {
                (g, k): w.wrap(jax.jit(
                    partial(self._decode_multi_impl, self.family,
                            self.model_cfg, self.mesh, g, k, _ep),
                    donate_argnums=(1, 5, 7, 9),
                    out_shardings=_decode_out,
                ), "decode_multi")
                for g in (False, True)
                for k in self._fuse_ladder()[1:]
            }

        # continuation decode (steady state): the burst descriptor lives on
        # device and advances INSIDE the decode program (advance=k), so an
        # unchanged-membership burst uploads nothing — the full path
        # uploads ~12 arrays per burst, each paying the host->device hop
        # (the round-3 scheduler-overhead finding).  _dev_desc is the
        # device descriptor pack of the last dispatched burst; _last_desc
        # the leader's host mirror used to prove the next burst is a pure
        # continuation of it.
        self._dev_desc: Optional[Dict[str, Any]] = None
        self._last_desc: Optional[Dict[str, Any]] = None
        self._desc_sharding = NamedSharding(self.mesh, P())
        self._adv_consts: Dict[int, Any] = {}

        self.waiting: List[_Slot] = []
        self._sched_calls: List[tuple] = []  # (fn, future) run between steps
        # async KV-event sink dispatches in flight: the loop only holds a
        # weak ref to a task, so fire-and-forget publications could be
        # gc'd mid-flight with their exceptions never observed (DYN005)
        self._event_tasks: set = set()
        self._parked: Dict[str, _Parked] = {}
        self.parked_ttl_s = 120.0
        # identity advertised in kv_transfer_params (set by the worker)
        self.transfer_identity: Dict[str, Any] = {}
        self._qlock = threading.Lock()  # guards `waiting` across threads
        self._step_lock = threading.Lock()  # held for each _sched_step run
        self._slots: List[Optional[_Slot]] = [None] * config.max_num_seqs
        # decode pipelining (decode_pipeline_depth): dispatched-but-unread
        # bursts + the device-resident token chain feeding the next burst
        self._inflight: deque = deque()
        self._chain_tokens = None          # device [B] last burst's output
        self._chain_owner: List[Optional[Tuple[str, int]]] = \
            [None] * config.max_num_seqs   # (seq_id, epoch) per lane
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._loop_ref: Optional[asyncio.AbstractEventLoop] = None
        self._closed = False
        # graceful drain (engine/worker.py drain()): set to reject new
        # requests with the migratable "worker draining" marker
        self.draining = False
        self.metrics: Dict[str, Any] = {
            "steps": 0, "prefill_tokens": 0, "decode_tokens": 0,
            "cache_hit_tokens": 0, "preemptions": 0, "step_time_s": 0.0,
            "requests": 0, "prompt_tokens": 0,
        }
        self.itl_ema_s = 0.0  # streamed inter-token latency (SLA planner)
        # forward-pass metrics stream (ref fpm_publisher.rs:1-10 /
        # instrumented_scheduler.py): one record per dispatched program —
        # decode bursts carry (lanes, fused k, gap since the previous
        # decode dispatch), prefill programs carry (rows, chunk tokens).
        # The worker drains this ring onto the event plane; the SLA
        # planner regresses its perf model on it online.
        self.fpm: deque = deque(maxlen=4096)
        self._fpm_last_decode_t = 0.0
        self._fpm_last_prefill_t = 0.0
        # roofline attrs handed from a dispatch path to the span that
        # wraps it (tracing-on only; consumed exactly once per dispatch)
        self._obs_dispatch_extra: Optional[dict] = None
        self._obs_decode_extra: Optional[dict] = None
        # time of the last BLOCKING device fetch (np.asarray round trip):
        # dispatch-gap MFU is only meaningful when a sync landed inside
        # the gap — pure async enqueues measure host time, not compute
        self._fpm_sync_t = 0.0
        # overlapped scheduling (config.overlap_scheduling): deferred
        # prefill first-token readbacks — each entry holds one dispatch's
        # sampled-token device array plus the completing slots awaiting
        # it; flushed (ONE device_wait) at the top of the next step,
        # while this step's programs execute behind it
        self._overlap = bool(config.overlap_scheduling)
        self._pending_first: List[dict] = []
        # adaptive decode fusion: consecutive decode-only steps (the
        # fusion ladder's ramp clock); reset on arrivals/cancellations
        self._decode_only_run = 0
        # SLA-aware admission: worst SLO burn rate the worker last fed
        # us (obs/slo.py via the worker's slo_metrics subscription) and
        # when — stale signals decay to 0 (_effective_slo_burn)
        self._slo_burn = 0.0
        self._slo_burn_t = 0.0

    # -- cache ------------------------------------------------------------
    def _init_kv_cache(self):
        m = self.model_cfg
        c = self.config
        # family-owned layout: GQA (k, v) or MLA (latent, rope-key) pair,
        # both in the head-major transposed block layout.  An int8 cache
        # (self.kv_dtype, quant/kv.py) adds fp32 scale planes as members
        # 3 and 4 of the tuple, sharded with the same tp split.
        dtype = jnp.int8 if self.kv_dtype == "int8" else m.dtype
        k_shape, v_shape = self.family.kv_cache_shapes(
            m, c.num_blocks, c.block_size)
        k_spec, v_spec = self.family.kv_cache_specs()
        # dynlint: disable=DYN001 one-shot sharded-zeros allocation at init, never dispatched while serving
        k = jax.jit(partial(jnp.zeros, k_shape, dtype),
                    out_shardings=NamedSharding(self.mesh, k_spec))()
        # dynlint: disable=DYN001 one-shot sharded-zeros allocation at init, never dispatched while serving
        v = jax.jit(partial(jnp.zeros, v_shape, dtype),
                    out_shardings=NamedSharding(self.mesh, v_spec))()
        if self.kv_dtype != "int8":
            return (k, v)
        ks_shape, vs_shape = self.family.kv_cache_scale_shapes(
            m, c.num_blocks, c.block_size)
        ks_spec, vs_spec = self.family.kv_cache_scale_specs()
        # dynlint: disable=DYN001 one-shot sharded-zeros allocation at init, never dispatched while serving
        ks = jax.jit(partial(jnp.zeros, ks_shape, jnp.float32),
                     out_shardings=NamedSharding(self.mesh, ks_spec))()
        # dynlint: disable=DYN001 one-shot sharded-zeros allocation at init, never dispatched while serving
        vs = jax.jit(partial(jnp.zeros, vs_shape, jnp.float32),
                     out_shardings=NamedSharding(self.mesh, vs_spec))()
        return (k, v, ks, vs)

    # -- jitted programs --------------------------------------------------
    @staticmethod
    def _decode_impl(family, model_cfg, mesh, greedy, epilogue, params,
                     kv, chain, use_chain, tokens, positions, block_tables,
                     ctx_lens, seeds, steps, temps, top_ks, top_ps, valid,
                     advance, lora_bank=None, lidx=None):
        """chain/use_chain: device-resident token chaining — lanes whose
        previous burst is still unread take their input token from the
        prior burst's on-device output instead of a host round-trip.
        `greedy` is a static specialization: an all-greedy batch skips the
        sampling machinery (sampler.py greedy_tokens).  `epilogue` is the
        static fused-sampling choice (ops/fused_sampling.py): the decode
        trunk stops at the final-norm hidden and the projection streams
        tile-by-tile into the sampler statistics, so [B, vocab] logits
        never materialize — byte-identical at greedy to the reference
        path below, which stays as the off-mode fallback.

        `advance` (traced scalar) is the continuation clock: steady-state
        bursts re-dispatch the PREVIOUS device descriptor with advance=k
        instead of uploading fresh positions/ctx/steps — the advanced
        arrays are returned for the next burst.  One program serves both
        modes, so donated KV never crosses programs (a separate
        continuation program made XLA re-lay the multi-GB cache on every
        transition — measured at seconds per full burst)."""
        positions = positions + advance
        ctx_lens = ctx_lens + advance
        steps = steps + advance
        tokens = jnp.where(use_chain, chain, tokens)
        lora_kw = ({"lora_bank": lora_bank, "adapter_idx": lidx}
                   if lora_bank is not None else {})
        if epilogue:
            h, kv = family.decode_hidden(
                params, model_cfg, kv, tokens, positions, block_tables,
                ctx_lens, valid=valid, mesh=mesh, **lora_kw,
            )
            uw = family.unembed_weight(params, model_cfg)
            if greedy:
                next_tokens = fused_greedy_tokens(h, uw)
            else:
                next_tokens = fused_sample_tokens(h, uw, seeds, steps,
                                                  temps, top_ks, top_ps)
        else:
            logits, kv = family.decode(
                params, model_cfg, kv, tokens, positions, block_tables,
                ctx_lens, valid=valid, mesh=mesh, **lora_kw,
            )
            if greedy:
                next_tokens = greedy_tokens(logits)
            else:
                next_tokens = sample_tokens(logits, seeds, steps, temps,
                                            top_ks, top_ps)
        # [1, B]: burst-shaped like multi
        return next_tokens[None], kv, positions, ctx_lens, steps

    @staticmethod
    def _decode_multi_impl(family, model_cfg, mesh, greedy, num_steps,
                           epilogue, params, kv, chain, use_chain, tokens,
                           positions, block_tables, ctx_lens, seeds, steps,
                           temps, top_ks, top_ps, valid, advance,
                           lora_bank=None, lidx=None):
        """num_steps fused decode steps (family decode_multi); sampling
        streams stay per-token identical to the single-step path (seed
        folded with the running step counter).  `epilogue`/`advance`: see
        _decode_impl — with the epilogue the scan body samples from the
        final-norm hidden (family decode_multi_hidden), so no step of the
        burst materializes logits."""
        positions = positions + advance
        ctx_lens = ctx_lens + advance
        steps = steps + advance
        tokens = jnp.where(use_chain, chain, tokens)
        lora_kw = ({"lora_bank": lora_bank, "adapter_idx": lidx}
                   if lora_bank is not None else {})
        if epilogue:
            uw = family.unembed_weight(params, model_cfg)
            if greedy:
                def sample_fn(h, step_idx):
                    return fused_greedy_tokens(h, uw)
            else:
                def sample_fn(h, step_idx):
                    return fused_sample_tokens(h, uw, seeds,
                                               steps + step_idx, temps,
                                               top_ks, top_ps)

            burst, kv = family.decode_multi_hidden(
                params, model_cfg, kv, tokens, positions, block_tables,
                ctx_lens, num_steps, sample_fn, valid=valid, mesh=mesh,
                **lora_kw,
            )
            return burst, kv, positions, ctx_lens, steps
        if greedy:
            sample_fn = None  # decode_multi defaults to argmax
        else:
            def sample_fn(logits, step_idx):
                return sample_tokens(logits, seeds, steps + step_idx,
                                     temps, top_ks, top_ps)

        burst, kv = family.decode_multi(
            params, model_cfg, kv, tokens, positions, block_tables,
            ctx_lens, num_steps, sample_fn, valid=valid, mesh=mesh,
            **lora_kw,
        )
        return burst, kv, positions, ctx_lens, steps

    @staticmethod
    def _inject_impl(kv, kb, vb, ids, ksb=None, vsb=None):
        """Scatter pulled KV blocks into the cache (ids padded with 0 write
        harmlessly into the garbage block).

        kb/vb arrive in the UNIVERSAL transfer layout [L, nb, bs, nkv, hd]
        (stable on the wire regardless of either engine's physical layout)
        and are permuted into the head-major block layout here — the TPU
        analogue of the reference's universal_to_block kernel
        (lib/kvbm-kernels/cuda/tensor_kernels.cu:192).  For an int8 cache
        the fp32 scale planes ride as ksb/vsb [L, nb, bs, nkv] and
        scatter into the sibling scale arrays — the quantized
        representation moves verbatim (bit-exact scales, half the
        payload bytes), never dequantizing en route."""
        if len(kv) == 4:
            k, v, ks, vs = kv
        else:
            k, v = kv
            ks = vs = None
        kb = jnp.transpose(kb, (0, 3, 1, 4, 2))  # -> [L, nkv, nb, hd, bs]
        vb = jnp.transpose(vb, (0, 3, 1, 4, 2))
        k = k.at[:, :, ids].set(kb.astype(k.dtype))
        v = v.at[:, :, ids].set(vb.astype(v.dtype))
        if ks is None:
            return (k, v)
        ksb = jnp.transpose(ksb, (0, 3, 1, 2))   # -> [L, nkv, nb, bs]
        vsb = jnp.transpose(vsb, (0, 3, 1, 2))
        ks = ks.at[:, :, ids].set(ksb.astype(ks.dtype))
        vs = vs.at[:, :, ids].set(vsb.astype(vs.dtype))
        return (k, v, ks, vs)

    @staticmethod
    def _gather_impl(kv, ids):
        """Gather blocks out of the cache into the universal transfer layout
        [L, nb, bs, nkv, hd] (block_to_universal analogue,
        lib/kvbm-kernels/cuda/tensor_kernels.cu:151).  Padded ids read the
        garbage block; the host slices them off.  An int8 cache returns
        (kb, vb, ksb, vsb) with the scale planes in [L, nb, bs, nkv]."""
        if len(kv) == 4:
            k, v, ks, vs = kv
        else:
            k, v = kv
            ks = None
        kb = jnp.transpose(k[:, :, ids], (0, 2, 4, 1, 3))
        vb = jnp.transpose(v[:, :, ids], (0, 2, 4, 1, 3))
        if ks is None:
            return kb, vb
        ksb = jnp.transpose(ks[:, :, ids], (0, 2, 3, 1))
        vsb = jnp.transpose(vs[:, :, ids], (0, 2, 3, 1))
        return kb, vb, ksb, vsb

    @staticmethod
    def _prefill_impl(family, model_cfg, params, kv, tokens, positions,
                      block_table, ctx_len, true_len, seed, temp, top_k,
                      top_p, lora_bank=None, lidx=None):
        lora_kw = ({"lora_bank": lora_bank, "adapter_idx": lidx}
                   if lora_bank is not None else {})
        logits, kv = family.prefill(
            params, model_cfg, kv, tokens, positions, block_table,
            ctx_len, true_len, **lora_kw,
        )
        tok = sample_tokens(
            logits[None], seed[None], jnp.zeros((1,), jnp.int32),
            temp[None], top_k[None], top_p[None],
        )[0]
        return tok, kv

    @staticmethod
    def _prefill_ring_impl(family, model_cfg, mesh, params, kv, toks,
                           positions, block_table, true_len, seed, temp,
                           top_k, top_p):
        """One-shot sequence-parallel prefill + first-token sample (the
        sp analogue of _prefill_impl; ring attention shards the O(T^2)
        attention over the mesh's sp axis)."""
        logits, kv = family.prefill_ring(
            params, model_cfg, kv, toks, positions, block_table,
            true_len, mesh=mesh,
        )
        tok = sample_tokens(
            logits[None], seed[None], jnp.zeros((1,), jnp.int32),
            temp[None], top_k[None], top_p[None],
        )[0]
        return tok, kv

    @staticmethod
    def _prefill_batched_impl(family, model_cfg, params, kv, toks,
                              positions, tables, ctx_lens, true_lens,
                              seeds, temps, top_ks, top_ps,
                              lora_bank=None, lidx=None):
        """Multi-sequence chunked prefill (family prefill_batched):
        concurrent arrivals share one program instead of serializing B=1
        chunks.  First tokens are sampled per row; rows whose prompt is not
        finished this chunk have their sample discarded by the host."""
        lora_kw = ({"lora_bank": lora_bank, "adapter_idx": lidx}
                   if lora_bank is not None else {})
        logits, kv = family.prefill_batched(
            params, model_cfg, kv, toks, positions, tables,
            ctx_lens, true_lens, **lora_kw,
        )
        tok = sample_tokens(
            logits, seeds, jnp.zeros(seeds.shape, jnp.int32), temps,
            top_ks, top_ps,
        )
        return tok, kv

    @staticmethod
    def _prefill_packed_impl(family, model_cfg, mesh, params, kv, toks,
                             positions, seg_ids, tables, last_idx, valid,
                             seeds, temps, top_ks, top_ps,
                             lora_bank=None, lidx=None):
        """Packed multi-sequence chunked prefill (family prefill_packed):
        co-scheduled prompts/chunks run as ONE padding-free token stream
        with segment ids.  First tokens are sampled per segment row; rows
        whose prompt is not finished this chunk have their sample
        discarded by the host.  `mesh` rides to the attention op for the
        Pallas packed kernel's tp shard_map (like _decode_impl)."""
        lora_kw = ({"lora_bank": lora_bank, "adapter_idx": lidx}
                   if lora_bank is not None else {})
        logits, kv = family.prefill_packed(
            params, model_cfg, kv, toks, positions, seg_ids, tables,
            last_idx, valid, mesh=mesh, **lora_kw,
        )
        tok = sample_tokens(
            logits, seeds, jnp.zeros(seeds.shape, jnp.int32), temps,
            top_ks, top_ps,
        )
        return tok, kv

    @staticmethod
    def _spec_verify_impl(family, model_cfg, mesh, params, kv, toks,
                          positions, seg_ids, tables, valid, temps_t):
        """Packed multi-token verification (spec/): every speculating
        sequence's row [last_token, d1..dk] scored in ONE padding-free
        segment-id program (family spec_verify_packed over
        ops/packed_prefill.py), draft-position KV written in place.
        Returns per-position top-CAP candidate ids + temperature-scaled
        logits and the full-vocab logsumexp of the scaled logits — the
        exact ingredients of sampler.py's masked-window categorical, so
        the host-side acceptance test (sampler.spec_accept_tokens) draws
        against the true target distribution."""
        from .sampler import CAP

        logits, kv = family.spec_verify_packed(
            params, model_cfg, kv, toks, positions, seg_ids, tables,
            valid, mesh=mesh,
        )
        scaled = logits / jnp.maximum(temps_t, 1e-6)[:, None]
        vals, ids = jax.lax.top_k(scaled, CAP)
        lse = jax.scipy.special.logsumexp(scaled, axis=-1)
        return ids, vals, lse, kv

    def apply_step(self, kind: str, a: Dict[str, np.ndarray]) -> None:
        """Multi-host follower: execute one broadcast step descriptor —
        the exact jit call the leader ran, on this process's local shards
        (parallel/multihost.py).  Sampled tokens are discarded; only the
        KV/weights state evolution matters on followers."""
        # lora args mirror the leader's calls exactly: when the bank
        # exists both sides pass (bank, lidx) — a one-sided lora arg would
        # compile a DIFFERENT program and desynchronize the collective
        # schedule
        if kind == "prefill_batch":
            lora = ((self.lora_bank, jnp.asarray(a["lidx"]))
                    if self.lora_bank is not None else (None, None))
            _, self.kv = self._jit_prefill_batched(
                self.params, self.kv,
                jnp.asarray(a["toks"]), jnp.asarray(a["positions"]),
                jnp.asarray(a["tables"]), jnp.asarray(a["ctx_lens"]),
                jnp.asarray(a["true_lens"]), jnp.asarray(a["seeds"]),
                jnp.asarray(a["temps"]), jnp.asarray(a["top_ks"]),
                jnp.asarray(a["top_ps"]), *lora,
            )
        elif kind == "prefill_packed":
            lora = ((self.lora_bank, jnp.asarray(a["lidx"]))
                    if self.lora_bank is not None else (None, None))
            _, self.kv = self._jit_prefill_packed(
                self.params, self.kv,
                jnp.asarray(a["toks"]), jnp.asarray(a["positions"]),
                jnp.asarray(a["seg_ids"]), jnp.asarray(a["tables"]),
                jnp.asarray(a["last_idx"]), jnp.asarray(a["valid"]),
                jnp.asarray(a["seeds"]), jnp.asarray(a["temps"]),
                jnp.asarray(a["top_ks"]), jnp.asarray(a["top_ps"]), *lora,
            )
        elif kind == "prefill":
            lora = ((self.lora_bank, jnp.int32(a["lidx"]))
                    if self.lora_bank is not None else (None, None))
            _, self.kv = self._jit_prefill(
                self.params, self.kv,
                jnp.asarray(a["toks"]), jnp.asarray(a["positions"]),
                jnp.asarray(a["block_table"]),
                jnp.int32(a["pos"]), jnp.int32(a["chunk"]),
                jnp.int32(a["seed"]), jnp.float32(a["temp"]),
                jnp.int32(a["top_k"]), jnp.float32(a["top_p"]), *lora,
            )
        elif kind == "decode_topk":
            # guided candidate step: same collective program, result is
            # the leader's to consume
            _, _, self.kv = self._topk_jit()(
                self.params, self.kv, jnp.asarray(a["tokens"]),
                jnp.asarray(a["positions"]), jnp.asarray(a["tables"]),
                jnp.asarray(a["ctx_lens"]), jnp.asarray(a["valid"]),
            )
        elif kind == "decode_topk_wide":
            # widened-M retry: the position's KV rewrite is value-identical
            _, _, self.kv = self._topk_wide_jit()(
                self.params, self.kv, jnp.asarray(a["tokens"]),
                jnp.asarray(a["positions"]), jnp.asarray(a["tables"]),
                jnp.asarray(a["ctx_lens"]), jnp.asarray(a["valid"]),
            )
        elif kind == "spec_verify":
            # speculative verification: the acceptance decision is the
            # leader's; followers only need the identical KV evolution
            _, _, _, self.kv = self._jit_spec_verify(
                self.params, self.kv,
                jnp.asarray(a["toks"]), jnp.asarray(a["positions"]),
                jnp.asarray(a["seg_ids"]), jnp.asarray(a["tables"]),
                jnp.asarray(a["valid"]), jnp.asarray(a["temps_t"]),
            )
        elif kind == "prefill_ring":
            _, self.kv = self._jit_prefill_ring(
                self.params, self.kv, jnp.asarray(a["toks"]),
                jnp.asarray(a["positions"]),
                jnp.asarray(a["block_table"]),
                jnp.int32(a["true_len"]), jnp.int32(a["seed"]),
                jnp.float32(a["temp"]), jnp.int32(a["top_k"]),
                jnp.float32(a["top_p"]),
            )
        elif kind == "lora_write":
            from ..lora.bank import write_adapter

            tensors = {k: v for k, v in a.items() if k != "slot"}
            self.lora_bank = write_adapter(self.lora_bank, int(a["slot"]),
                                           tensors)
        elif kind == "embed":
            # read-only, but a collective program every process must run
            self._run_embed(np.asarray(a["toks"]), int(a["true_len"]))
        elif kind in ("decode", "decode_multi"):
            # _dispatch_decode keeps the follower's device token chain
            # symmetric with the leader's (use_chain lanes resolve to the
            # follower's own previous burst, which is value-identical).
            # Adaptive fusion: the leader's burst size rides the
            # descriptor (falling back to the full fusion for streams
            # from pre-adaptive leaders) — the follower must dispatch the
            # SAME (greedy, k) program or the collective schedule forks.
            k = (int(a.get("k", self.config.decode_fused_steps))
                 if kind == "decode_multi" else 1)
            self._dispatch_decode(k, a)
        elif kind == "decode_cont":
            # continuation bursts ship no arrays: the follower's own
            # device pack (persisted by its preceding full decode replay)
            # advances in-program, exactly like the leader's
            self._dispatch_decode_cont(int(a["k"]), int(a["advance"]),
                                       bool(int(a["greedy"])))
        elif kind == "gather":
            # read-only, but still a collective program every process of
            # the slice must execute (KVBM offload, parked-KV extraction);
            # the result is the leader's to consume
            self._jit_gather(self.kv, jnp.asarray(a["ids"]))
        elif kind == "inject":
            # KVBM onboard or disagg KV pull: payload rides the stream, so
            # followers need no tiers/transport of their own (int8 caches
            # add the ksb/vsb scale planes to the same descriptor)
            scales = ([jnp.asarray(a["ksb"]), jnp.asarray(a["vsb"])]
                      if "ksb" in a else [])
            self.kv = self._jit_inject(
                self.kv, jnp.asarray(a["kb"]), jnp.asarray(a["vb"]),
                jnp.asarray(a["ids"]), *scales,
            )
        else:
            raise ValueError(f"unknown step kind {kind!r}")

    def warmup_decode(self) -> None:
        """Compile every decode-program variant serving can reach — both
        burst sizes (k=1 interleaves with prefill, k=fused in steady
        state), greedy and sampled, full and continuation dispatch — so
        no first-request or mid-serving burst ever eats a 10s+ XLA
        compile (measured: a (greedy, k=1) variant compiling inside the
        serving window cost more than all other scheduler overhead
        combined).  Runs on the caller's thread; call before serving
        traffic (worker startup / bench warm phase).  Prefill buckets
        are NOT warmed here (one per bucket is admission-driven and the
        first request pays exactly one).

        Holds _step_lock for the whole dispatch+restore section: the
        worker serves its generate endpoint (and arms the health-check
        canary) before warmup runs, so a canary probe landing while
        warmup is still compiling starts the scheduler loop — an
        unlocked _sched_step then reads self.kv between two warmup
        dispatches that have already donated it (observed as "Array has
        been deleted" in _prefill_packed and a permanently dead loop
        when decode compiles outlast the canary's 30s wait, e.g. the
        interpret impls on CPU).  Under the lock that step simply waits
        out warmup and sees a consistent engine."""
        B = self.config.max_num_seqs
        zero = {
            "tokens": np.zeros(B, np.int32),
            "use_chain": np.zeros(B, bool),
            "positions": np.zeros(B, np.int32),
            "tables": np.zeros((B, self.config.max_blocks_per_seq),
                               np.int32),
            "ctx_lens": np.ones(B, np.int32),
            "seeds": np.zeros(B, np.int32),
            "steps": np.ones(B, np.int32),
            "top_ks": np.zeros(B, np.int32),
            "top_ps": np.ones(B, np.float32),
            "valid": np.zeros(B, bool),  # nothing real decodes
        }
        if self.lora_bank is not None:
            zero["lidx"] = np.zeros(B, np.int32)
        # every fusion-ladder rung (adaptive bursts ramp through all of
        # them) — a rung missing here is a mid-serving compile later
        ks = self._fuse_ladder()
        with self._step_lock:
            chain0, desc0, last0 = (self._chain_tokens, self._dev_desc,
                                    self._last_desc)
            for greedy in (True, False):
                a = dict(zero, temps=np.full(
                    B, 0.0 if greedy else 0.7, np.float32))
                for k in ks:
                    self._dispatch_decode(k, a)
                    self._dispatch_decode_cont(k, k, greedy)
            jax.block_until_ready(self.kv)
            # warmup bursts wrote nothing (valid all-false) but did
            # advance the chain/descriptor state machinery: reset it
            self._chain_tokens, self._dev_desc, self._last_desc = (
                chain0, desc0, last0)

    # -- request entry ----------------------------------------------------
    def start(self) -> None:
        if self._task is None:
            self._loop_ref = asyncio.get_running_loop()
            self._task = asyncio.create_task(self._loop())

    async def close(self) -> None:
        self._closed = True
        self._wake.set()
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self._fail_all_streams()
        self._inflight.clear()  # drop unread bursts (streams already dead)
        self._pending_first.clear()  # and deferred first-token readbacks
        if self.kvbm is not None:
            # quiesce: a cancelled loop task does not stop a _sched_step
            # already running in its thread, and that step may be mid-write
            # into the G3 dir whose ownership kvbm.close() releases
            await asyncio.to_thread(self._step_lock.acquire)
            self._step_lock.release()
            self.kvbm.close()

    def _fail_all_streams(
        self,
        error: str = "worker engine error: engine loop failed or shut down",
    ) -> None:
        """Terminate every in-flight stream (shutdown or loop crash)."""
        err = LLMEngineOutput(finish_reason="error", error=error)
        with self._qlock:
            stuck = list(self.waiting) + [
                s for s in self._slots if s is not None
            ]
            self.waiting.clear()
        for slot in stuck:
            if not slot.finished:
                slot.finished = True
                # finished=True makes the consumer's teardown skip the
                # cancel request, so ask for it here: if the scheduler is
                # still alive (drain_abort — the loop keeps running),
                # _process_cancellations reaps the slot and frees its KV
                # blocks; a process that stays up after a drain RPC must
                # not leak every aborted slot.  On the loop-crash path
                # nobody processes this, which is moot — close() tears
                # the whole cache down.
                slot.cancel_requested = True
                slot.out_q.put_nowait(err)

    def drain_abort(self) -> None:
        """Graceful-drain deadline: error every in-flight stream with
        the migratable "worker draining" marker so the frontend replays
        each request (token-replay migration) on a surviving worker
        with no client-visible failure."""
        self.draining = True
        # flight recorder: the last N spans are the timeline that led to
        # the abort — dump them before the streams are torn down
        obs.flight_dump("drain_abort")
        self._fail_all_streams(error=DRAIN_ABORT)
        self._wake.set()

    def set_slo_burn(self, burn: float) -> None:
        """SLA-aware admission input: the worst SLO error-budget burn
        rate the frontends currently report (obs/slo.py burn_rates; fed
        by the worker's slo_metrics subscription).  Any-thread safe (two
        atomic float stores); consumed by _prefill_dispatch, where a
        burn above config.slo_yield_burn makes prefill chunks yield
        budget to decode until ITL recovers."""
        self._slo_burn = float(burn)
        self._slo_burn_t = time.monotonic()

    def _effective_slo_burn(self) -> float:
        """The last reported burn, or 0.0 once it has gone stale (a dead
        frontend / disabled SLO plane must not throttle prefill
        forever)."""
        if time.monotonic() - self._slo_burn_t > \
                self.config.slo_burn_stale_s:
            return 0.0
        return self._slo_burn

    @property
    def num_active_seqs(self) -> int:
        return sum(s is not None for s in self._slots) + len(self.waiting)

    def kv_usage(self) -> float:
        return self.allocator.usage()

    def kv_occupancy(self) -> Dict[str, Dict[str, int]]:
        """Block occupancy per storage tier, for the worker's /metrics
        gauges: g1 = the HBM allocator (id 0 is the garbage block, so
        capacity is num_blocks - 1), g2..g4 = the KVBM tiers when
        enabled (kvbm/manager.py occupancy)."""
        a = self.allocator
        usable = a.num_blocks - 1
        out: Dict[str, Dict[str, int]] = {"g1": {
            "used": usable - a.num_free, "free": a.num_free,
            "capacity": usable, "evictable": a.num_evictable,
        }}
        if self.kvbm is not None:
            out.update(self.kvbm.occupancy())
        return out

    def kv_block_bytes(self) -> int:
        """Host-tier bytes one block's payload moves when onboarded
        (all cache components, per physical block) — the numerator of
        the worker's published per-tier onboard costs."""
        try:
            return int(sum(a.nbytes for a in self.kv)
                       // max(1, self.config.num_blocks))
        except Exception:
            return 0

    async def sweep_kvbm_g4(self) -> int:
        """One lineage-driven GC pass over the shared G4 store (called
        from the worker's load loop on a slow cadence, never from the
        scheduler thread — the sweep lists a shared directory).  Hot
        lineages get their TTL clock renewed, dead lineages reap early,
        the rest age by TTL (kvbm/residency.py).  Reaped hashes are
        folded through the consolidator ON the scheduler thread so the
        engine's cross-tier books drop them too — a later re-spill of
        the same hash must re-emit stored(g4) or routers never re-learn
        the blob."""
        if self.kvbm is None or self.kvbm.g4 is None:
            return 0
        if self.kvbm.breaker.state("g4") == "open":
            # the tier is dark: hammering a dead mount from the sweep
            # only delays the half-open probe's clean read
            return 0
        from ..kvbm.residency import LineageResidency

        res = LineageResidency(self.kv_ledger, pool=self.kvbm.g4)
        try:
            swept = await asyncio.to_thread(self.kvbm.g4.sweep, None, res)
        except OSError:
            logger.warning("G4 residency sweep failed", exc_info=True)
            return 0
        if swept:
            def emit() -> int:
                self._emit_tier_events([([], list(swept), "g4")])
                return len(swept)

            await self._call_on_scheduler(emit)
        return len(swept)

    # -- KV integrity (checksummed cache fabric) ---------------------------
    def _note_kv_corruption(self, tier: str, h: Optional[int]) -> None:
        """One checksum-failed consume anywhere in the fabric (G3 pool,
        G4 object store, remote pull, disagg frame): count it for
        dynamo_kv_integrity_failures_total{tier,action="quarantine"} and
        attribute it in the KV ledger (violation kind `corrupt`, flight
        snapshot on each tier's first).  The caller already quarantined
        the bytes and degraded to a miss — serving falls back to
        recompute with byte-identical output, so this hook is purely
        forensic and must never raise."""
        try:
            key = (tier, "quarantine")
            self.kv_integrity[key] = self.kv_integrity.get(key, 0) + 1
            if self.kv_ledger is not None:
                self.kv_ledger.corruption(tier, h)
        except Exception:
            logger.warning("kv corruption attribution failed",
                           exc_info=True)

    def kv_integrity_counters(self) -> Dict[Tuple[str, str], int]:
        """(tier, action) -> count rows for the integrity-failure
        counter: quarantines recorded here + the KVBM manager's I/O
        timeouts/errors."""
        out = dict(self.kv_integrity)
        if self.kvbm is not None:
            for k, v in self.kvbm.io_failure_counters().items():
                out[k] = out.get(k, 0) + v
        return out

    # -- KV ledger audit (obs/kv_ledger.py) --------------------------------
    def _audit_ledger_locked(self, where: str = "step") -> dict:
        """One reconciliation sweep: the ledger's books vs the
        allocator's free-list/refcounts, the scheduler's live slot
        view, and the KVBM pool manifests.  Caller holds _step_lock
        (or IS the step)."""
        led = self.kv_ledger
        if led is None:
            return {}
        live = [self._seq_id(s) for s in self._slots if s is not None]
        with self._qlock:
            live += [self._seq_id(s) for s in self.waiting]
        parked = [p.seq_id for p in self._parked.values()]
        viol = led.audit_allocator(self.allocator, live, parked)
        viol += led.audit_kvbm(self.kvbm)
        return led.finish_audit(viol, where=where)

    def _audit_ledger(self, where: str = "on_demand") -> dict:
        with self._step_lock:
            if self._closed:
                return {}
            return self._audit_ledger_locked(where)

    async def audit_kv(self) -> dict:
        """On-demand reconciliation (the /debug/kv handler's entry
        point); safe on an idle engine — takes the step lock off the
        event loop."""
        if self.kv_ledger is None:
            return {}
        return await asyncio.to_thread(self._audit_ledger)

    @property
    def spec_enabled(self) -> bool:
        """Speculative decoding actually active: the config asked for it
        AND the family supports packed verification (MLA falls back to
        plain decode in v1) — what the worker should advertise, which
        the raw config value alone cannot tell."""
        return self._spec_ok

    async def generate(
        self, request: PreprocessedRequest, token=None
    ) -> AsyncIterator[LLMEngineOutput]:
        self.start()
        if self.draining:
            # reject before admission with the migratable marker: the
            # router may still dispatch here in the window between lease
            # withdrawal and its watch converging
            yield LLMEngineOutput(finish_reason="error", error=DRAIN_REJECT)
            return
        if self._task is not None and self._task.done():
            # the scheduler loop died (crash injection or a real bug):
            # fail fast instead of parking the request forever — the
            # marker classifies as migratable so the frontend replays it
            yield LLMEngineOutput(
                finish_reason="error",
                error="worker engine error: engine loop crashed",
            )
            return
        if len(request.token_ids) >= self.config.max_context:
            yield LLMEngineOutput(
                finish_reason="error",
                error=f"prompt is {len(request.token_ids)} tokens; engine "
                      f"max_context is {self.config.max_context}",
            )
            return
        # after validation: rejected requests cost no engine work and must
        # not inflate the SLA planner's arrival rate / mean ISL
        self.metrics["requests"] += 1
        self.metrics["prompt_tokens"] += len(request.token_ids)
        dp = request.disaggregated_params
        want_pull = dp is not None and dp.get("engine") == "jax"
        if want_pull and self.kv_pull_fn is None:
            logger.warning("disaggregated_params but no kv_pull_fn; "
                           "falling back to local prefill")
            want_pull = False
        if self.kvbm is not None and self.remote_kvbm_fetch is not None:
            try:
                await self._remote_prefetch(request)
            except Exception:
                # remote warm-up is an optimization; local prefill is the
                # always-correct fallback
                logger.warning("remote KVBM prefetch failed for %s",
                               request.request_id, exc_info=True)
        lora_idx = 0
        if request.lora_name:
            if self.lora_bank is None:
                # serving the base model labeled as the adapter would be
                # silently wrong output; fail loud so the frontend
                # migrates / surfaces it
                yield LLMEngineOutput(
                    finish_reason="error",
                    error=f"lora adapter {request.lora_name!r} requested "
                          "but this worker has LoRA disabled "
                          "(lora_max_adapters=0)",
                )
                return
            try:
                lora_idx = await self._resolve_lora(request.lora_name)
            except Exception as e:
                yield LLMEngineOutput(
                    finish_reason="error",
                    error=f"lora adapter {request.lora_name!r}: {e}",
                )
                return
        slot = _Slot(
            index=-1,
            request=request,
            seq=TokenBlockSequence(
                request.token_ids, self.config.block_size,
                salt=request_salt(request.lora_name,
                                  request.media_hashes),
            ),
            out_q=asyncio.Queue(),
            block_table=np.zeros(self.config.max_blocks_per_seq, np.int32),
            sampling_seed=(
                request.sampling.seed
                if request.sampling.seed is not None
                # stable across processes (unlike hash(): PYTHONHASHSEED)
                # so a replayed/migrated request samples the same stream
                else zlib.crc32(request.request_id.encode()) & 0x7FFFFFFF
            ),
            lora_idx=lora_idx,
            enqueued_t=time.monotonic(),
        )
        from ..protocols.llm import DISAGG_ANNOTATION

        slot.disagg_prefill = DISAGG_ANNOTATION in (request.annotations or [])
        if request.sampling.guided_json is not None:
            from ..guided import JsonSchemaGuide

            slot.guide = JsonSchemaGuide(request.sampling.guided_json)
        pull_task = None
        if want_pull:
            slot.pulling = True
            slot.admitted = asyncio.Event()
        if self.kv_ledger is not None:
            # ledger tape entries for this sequence join the request's
            # distributed trace (frontend-minted traceparent annotation)
            self.kv_ledger.bind_seq(
                request.request_id,
                obs.trace_id_from_annotations(request.annotations))
        with self._qlock:
            slot.queue_pos = len(self.waiting)
            self.waiting.append(slot)
        if lora_idx:
            # enqueued: the waiting/_slots scan now holds the reference
            self._lora_pins[lora_idx] -= 1
        self._wake.set()
        if want_pull:
            # streaming pull: chunk injects interleave with decode steps;
            # on any failure the slot falls back to local prefill
            pull_task = asyncio.create_task(self._stream_pull(slot, dp))
        from ..runtime.aio import CANCELLED, next_or_cancel

        try:
            while True:
                item = await next_or_cancel(
                    slot.out_q,
                    token.stopped_event if token is not None else None,
                )
                if item is CANCELLED:
                    slot.cancel_requested = True
                    self._wake.set()
                    yield LLMEngineOutput(finish_reason="cancelled")
                    return
                yield item
                if item.finish_reason is not None:
                    return
        finally:
            if pull_task is not None and not pull_task.done():
                pull_task.cancel()
            if not slot.finished:
                # actual teardown happens on the scheduler thread
                slot.cancel_requested = True
                self._wake.set()

    def _process_cancellations(self) -> None:
        """Runs on the scheduler thread at the top of every step."""
        with self._qlock:
            for slot in list(self.waiting):
                if slot.cancel_requested:
                    self.waiting.remove(slot)
                    slot.finished = True
        for i, slot in enumerate(self._slots):
            if slot is not None and slot.cancel_requested:
                slot.finished = True
                self._slots[i] = None
                self._emit_events(self.allocator.free(self._seq_id(slot)))
                # membership changed mid-stretch: de-fuse so the freed
                # lane's capacity returns to useful work within a short
                # burst (adaptive fusion ramps back up afterwards)
                self._decode_only_run = 0

    def _seq_id(self, slot: _Slot) -> str:
        return slot.request.request_id

    def _emit_events(self, res, tier: str = "g1") -> None:
        """Thread-safe KV event emission (called from the scheduler thread).

        Mutations are first folded through the cross-tier consolidator so
        routers see net PER-TIER residency (stored on entering a tier,
        removed on leaving it — duplicate same-tier mutations net out; the
        tier-aware index derives union ownership router-side).  The sink
        may be synchronous (preferred: enqueue +
        serialized publish, see KvEventPublisher.enqueue_batch) or an async
        callable.  Either way it is invoked on the loop thread via
        call_soon_threadsafe, whose FIFO callback ordering keeps wire order
        equal to mutation order."""
        if res is None:
            return
        stored = list(getattr(res, "stored", []))
        removed = list(getattr(res, "removed", []))
        if not (stored or removed):
            return
        if tier != "g1" and self.kv_ledger is not None:
            # KVBM tier membership for the ledger auditor (pre-netting:
            # the ledger reconciles per-tier against the pool manifests;
            # g1 transitions are recorded inside the allocator itself)
            self.kv_ledger.tier_batch(stored, removed, tier)
        # G1 evictions of blocks that were offloaded must not drop the G2/G3
        # copy — the consolidator handles the netting; the pools themselves
        # only drop on their own capacity pressure.
        net_stored, net_removed, _ = self._consolidator.apply(
            stored, removed, tier
        )
        self._dispatch_events(net_stored, net_removed, tier)

    def _emit_tier_events(self, batches) -> None:
        """Emit [(stored, removed, tier), ...] batches from the KVBM manager
        (already per-tier; still netted through the consolidator)."""
        for stored, removed, tier in batches:
            self._emit_events(
                SimpleNamespace(stored=stored, removed=removed), tier=tier
            )

    def _dispatch_events(self, stored, removed, tier: str) -> None:
        if self.kv_event_sink is None or not (stored or removed):
            return
        sink = self.kv_event_sink
        takes_tier = self._sink_takes_tier

        def call():
            return sink(stored, removed, tier) if takes_tier \
                else sink(stored, removed)

        def dispatch():
            r = call()
            if inspect.isawaitable(r):
                from ..runtime.aio import spawn_retained

                spawn_retained(r, self._event_tasks)

        if self._loop_ref is not None:
            self._loop_ref.call_soon_threadsafe(dispatch)
        else:
            # pre-start only (no loop yet): nothing is routing yet, so an
            # async sink's events can be dropped safely
            r = call()
            if inspect.isawaitable(r):
                r.close()

    async def _resolve_lora(self, name: str) -> int:
        """Map an adapter name to its bank slot, lazily loading from
        lora_dir on first use.  Eviction is LRU among adapters not
        referenced by any active/waiting sequence OR pinned by a resolved
        request that hasn't enqueued yet (the pin closes the window where
        an eviction could silently swap the adapter under a request).
        All registry mutations run on the scheduler thread; the file load
        runs in an executor so streams never stall on it.
        Ref: lora/cache.rs + controller.rs, collapsed into lazy
        load-on-first-request (routing.py explains why no load RPCs)."""

        def lookup() -> Optional[int]:
            idx = self._lora_slots.get(name)
            if idx is not None:
                self._lora_lru.remove(name)
                self._lora_lru.append(name)
                self._lora_pins[idx] = self._lora_pins.get(idx, 0) + 1
            return idx

        idx = await self._call_on_scheduler(lookup)
        if idx is not None:
            return idx
        if self._lora_source is None:
            raise ValueError("unknown adapter (engine has no lora_dir)")
        loop = asyncio.get_running_loop()
        adapter = await loop.run_in_executor(
            None,
            lambda: self._lora_source.load(
                name, self.model_cfg.n_layers
            ).padded_to(self.config.lora_rank))

        def install() -> int:
            existing = self._lora_slots.get(name)
            if existing is not None:  # raced with another request
                self._lora_pins[existing] = \
                    self._lora_pins.get(existing, 0) + 1
                return existing
            in_use = {s.lora_idx for s in self._slots if s is not None}
            with self._qlock:
                in_use |= {s.lora_idx for s in self.waiting}
            in_use |= {i for i, c in self._lora_pins.items() if c > 0}
            free = (set(range(1, self.config.lora_max_adapters + 1))
                    - set(self._lora_slots.values()))
            if free:
                slot = min(free)
            else:
                victim = next(
                    (n for n in self._lora_lru
                     if self._lora_slots[n] not in in_use), None)
                if victim is None:
                    raise RuntimeError(
                        "all adapter slots are referenced by active "
                        "sequences; raise lora_max_adapters")
                slot = self._lora_slots.pop(victim)
                self._lora_lru.remove(victim)
            from ..lora.bank import write_adapter

            if self.step_sink is not None:
                # bank mutations ride the step stream: followers apply the
                # same write so every process's adapter bank (a jit input)
                # stays bit-identical with the leader's
                self.step_sink("lora_write", {
                    "slot": np.int32(slot),
                    # dynlint: disable=DYN011 adapter tensors are host-loaded numpy, not device arrays
                    **{k: np.asarray(v) for k, v in
                       adapter.tensors.items()},
                })
            self.lora_bank = write_adapter(self.lora_bank, slot,
                                           adapter.tensors)
            self._lora_slots[name] = slot
            self._lora_lru.append(name)
            self._lora_pins[slot] = self._lora_pins.get(slot, 0) + 1
            logger.info("lora adapter %r loaded into slot %d (rank %d)",
                        name, slot, adapter.rank)
            return slot

        return await self._call_on_scheduler(install)

    def _call_on_scheduler(self, fn) -> asyncio.Future:
        """Run `fn()` between scheduler steps (the allocator and KV cache are
        owned by the scheduler; cross-thread access would race donation)."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._sched_calls.append((fn, fut))
        self._wake.set()
        if self._task is None or self._task.done():
            # no live loop to drain for us (unstarted, crashed, or closed)
            self._drain_sched_calls()
        return fut

    def _drain_sched_calls(self) -> None:
        while self._sched_calls:
            fn, fut = self._sched_calls.pop(0)
            try:
                result = fn()
            except Exception as e:  # surface to the caller
                err = e

                def set_exc(f=fut, err=err):
                    if not f.done():
                        f.set_exception(err)

                if self._loop_ref is not None:
                    self._loop_ref.call_soon_threadsafe(set_exc)
                else:
                    set_exc()
            else:
                if self._loop_ref is not None:
                    self._loop_ref.call_soon_threadsafe(
                        _set_result_safe, fut, result
                    )
                else:
                    _set_result_safe(fut, result)

    @property
    def supports_embedding(self) -> bool:
        return hasattr(self.family, "embed_text")

    async def embed(self, token_ids: List[int]) -> np.ndarray:
        """Pooled text embedding (family embed_text), bucketed like
        prefill so repeat lengths hit the jit cache."""
        if not self.supports_embedding:
            raise RuntimeError(
                f"model family {self.family.__name__} has no embed_text")
        if len(token_ids) > self.config.prefill_buckets[-1]:
            raise ValueError(
                f"input is {len(token_ids)} tokens; embedding max is "
                f"{self.config.prefill_buckets[-1]}")
        bucket = self._bucket_for(len(token_ids))
        toks = np.zeros(bucket, np.int32)
        toks[: len(token_ids)] = token_ids
        true_len = len(token_ids)

        def run():
            if self.step_sink is not None:
                # a collective program every process of the slice must
                # execute — embed rides the step stream like everything
                # else (the result is the leader's to consume)
                self.step_sink("embed", {"toks": toks,
                                         "true_len": np.int32(true_len)})
            return self._run_embed(toks, true_len)

        self.start()
        return await self._call_on_scheduler(run)

    def _run_embed(self, toks: np.ndarray, true_len: int) -> np.ndarray:
        jit = getattr(self, "_jit_embed", None)
        if jit is None:
            jit = self._jit_embed = self.compile_watch.wrap(jax.jit(
                partial(self.family.embed_text, self.params,
                        self.model_cfg)), "embed",
                tokens_of=lambda a: a[0].shape[0])
        with self.mesh:
            vec = jit(jnp.asarray(toks), jnp.int32(true_len))
            t_obs = obs.begin()
            out = np.asarray(vec, np.float32)
            obs.end("device_wait", t_obs, track=self._obs_track,
                    what="embed_fetch")
            return out

    async def clear_kv_blocks(self) -> int:
        """Drop the reusable prefix cache (active sequences keep theirs)."""
        def do_clear():
            removed = self.allocator.clear_cached()
            # emit from the scheduler thread so these removals stay ordered
            # against stores from the next step (a later stored(H) for a
            # re-admitted prefix must reach the wire after this removed(H))
            self._emit_events(SimpleNamespace(stored=[], removed=removed))
            if self.kvbm is not None:
                self._emit_tier_events(self.kvbm.clear())
            return removed

        removed = await self._call_on_scheduler(do_clear)
        return len(removed)

    # -- disaggregation: parked prefills + KV extraction -------------------
    def kv_wire_layout(self, n_blocks: int = 0):
        """This engine's KvLayout for wire headers/validation, derived from
        its OWN cache arrays (family-agnostic: GQA k==v shapes, MLA
        latent/rope-key pair with different head dims)."""
        from ..disagg.transfer import KvLayout

        k_cache, v_cache = self.kv[0], self.kv[1]
        return KvLayout(
            num_layers=k_cache.shape[0], num_blocks=n_blocks,
            block_size=self.config.block_size,
            kv_heads=k_cache.shape[1], head_dim=k_cache.shape[3],
            dtype=np.dtype(k_cache.dtype).name,
            tp=self.config.tp, dp=self.config.dp,
            head_dim_v=(v_cache.shape[3]
                        if v_cache.shape[3] != k_cache.shape[3] else 0),
            scales=is_quantized(self.kv),
        )

    def universal_shardings(self):
        """Per-component NamedShardings for universal-layout chunks on
        this engine's mesh: the cache's head-axis sharding moved to the
        universal head axis (data [L, nb, bs, nkv, hd]; int8 scale
        planes [L, nb, bs, nkv]).  Device-resident pulls land chunks
        here so inject consumes them without a host bounce.  Tuple arity
        matches the cache's (2 or 4)."""
        k_spec, v_spec = self.family.kv_cache_specs()
        # cache layout [L, H, NB, HD, BS] -> universal [L, NB, BS, H, HD];
        # MLA families use an empty spec (replicated latent cache)
        kh = k_spec[1] if len(k_spec) > 1 else None
        vh = v_spec[1] if len(v_spec) > 1 else None
        out = [NamedSharding(self.mesh, P(None, None, None, kh, None)),
               NamedSharding(self.mesh, P(None, None, None, vh, None))]
        if is_quantized(self.kv):
            out += [NamedSharding(self.mesh, P(None, None, None, kh)),
                    NamedSharding(self.mesh, P(None, None, None, vh))]
        return tuple(out)

    async def parked_info(self, request_id: str):
        """(n_blocks, prompt_len) of a parked prefill (pull 'open' op)."""

        def info():
            parked = self._parked.get(request_id)
            if parked is None:
                raise KeyError(f"no parked KV for request {request_id!r}")
            return len(parked.block_ids), parked.prompt_len

        return await self._call_on_scheduler(info)

    async def extract_parked_chunk(self, request_id: str, start: int,
                                   count: int, *, to_host: bool = True):
        """Gather blocks [start, start+count) of a parked prefill in the
        universal transfer layout — ONE scheduler op per chunk, so decode
        bursts interleave with a long extraction instead of stalling
        behind a whole-prompt gather (the round-3 ITL-spike finding).

        to_host=False keeps the gathered chunk device-resident for the
        device-to-device tiers (broker / transfer server)."""

        def gather():
            parked = self._parked.get(request_id)
            if parked is None:
                raise KeyError(f"no parked KV for request {request_id!r}")
            chunk_ids = parked.block_ids[start:start + count]
            if len(chunk_ids) != count:
                raise ValueError(
                    f"chunk [{start},{start + count}) out of range for "
                    f"{len(parked.block_ids)} parked blocks")
            ids = _pow2_ids(chunk_ids)
            if self.step_sink is not None:
                # reads are collective programs too: every process of the
                # slice must execute the same gather or it hangs
                self.step_sink("gather", {"ids": ids})
            arrs = self._jit_gather(self.kv, jnp.asarray(ids))
            # axis 1 is the block axis for every component (data AND the
            # int8 scale planes): slice the pow2 padding off uniformly
            arrs = tuple(a[:, :count] for a in arrs)
            if to_host:
                t_d = obs.begin()
                out = tuple(np.asarray(a) for a in arrs)
                obs.end("device_wait", t_d, track=self._obs_track,
                        what="parked_extract")
                return out
            return arrs

        return await self._call_on_scheduler(gather)

    async def release_parked(self, request_id: str) -> None:
        def release():
            parked = self._parked.pop(request_id, None)
            if parked is not None:
                if self.kv_ledger is not None:
                    self.kv_ledger.unpark(parked.seq_id)
                self._emit_events(self.allocator.free(parked.seq_id))

        await self._call_on_scheduler(release)

    def _reap_parked(self) -> None:
        now = time.monotonic()
        for rid in [r for r, p in self._parked.items()
                    if now > p.expires_t]:
            logger.warning("parked KV for %s expired unpulled", rid)
            parked = self._parked.pop(rid)
            if self.kv_ledger is not None:
                self.kv_ledger.unpark(parked.seq_id)
            self._emit_events(self.allocator.free(parked.seq_id))

    # -- scheduler loop ---------------------------------------------------
    async def _loop(self) -> None:
        try:
            while not self._closed:
                if self._sched_calls:
                    # heavy calls (KV gathers) run off the event loop; no
                    # scheduler step is in flight while we await this
                    await asyncio.to_thread(self._drain_sched_calls)
                self._reap_parked()
                # a slot mid-pull has no step work of its own (its chunk
                # injects arrive as sched_calls, which set _wake): don't
                # hot-spin the step loop on its behalf — EXCEPT when its
                # cancellation is pending, which needs one step to reap
                # it (_process_cancellations); without that carve-out a
                # request cancelled mid-pull on an otherwise idle worker
                # held its KV blocks until unrelated traffic arrived
                busy = (any(s is not None
                            and (not s.pulling or s.cancel_requested)
                            for s in self._slots)
                        or bool(self._inflight))
                if not busy and not self.waiting:
                    self._wake.clear()
                    if self._sched_calls:
                        continue
                    if self.kv_ledger is not None \
                            and self.kv_ledger.audit_due(5.0):
                        # idle-tick reconciliation: an idle worker's
                        # books still get swept (leaks hide best in
                        # caches nobody is touching)
                        await asyncio.to_thread(self._audit_ledger,
                                                "idle")
                    if self._parked:
                        # wake periodically so the parked-KV TTL reaper runs
                        # even on an otherwise idle worker
                        try:
                            await asyncio.wait_for(self._wake.wait(), 5.0)
                        except asyncio.TimeoutError:
                            pass
                    else:
                        await self._wake.wait()
                    continue
                t0 = time.monotonic()
                await asyncio.to_thread(self._sched_step)
                self.metrics["step_time_s"] = time.monotonic() - t0
                self.metrics["steps"] += 1
                await asyncio.sleep(0)  # yield to the event loop
        except asyncio.CancelledError:
            pass
        except Exception:
            logger.exception("engine loop crashed")
            obs.flight_dump("engine_crash")
            self._fail_all_streams()
            raise

    def _sched_step(self) -> None:
        """One scheduler iteration, entirely on the worker thread.

        vLLM-style interleaving: admit any number of waiting requests
        (allocation only), run at most ONE budget-capped prefill chunk, then
        a decode step for every slot past prefill — so a long prompt never
        stalls active decodes for more than one chunk's compute
        (the head-of-line blocking the round-1 verdict called out).

        _step_lock lets close() wait out an in-flight step (cancelling the
        loop task does not stop an already-running thread) before releasing
        resources a step may be mid-write on, e.g. the G3 cache dir.  The
        _closed check under the lock closes the remaining window: a step
        whose thread started but had not yet acquired the lock when close()
        swept through must not touch the released resources."""
        with self._step_lock:
            if self._closed:
                return
            # chaos seam: crash ("fail") or wedge the scheduler on step
            # N — the loop's crash handler fails all streams with the
            # migratable worker-engine-error marker; a wedge is caught
            # by the canary (health_check.py)
            chaos.hit("engine.step", key=self.config.served_name)
            # timeline spans (obs/): one `step` covering the iteration,
            # `sched` over the host-only scheduling work; the dispatch
            # phases emit their own spans inside.  Each is one
            # module-global None check when tracing is off.
            # Overlapped mode: when unread bursts are in flight the
            # device is still executing them, so this host scheduling
            # work is OVERLAPPED, not overhead — it reports as
            # `enqueue_ahead` (report.py excludes it from
            # sched_overhead_frac; the wall partition stays exact).
            t_step = obs.begin()
            t = obs.begin()
            overlapped = self._overlap and bool(self._inflight)
            self._process_cancellations()
            self._maybe_offload()
            self._admit_waiting()
            obs.end("enqueue_ahead" if overlapped else "sched", t,
                    track=self._obs_track)
            # deferred first tokens from the PREVIOUS step's completing
            # prefills: flushed before this step's dispatches, so the
            # blocking fetch pays only for work the device has had a
            # full step to finish (overlap mode; sync fetches inline)
            self._flush_pending_first()
            self._prefill_step()
            self._guided_step()
            self._spec_step()
            if any(s is not None and not s.prefilling
                   and not s.awaiting_first for s in self._slots):
                self._decode_step()
            elif self._inflight:
                # no dispatchable decode work: flush the pipeline tail so
                # trailing tokens/finishes are delivered promptly
                self._drain_inflight()
            led = self.kv_ledger
            if led is not None and led.audit_due():
                # reconciliation sweep on the finish cadence (a request
                # freed its blocks since the last audit) — the books are
                # checked while the leak is one request old, not one
                # incident old
                self._audit_ledger_locked("step")
            if t_step:  # attrs are only worth computing when tracing
                obs.end("step", t_step, track=self._obs_track,
                        active=sum(1 for s in self._slots
                                   if s is not None),
                        waiting=len(self.waiting))

    # -- distributed KVBM (kvbm/remote.py) ---------------------------------
    async def _remote_prefetch(self, request: PreprocessedRequest) -> None:
        """Pull this prompt's missing leading blocks from a peer's host
        cache and stage them into the LOCAL G2, so admission's existing
        G2 onboarding path finds them — no scheduler-thread changes.
        Racy local-presence checks are safe: the worst case is pulling a
        block that arrived locally meanwhile (the stage skips it)."""
        from ..tokens import compute_block_hashes_for_request

        hashes = compute_block_hashes_for_request(
            request.token_ids, self.config.block_size,
            lora_name=request.lora_name,
            media_hashes=request.media_hashes,
        )
        start = 0
        while start < len(hashes) and hashes[start] in self.kvbm:
            start += 1
        if start >= len(hashes):
            return
        blocks = await self.remote_kvbm_fetch(hashes[start:])
        if not blocks:
            return

        def stage() -> int:
            n = 0
            arity = len(self.kv)
            for h, *arrays in blocks:
                if h in self.kvbm:
                    continue
                if len(arrays) != arity:
                    # peer runs the other cache dtype (mixed fleet): its
                    # payload cannot scatter into this cache — skip, the
                    # leading-run contract makes the tail unusable too
                    break
                self._emit_tier_events(self.kvbm.offload(h, *arrays))
                n += 1
            return n

        staged = await self._call_on_scheduler(stage)
        if staged:
            self.metrics["remote_onboarded"] = (
                self.metrics.get("remote_onboarded", 0) + staged)
            logger.info("staged %d remote KV blocks for %s", staged,
                        request.request_id)

    def read_host_blocks(self, hashes: List[int]):
        """Serve a peer's pull: fetch each block from the local tiers
        (promoting to G2 — a peer pulling it marks the prefix hot) until
        the first miss.  Runs between scheduler steps."""

        def read():
            out = []
            for h in hashes:
                blk, events, _src = self.kvbm.fetch(h) \
                    if self.kvbm is not None else (None, [], None)
                self._emit_tier_events(events)
                if blk is None:
                    break
                out.append((h, *blk))
            return out

        return self._call_on_scheduler(read)

    # -- KVBM offload/onboard ----------------------------------------------
    def _maybe_offload(self) -> None:
        """Copy the coldest evictable HBM blocks to the G2 host tier before
        eviction pressure destroys them.  One batched gather per step; the
        blocks stay live in G1 (offload is a copy, not a move), so there is
        no correctness window."""
        if self.kvbm is None or self.allocator.num_free >= self._offload_watermark:
            return
        cands = self.allocator.coldest_evictable(
            self.config.offload_batch, exclude=self.kvbm.offload_skip,
            scan_limit=4 * self.config.offload_batch + 64,
        )
        if not cands:
            return
        t_obs = obs.begin()
        ids = _pow2_ids([bid for _, bid in cands])
        if self.step_sink is not None:
            self.step_sink("gather", {"ids": ids})
        t_d = obs.begin()
        arrs = [np.asarray(a)
                for a in self._jit_gather(self.kv, jnp.asarray(ids))]
        obs.end("device_wait", t_d, track=self._obs_track,
                what="offload_gather")
        for i, (h, _) in enumerate(cands):
            # contiguous copies: a [:, i] view would pin the whole gathered
            # batch buffer in host RAM for as long as any one block lives.
            # int8 caches offload (k, v, k_scale, v_scale) per block —
            # half the host-tier bytes, scales bit-exact (kvbm/pools.py)
            self._emit_tier_events(self.kvbm.offload(
                h, *(np.ascontiguousarray(a[:, i]) for a in arrs)))
        obs.end("kvbm_offload", t_obs, track=self._obs_track,
                blocks=len(cands))

    def _try_onboard(self, slot: _Slot, hit: int, cap_blocks: int) -> int:
        """Extend a G1 prefix hit with blocks onboarded from G2/G3/G4:
        scatter their payloads into the freshly allocated HBM blocks
        instead of recomputing prefill.  match_run (and the fetch walk)
        reach through the shared object store, so a cold worker under
        shared-prefix load onboards the fleet's history — the G4 path the
        tiered router prices and routes to.  Returns the number of blocks
        onboarded."""
        if self.kvbm is None:
            return 0
        hashes = slot.seq.block_hashes
        run = self.kvbm.match_run(hashes[hit:cap_blocks])
        if run == 0:
            return 0
        t_obs = obs.begin()
        block_ids = self.allocator.seq_block_ids(self._seq_id(slot))
        arity = len(self.kv)
        comps: List[list] = [[] for _ in range(arity)]
        ids = []
        by_tier: Dict[str, int] = {}
        for i in range(hit, hit + run):
            blk, events, src = self.kvbm.fetch(hashes[i])
            self._emit_tier_events(events)
            if blk is None:  # dropped from the pool mid-walk
                break
            if len(blk) != arity:
                # a block staged from a peer running the OTHER cache
                # dtype (mixed fleet): scatter-without-scales would be
                # silent corruption — treat as a miss and recompute
                logger.warning(
                    "KVBM block %x has %d payload arrays but the cache "
                    "expects %d (kv dtype mismatch); recomputing",
                    hashes[i], len(blk), arity)
                break
            for c, arr in zip(comps, blk):
                c.append(arr)
            ids.append(block_ids[i])
            if src is not None:
                by_tier[src] = by_tier.get(src, 0) + 1
                if self.kv_ledger is not None:
                    self.kv_ledger.onboard(hashes[i], src,
                                           seq=self._seq_id(slot))
        if not ids:
            return 0
        n = len(ids)
        ids_arr = _pow2_ids(ids)
        bucket = len(ids_arr)
        stacked = []
        for c in comps:
            pad = [(0, 0), (0, bucket - n)] + [(0, 0)] * (c[0].ndim - 1)
            stacked.append(np.pad(np.stack(c, axis=1), pad))
        if self.step_sink is not None:
            # onboard payloads ride the wire so followers need no KVBM
            # tiers of their own — their self.kv evolves from the stream
            desc = {"kb": stacked[0], "vb": stacked[1], "ids": ids_arr}
            if arity == 4:
                desc["ksb"], desc["vsb"] = stacked[2], stacked[3]
            self.step_sink("inject", desc)
        self.kv = self._jit_inject(
            self.kv, *(jnp.asarray(a) for a in stacked[:2]),
            jnp.asarray(ids_arr), *(jnp.asarray(a) for a in stacked[2:])
        )
        for src, cnt in by_tier.items():
            key = f"kv_onboard_{src}"
            self.metrics[key] = self.metrics.get(key, 0) + cnt
        obs.end("kvbm_onboard", t_obs, track=self._obs_track, blocks=n,
                tokens=n * self.config.block_size,
                **{f"from_{s}": c for s, c in by_tier.items()})
        return n

    # -- prefill ----------------------------------------------------------
    def _bucket_for(self, n: int) -> int:
        for b in self.config.prefill_buckets:
            if n <= b:
                return b
        return self.config.prefill_buckets[-1]

    def _admit_waiting(self) -> None:
        """Move waiting requests into free slots (block allocation + prefix
        cache lookup; no model compute)."""
        while True:
            with self._qlock:
                if not self.waiting:
                    return
                free_idx = next(
                    (i for i, s in enumerate(self._slots) if s is None), None
                )
                if free_idx is None:
                    return
                slot = self.waiting[0]
                c = self.config
                prompt_len = len(slot.seq)
                hashes = slot.seq.block_hashes
                # never reuse the whole prompt: the last token must be
                # computed to produce first-token logits
                cap_blocks = max(0, (prompt_len - 1) // c.block_size)
                res = self.allocator.allocate(
                    self._seq_id(slot), hashes[:cap_blocks],
                    slot.seq.num_blocks,
                )
                if res is None:
                    return  # capacity: stay in queue (FIFO)
                self.waiting.pop(0)
            self._emit_events(res)
            slot.index = free_idx
            self._slots[free_idx] = slot
            bids = res.block_ids
            slot.block_table[: len(bids)] = bids
            slot.committed_blocks = res.cached_blocks
            # extend the G1 hit with G2/G3 onboarding (KV scattered back
            # into HBM instead of recomputed)
            onboarded = self._try_onboard(slot, res.cached_blocks, cap_blocks)
            for i in range(res.cached_blocks, res.cached_blocks + onboarded):
                cres = self.allocator.commit_block(
                    self._seq_id(slot), i, slot.seq.block_hashes[i]
                )
                self._emit_events(cres)
                slot.committed_blocks = i + 1
            total_cached = res.cached_blocks + onboarded
            cached_tokens = total_cached * c.block_size
            slot.cached_tokens = cached_tokens
            self.metrics["cache_hit_tokens"] += cached_tokens
            if onboarded:
                self.metrics["onboarded_tokens"] = (
                    self.metrics.get("onboarded_tokens", 0)
                    + onboarded * c.block_size
                )
            slot.ctx_len = cached_tokens
            slot.prompt_len = prompt_len
            slot.prefill_pos = cached_tokens

            # disagg decode: wake the pull task now that blocks exist; the
            # slot idles (prefill/decode skip it) while chunk injects
            # stream in between steps
            if slot.pulling and slot.admitted is not None \
                    and self._loop_ref is not None:
                self._loop_ref.call_soon_threadsafe(slot.admitted.set)

    def _prefill_step(self) -> None:
        """Run prefill chunks for up to max_prefill_seqs prefilling slots
        (earliest-enqueued first) in ONE program, the step's total token
        count capped near the chunk budget (chunks + one decode token per
        active slot).  Default path: PACKED chunked prefill — every
        co-scheduled chunk concatenates into one padding-free token
        stream with segment ids (engine/prefill.py planner).  Families
        without prefill_packed (and capacity-MoE configs) fall back to
        the padded B=1 / batched programs; cold long prompts on an sp
        mesh still take the one-shot ring program."""
        pslots = sorted(
            (s for s in self._slots
             if s is not None and s.prefilling and not s.pulling),
            key=lambda s: s.enqueued_t,
        )[: self.config.max_prefill_seqs]
        if not pslots:
            return
        t_obs = obs.begin()
        try:
            self._prefill_dispatch(pslots)
        finally:
            extra = self._obs_dispatch_extra or {}
            self._obs_dispatch_extra = None
            obs.end("prefill_dispatch", t_obs, track=self._obs_track,
                    rows=len(pslots), **extra)

    def _prefill_dispatch(self, pslots) -> None:
        """Route this step's prefilling slots to one program (see
        _prefill_step; split out so the dispatch span covers every
        path)."""
        c = self.config
        self.metrics["prefill_steps"] = \
            self.metrics.get("prefill_steps", 0) + 1
        decoding = sum(
            1 for s in self._slots if s is not None and not s.prefilling
        )
        budget = max(c.chunk_budget - decoding, c.prefill_buckets[0])
        # SLA-aware admission (the PR 1 mixed-scheduling loop closed
        # against the PR 7 SLO plane): when the frontier burn rate says
        # the ITL/TTFT error budget is burning faster than allowed AND
        # decodes are live, prefill yields chunk budget to decode —
        # scaled by threshold/burn, floored at the smallest bucket so
        # prefill always advances (no livelock, TTFT degrades gradually
        # instead of decode ITL collapsing).
        if c.slo_yield_burn > 0 and decoding:
            burn = self._effective_slo_burn()
            if burn > c.slo_yield_burn:
                budget = max(int(budget * c.slo_yield_burn / burn),
                             c.prefill_buckets[0])
                self.metrics["slo_yield_steps"] = \
                    self.metrics.get("slo_yield_steps", 0) + 1
        if len(pslots) == 1 and self._ring_eligible(pslots[0]):
            # long-context path (see _prefill_one's rationale)
            self._prefill_ring_one(pslots[0])
            return
        if self._packed_prefill_ok:
            self._prefill_packed_step(pslots, budget)
            return
        if len(pslots) == 1:
            self._prefill_one(pslots[0], budget)
            return

        # Equal budget shares, NO donation of leftovers: every row pads to
        # the largest chunk's bucket, so letting one row grow past the
        # share would multiply the whole batch's padded compute (n×bucket)
        # far beyond the budget that bounds decode ITL.  When the budget is
        # too tight to give every row the minimum bucket, batch FEWER slots
        # this step (earliest first) rather than multiplying the floor by
        # n — total compute stays ≤ n·bucket(share) ≤ ~2·budget either way.
        n = max(1, min(len(pslots), budget // c.prefill_buckets[0]))
        pslots = pslots[:n]
        if n == 1:
            self._prefill_one(pslots[0], budget)
            return
        share = max(budget // n, c.prefill_buckets[0])
        chunks = [min(c.prefill_buckets[-1], share,
                      s.prompt_len - s.prefill_pos) for s in pslots]

        bucket = self._bucket_for(max(chunks))
        Bp = _pow2_len(n)
        toks = np.zeros((Bp, bucket), np.int32)
        positions = np.zeros((Bp, bucket), np.int32)
        tables = np.zeros((Bp, c.max_blocks_per_seq), np.int32)
        ctx_lens = np.zeros(Bp, np.int32)
        true_lens = np.zeros(Bp, np.int32)
        seeds = np.zeros(Bp, np.int32)
        temps = np.zeros(Bp, np.float32)
        top_ks = np.zeros(Bp, np.int32)
        top_ps = np.ones(Bp, np.float32)
        for i, (slot, chunk) in enumerate(zip(pslots, chunks)):
            pos = slot.prefill_pos
            toks[i, :chunk] = slot.seq.tokens[pos: pos + chunk]
            positions[i] = pos + np.arange(bucket, dtype=np.int32)
            tables[i] = slot.block_table
            ctx_lens[i] = pos
            true_lens[i] = chunk
            s = slot.request.sampling
            seeds[i] = slot.sampling_seed
            temps[i] = s.temperature
            top_ks[i] = s.top_k
            top_ps[i] = s.top_p
        lidx = np.zeros(Bp, np.int32)
        for i, (slot, _) in enumerate(zip(pslots, chunks)):
            lidx[i] = slot.lora_idx
        if self.step_sink is not None:
            self.step_sink("prefill_batch", {
                "toks": toks, "positions": positions,
                "tables": tables, "ctx_lens": ctx_lens,
                "true_lens": true_lens, "seeds": seeds, "temps": temps,
                "top_ks": top_ks, "top_ps": top_ps,
                **({"lidx": lidx} if self.lora_bank is not None else {}),
            })
        tok, self.kv = self._jit_prefill_batched(
            self.params, self.kv,
            jnp.asarray(toks), jnp.asarray(positions), jnp.asarray(tables),
            jnp.asarray(ctx_lens), jnp.asarray(true_lens),
            jnp.asarray(seeds), jnp.asarray(temps), jnp.asarray(top_ks),
            jnp.asarray(top_ps), self.lora_bank,
            jnp.asarray(lidx) if self.lora_bank is not None else None,
        )
        self._fpm_prefill(
            rows=n, tokens=int(sum(chunks)), bucket=bucket,
            completing=sum(1 for s, ch in zip(pslots, chunks)
                           if s.prefill_pos + ch >= s.prompt_len),
            xla=self._jit_prefill_batched.cost(Bp * bucket))
        # the sampled tokens matter ONLY when some row completes its
        # prompt this chunk (np.asarray is a blocking device round trip,
        # ~35-100ms through the tunnel; intermediate chunks discard the
        # sample — per-chunk fetches were the dominant term in round 4's
        # 2.9s TTFT); overlap mode defers even that fetch one step
        need = self._completing_rows(pslots, chunks)
        firsts = (self._prefill_samples(
            tok, [(s, i) for i, s in need.items()]) if need else None)
        for i, (slot, chunk) in enumerate(zip(pslots, chunks)):
            if i in need:
                first = int(firsts[i]) if firsts is not None else None
            else:
                first = -1
            self._finish_prefill_chunk(slot, chunk, first)

    def _fpm_prefill(self, rows: int, tokens: int, bucket: int,
                     packed: bool = False, completing: int = 0,
                     xla: Optional[dict] = None) -> None:
        """One FPM record per prefill program — the inputs the SLA
        planner's FpmObserver turns into prefill-phase MFU and pressure.

        Beyond (rows, tokens, bucket) the record carries:

        - gap_s: dispatch-to-dispatch gap (the decode records'
          convention).  The gap spans everything between two prefill
          dispatches — interleaved decode steps included — and jit
          dispatch is async, so it only reflects device time when a
          blocking fetch landed inside it.
        - flops: dense-matmul estimate for the chunk.  When the config
          pins the platform peak (peak_tflops) AND a device sync fell
          inside the gap, the record carries the derived mfu directly,
          clamped to 1.0; it is an approximation biased LOW by
          interleaved decode work and absent entirely on sync-free
          intervals (timing each chunk exactly would need a blocking
          fetch per dispatch, the round trip this path exists to
          avoid — bench_prefill_phases.py measures the unbiased
          number).
        - queue_depth: waiting + still-prefilling slots, MINUS the
          `completing` slots whose prompt this very dispatch finishes —
          the burst's final record must read 0, or the observer reports
          phantom pressure for a full window after the fleet goes
          idle.
        - xla: the dispatched program's cost_analysis entry from the
          compile watchdog (obs/compile_watch.py), when XLA has a cost
          model for it.  Rides the record as xla_flops/xla_bytes (the
          roofline gauges' inputs) and REPLACES the hand-counted dense
          estimate in the derived mfu — the measured program includes
          attention and the real logit rows, which the estimate
          excludes by construction."""
        now = time.monotonic()
        gap = (now - self._fpm_last_prefill_t
               if self._fpm_last_prefill_t else 0.0)
        if gap > 1.0:
            gap = 0.0  # idle stretch, not prefill latency: mark unknown
        # len() of a list is an atomic read; the exact depth is advisory
        # (this runs before _finish_prefill_chunk flips .prefilling, so
        # completing slots still count — subtract them)
        depth = max(0, len(self.waiting) + sum(
            1 for s in self._slots if s is not None and s.prefilling)
            - completing)
        flops = tokens * self._flops_per_token
        synced = self._fpm_sync_t >= self._fpm_last_prefill_t
        rec = {
            "t": now, "kind": "prefill", "rows": rows, "tokens": tokens,
            "bucket": bucket, "packed": packed, "gap_s": gap,
            "flops": flops, "queue_depth": depth, "synced": synced,
        }
        if xla is not None:
            rec["xla_flops"] = xla["flops"]
            rec["xla_bytes"] = xla["bytes"]
        if gap > 0.0 and self.config.peak_tflops > 0.0 and synced:
            # only when a blocking device fetch landed inside the gap:
            # jit dispatch is async, so a sync-free gap measures host
            # enqueue time, not chunk compute, and flops/gap would
            # overstate MFU without bound.  Clamped at 1.0 — a sync near
            # the interval's start can still leave gap short of the full
            # device time.  `mfu` prefers the measured program's cost
            # analysis (it includes attention + the real logit rows AND
            # the padding the device actually executes); `est_mfu` keeps
            # the hand count so divergence between the two is visible —
            # obs.report's roofline table prints them side by side.
            est = min(flops / gap / (self.config.peak_tflops * 1e12), 1.0)
            rec["est_mfu"] = est
            rec["mfu"] = (min(xla["flops"] / gap
                              / (self.config.peak_tflops * 1e12), 1.0)
                          if xla is not None else est)
        self.fpm.append(rec)
        if obs.enabled():
            # hand the record's roofline-relevant fields to the
            # enclosing prefill_dispatch span (_prefill_step ends it and
            # cannot see this path's locals); consumed exactly once
            self._obs_dispatch_extra = {
                k: rec[k] for k in ("tokens", "bucket", "gap_s", "synced",
                                    "mfu", "est_mfu", "xla_flops",
                                    "xla_bytes")
                if k in rec}
        self._fpm_last_prefill_t = now

    def _prefill_packed_step(self, pslots, budget: int) -> None:
        """One packed prefill dispatch: the planner water-fills the token
        budget across the prefilling slots and concatenates their chunks
        (including prefix-cache-hit tails, which start at prefill_pos >
        0) into a single padding-free stream — one program, one shape
        family, no per-row bucket padding (the round-5 0.098-MFU fix)."""
        from .prefill import plan_packed_prefill

        c = self.config
        plan = plan_packed_prefill(
            pslots, budget, block_size=c.block_size,
            max_blocks_per_seq=c.max_blocks_per_seq,
            min_bucket=c.prefill_buckets[0],
            with_lora=self.lora_bank is not None,
        )
        if plan is None:
            return
        a = plan.arrays
        if self.step_sink is not None:
            self.step_sink("prefill_packed", dict(a))
        tok, self.kv = self._jit_prefill_packed(
            self.params, self.kv,
            jnp.asarray(a["toks"]), jnp.asarray(a["positions"]),
            jnp.asarray(a["seg_ids"]), jnp.asarray(a["tables"]),
            jnp.asarray(a["last_idx"]), jnp.asarray(a["valid"]),
            jnp.asarray(a["seeds"]), jnp.asarray(a["temps"]),
            jnp.asarray(a["top_ks"]), jnp.asarray(a["top_ps"]),
            self.lora_bank,
            jnp.asarray(a["lidx"]) if self.lora_bank is not None else None,
        )
        self._fpm_prefill(
            rows=len(plan.slots), tokens=plan.tokens, bucket=plan.bucket,
            packed=True,
            completing=sum(1 for s, ch in zip(plan.slots, plan.chunks)
                           if s.prefill_pos + ch >= s.prompt_len),
            xla=self._jit_prefill_packed.cost(plan.bucket))
        # token fetch only when some segment completes its prompt this
        # chunk (see _prefill_step: intermediate chunks discard the
        # sample); overlap mode defers the readback one step
        need = self._completing_rows(plan.slots, plan.chunks)
        firsts = (self._prefill_samples(
            tok, [(s, i) for i, s in need.items()]) if need else None)
        for i, (slot, chunk) in enumerate(zip(plan.slots, plan.chunks)):
            if i in need:
                first = int(firsts[i]) if firsts is not None else None
            else:
                first = -1
            self._finish_prefill_chunk(slot, chunk, first)

    def _ring_eligible(self, slot: "_Slot") -> bool:
        """A cold (prefill_pos == 0), non-LoRA prompt longer than the
        largest bucket takes the one-shot sequence-parallel ring program
        when the mesh has one — one predicate for both the packed
        scheduler and the padded fallback, so they can never route the
        same slot differently."""
        return (self._jit_prefill_ring is not None
                and slot.prefill_pos == 0
                and slot.prompt_len > self.config.prefill_buckets[-1]
                and slot.lora_idx == 0)

    def _prefill_one(self, slot: "_Slot", budget: int) -> None:
        """The B=1 chunk program (single prefilling slot)."""
        c = self.config
        if self._ring_eligible(slot):
            # long-context path: one sequence-parallel program computes
            # the whole prompt with ring attention — the O(T^2) FLOPs
            # shard over sp devices instead of chunk-serializing on each.
            # Trade-off vs chunking: decode stalls for this ONE program
            # (not per chunk), but the sp-way split makes it short.
            self._prefill_ring_one(slot)
            return
        pos = slot.prefill_pos
        chunk = min(c.prefill_buckets[-1], budget, slot.prompt_len - pos)
        bucket = self._bucket_for(chunk)
        toks = np.zeros(bucket, np.int32)
        toks[:chunk] = slot.seq.tokens[pos: pos + chunk]
        positions = pos + np.arange(bucket, dtype=np.int32)
        s = slot.request.sampling
        if self.step_sink is not None:
            # copy: the sink crosses to the loop thread while the scheduler
            # keeps mutating the slot's live table (grow/release)
            self.step_sink("prefill", {
                "toks": toks, "positions": positions,
                "block_table": slot.block_table.copy(),
                "pos": np.int32(pos), "chunk": np.int32(chunk),
                "seed": np.int32(slot.sampling_seed),
                "temp": np.float32(s.temperature),
                "top_k": np.int32(s.top_k), "top_p": np.float32(s.top_p),
                **({"lidx": np.int32(slot.lora_idx)}
                   if self.lora_bank is not None else {}),
            })
        tok, self.kv = self._jit_prefill(
            self.params, self.kv,
            jnp.asarray(toks), jnp.asarray(positions),
            jnp.asarray(slot.block_table),
            jnp.int32(pos), jnp.int32(chunk),
            jnp.int32(slot.sampling_seed),
            jnp.float32(s.temperature), jnp.int32(s.top_k),
            jnp.float32(s.top_p), self.lora_bank,
            jnp.int32(slot.lora_idx) if self.lora_bank is not None
            else None,
        )
        self._fpm_prefill(
            rows=1, tokens=int(chunk), bucket=bucket,
            completing=int(slot.prefill_pos + chunk >= slot.prompt_len),
            xla=self._jit_prefill.cost(bucket))
        # token fetch only on the completing chunk (see _prefill_step:
        # intermediate chunks discard the sample); deferred in overlap
        if pos + chunk >= slot.prompt_len \
                and (slot.guide is None or slot.disagg_prefill):
            arr = self._prefill_samples(tok, [(slot, 0)])
            first = int(arr) if arr is not None else None
        else:
            first = -1
        self._finish_prefill_chunk(slot, chunk, first)

    def _prefill_ring_one(self, slot: "_Slot") -> None:
        """Whole-prompt sequence-parallel prefill (see _prefill_one)."""
        c = self.config
        T = slot.prompt_len
        # pad to a pow2 multiple of (sp * smallest bucket): T must divide
        # by sp for the ring, and pow2 rounding bounds distinct shapes
        g = c.sp * c.prefill_buckets[0]
        T_pad = _pow2_len(-(-T // g)) * g
        toks = np.zeros(T_pad, np.int32)
        toks[:T] = slot.seq.tokens[:T]
        positions = np.arange(T_pad, dtype=np.int32)
        s = slot.request.sampling
        if self.step_sink is not None:
            self.step_sink("prefill_ring", {
                "toks": toks, "positions": positions,
                "block_table": slot.block_table.copy(),
                "true_len": np.int32(T),
                "seed": np.int32(slot.sampling_seed),
                "temp": np.float32(s.temperature),
                "top_k": np.int32(s.top_k), "top_p": np.float32(s.top_p),
            })
        tok, self.kv = self._jit_prefill_ring(
            self.params, self.kv, jnp.asarray(toks),
            jnp.asarray(positions), jnp.asarray(slot.block_table),
            jnp.int32(T), jnp.int32(slot.sampling_seed),
            jnp.float32(s.temperature), jnp.int32(s.top_k),
            jnp.float32(s.top_p),
        )
        self.metrics["ring_prefills"] = \
            self.metrics.get("ring_prefills", 0) + 1
        if slot.guide is None or slot.disagg_prefill:
            arr = self._prefill_samples(tok, [(slot, 0)])
            first = int(arr) if arr is not None else None
        else:
            first = -1
        self._finish_prefill_chunk(slot, T, first)

    def _completing_rows(self, slots, chunks) -> Dict[int, "_Slot"]:
        """{program row -> slot} of slots whose prompt completes this
        chunk AND whose first sampled token is actually consumed
        (guided non-disagg completions discard the unconstrained sample
        and re-derive it in the guided step, so they never cost a
        fetch)."""
        return {
            i: s for i, (s, ch) in enumerate(zip(slots, chunks))
            if s.prefill_pos + ch >= s.prompt_len
            and (s.guide is None or s.disagg_prefill)
        }

    def _prefill_samples(self, tok, entries):
        """Completing slots' sampled first tokens, one program's worth.

        Sync mode: blocking fetch now (the lockstep reference path).
        Overlap mode: start the device->host copy and DEFER the read one
        step (_pending_first; _flush_pending_first at the top of the
        next step emits them) — the dispatching step never blocks on its
        own program, so the device_wait only ever pays for work the
        device had a full step to finish.  Returns the host array, or
        None when deferred.  `entries` is [(slot, program row)]."""
        if self._overlap:
            try:
                tok.copy_to_host_async()
            except AttributeError:  # non-jax stand-ins in tests
                pass
            ents = []
            for slot, row in entries:
                slot.awaiting_first = True
                ents.append((slot, (self._seq_id(slot), slot.epoch), row))
            self._pending_first.append({"tok": tok, "entries": ents})
            return None
        t_obs = obs.begin()
        arr = np.asarray(tok)
        obs.end("device_wait", t_obs, track=self._obs_track,
                what="prefill_first")
        self._fpm_sync_t = time.monotonic()
        return arr

    def _flush_pending_first(self) -> None:
        """Overlap mode: read back the PREVIOUS step's deferred prefill
        first tokens (one blocking fetch for everything deferred, while
        this step's dispatches run behind it) and emit or park them.
        Entries whose slot finished, cancelled, or preempted since
        dispatch are discarded — the same (seq_id, epoch) identity check
        the in-flight decode bursts use."""
        if not self._pending_first:
            return
        pending, self._pending_first = self._pending_first, []
        t_obs = obs.begin()
        arrs = [np.asarray(e["tok"]) for e in pending]
        obs.end("device_wait", t_obs, track=self._obs_track,
                what="prefill_first")
        self._fpm_sync_t = time.monotonic()
        for e, arr in zip(pending, arrs):
            flat = np.atleast_1d(arr)
            for slot, ident, row in e["entries"]:
                slot.awaiting_first = False
                if slot.finished or slot.index < 0 \
                        or self._slots[slot.index] is not slot \
                        or (self._seq_id(slot), slot.epoch) != ident:
                    continue
                self._complete_prefill(slot, int(flat[row]))

    def _finish_prefill_chunk(self, slot: "_Slot", chunk: int,
                              first: Optional[int]) -> None:
        """Advance a slot past a completed chunk.  `first` is the prompt's
        sampled first token when it completes this chunk; -1 marks a
        non-completing chunk (or a guided completion, which discards the
        sample); None marks a completed prompt whose token readback is
        deferred (_pending_first — the flush completes it next step)."""
        self.metrics["prefill_tokens"] += chunk
        slot.prefill_pos += chunk
        slot.prefill_chunks += 1
        slot.ctx_len = slot.prefill_pos
        # register blocks this chunk completed (registration is deferred to
        # materialization, so commit must track prefill progress chunkwise)
        self._commit_full_blocks(slot)
        if slot.prefilling:
            return  # more chunks to go; decode runs in between
        if slot.guide is not None and not slot.disagg_prefill:
            # constrained output: discard the unconstrained sample and
            # re-derive the first token's logits in the guided step by
            # re-running the last prompt position (its KV rewrite is
            # value-identical)
            slot.first_token_t = time.monotonic()
            slot.ctx_len = slot.prompt_len - 1
            slot.last_token = slot.seq.tokens[slot.prompt_len - 1]
            return
        if first is None:
            return  # awaiting_first; the next step's flush completes it
        self._complete_prefill(slot, first)

    def _complete_prefill(self, slot: "_Slot", first: int) -> None:
        """Prompt fully materialized and first token in hand: emit it (or
        park the KV for disagg pull)."""
        slot.first_token_t = time.monotonic()
        if slot.disagg_prefill:
            self._park_prefilled(slot, first)
            return
        self._push_token(slot, first)

    async def _stream_pull(self, slot: _Slot, dp: Dict[str, Any]) -> None:
        """Decode-side streaming pull: inject the prefill's KV chunk by
        chunk, each chunk one scheduler op, so decode bursts for OTHER
        slots run in between (no whole-prompt stall; host memory bounded
        by two chunks — the injecting one plus one prefetch in flight).
        Any failure falls back to local prefill — the slot's blocks are
        already allocated and prefill_pos still points at the cached
        prefix."""
        src = None
        t0 = time.monotonic()
        rid = slot.request.request_id
        t_obs = obs.begin()
        tid_obs = (obs.trace_id_from_annotations(slot.request.annotations)
                   if t_obs else None)

        async def pull_chunk(b0: int, n: int):
            # unified retry (runtime/retry.py): a transiently failing
            # chunk op (peer hiccup, injected fault) is retried with
            # jittered backoff before the whole pull gives up and falls
            # back to local prefill.  The chaos seam sits INSIDE the
            # retried call so `times=1` rules are absorbed by a retry
            # while unlimited rules exhaust it.
            async def once():
                await chaos.ahit("disagg.pull.chunk", key=f"{rid}:{b0}")
                return await src.chunk(b0, n)

            return await call_with_retry(
                once, PULL_POLICY,
                on_retry=lambda a, e: logger.warning(
                    "kv pull chunk [%d,%d) for %s failed (attempt %d): "
                    "%s", b0, b0 + n, rid, a, e),
            )

        try:
            await slot.admitted.wait()
            if slot.finished or slot.cancel_requested:
                return
            src = await self.kv_pull_fn(dp)
            header = await call_with_retry(src.open, PULL_POLICY)
            from ..disagg.transfer import KvLayout

            layout = KvLayout.from_dict(header["layout"])
            layout.check_compatible(self.kv_wire_layout())
            prompt_len = slot.prompt_len
            if int(header["prompt_len"]) != prompt_len:
                raise ValueError(
                    f"prefill parked {header['prompt_len']} tokens but the "
                    f"decode request has {prompt_len}")
            bs = self.config.block_size
            n_blocks = (prompt_len + bs - 1) // bs
            if layout.num_blocks != n_blocks:
                raise ValueError(
                    f"prefill parked {layout.num_blocks} blocks; decode "
                    f"needs {n_blocks}")
            # skip blocks the local prefix cache / KVBM already
            # materialized at admission — pull only the missing tail
            start = slot.cached_tokens // bs
            per = layout.blocks_per_chunk(self.config.transfer_chunk_bytes)
            if getattr(src, "device_resident", False):
                # device tiers: the chunk bound protects HOST memory, which
                # device-resident chunks never touch — 8x chunks cut the
                # scheduler-op round trips that dominated round-4's
                # 0.24 GB/s tier-1 pull
                per *= 8
            spans = [(b0, min(per, n_blocks - b0))
                     for b0 in range(start, n_blocks, per)]
            pulled = 0
            # pipelined: chunk i+1 is in flight on the SOURCE while chunk
            # i injects on this engine's scheduler (receiver-paced, one
            # outstanding prefetch — the sender registry holds one chunk)
            nxt = (asyncio.ensure_future(pull_chunk(*spans[0]))
                   if spans else None)
            try:
                for idx, (b0, n) in enumerate(spans):
                    if slot.finished or slot.cancel_requested:
                        return
                    arrs = await nxt
                    nxt = (asyncio.ensure_future(
                        pull_chunk(*spans[idx + 1]))
                        if idx + 1 < len(spans) else None)
                    await self._call_on_scheduler(
                        partial(self._inject_pulled_chunk, slot, b0, n,
                                arrs))
                    if isinstance(arrs[0], np.ndarray):
                        nbytes = sum(a.nbytes for a in arrs)
                        self.metrics["pull_host_chunk_bytes_max"] = max(
                            self.metrics.get("pull_host_chunk_bytes_max",
                                             0),
                            nbytes)
                    pulled += n
            finally:
                if nxt is not None:
                    nxt.cancel()  # no-op if already done
                    try:
                        await nxt
                    except asyncio.CancelledError:
                        # suppress only the prefetch future's OWN
                        # cancellation; re-raise when the pull TASK is
                        # being externally cancelled — either the
                        # prefetch ended uncancelled (the error must be
                        # ours), or (py3.11+) current_task reports a
                        # cancel that arrived while we awaited the
                        # self-cancelled prefetch — so the metrics/
                        # finish code below stops running after cancel
                        # instead of racing the teardown
                        cur = asyncio.current_task()
                        if not nxt.cancelled() or (
                                cur is not None
                                and getattr(cur, "cancelling",
                                            lambda: 0)() > 0):
                            raise
                    except Exception:
                        pass
            self.metrics["pull_blocks"] = (
                self.metrics.get("pull_blocks", 0) + pulled)
            self.metrics["pull_seconds"] = (
                self.metrics.get("pull_seconds", 0.0)
                + (time.monotonic() - t0))
            await self._call_on_scheduler(
                partial(self._finish_pull, slot, dp.get("first_token")))
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.warning("KV pull failed for %s; local prefill fallback",
                           slot.request.request_id, exc_info=True)

            def fallback():
                slot.pulling = False  # prefill path picks the slot up

            try:
                await self._call_on_scheduler(fallback)
            except Exception:
                pass
            self._wake.set()
        finally:
            obs.end("kv_pull", t_obs, request_id=rid, trace_id=tid_obs)
            if src is not None:
                try:
                    await src.close()
                except Exception:
                    pass

    def _inject_pulled_chunk(self, slot: _Slot, b0: int, n: int,
                             arrs) -> None:
        """Scheduler op: scatter one pulled chunk into the slot's blocks.

        `arrs` is (kb, vb) — plus (ksb, vsb) scale planes for an int8
        cache — numpy (host-staged tier) or device arrays (broker /
        transfer-server tiers).  Device chunks are re-laid onto this
        engine's own universal sharding first — with a different source
        mesh that device_put IS the ICI device-to-device move."""
        if slot.finished or slot.cancel_requested:
            return  # blocks may already be freed; drop the chunk
        if len(arrs) != len(self.kv):
            raise ValueError(
                f"pulled chunk has {len(arrs)} payload arrays but the "
                f"cache expects {len(self.kv)} (kv dtype mismatch)")
        block_ids = self.allocator.seq_block_ids(
            self._seq_id(slot))[b0:b0 + n]
        if len(block_ids) != n:
            raise ValueError(f"slot lost blocks [{b0},{b0 + n}) mid-pull")
        ids = _pow2_ids(block_ids)
        bucket = len(ids)
        if isinstance(arrs[0], np.ndarray):
            padded = [np.pad(a, ((0, 0), (0, bucket - n))
                             + ((0, 0),) * (a.ndim - 2)) for a in arrs]
        else:
            shardings = self.universal_shardings()
            arrs = [jax.device_put(a, sh) for a, sh in zip(arrs, shardings)]
            padded = [jnp.pad(a, ((0, 0), (0, bucket - n))
                              + ((0, 0),) * (a.ndim - 2)) for a in arrs]
        if self.step_sink is not None:
            # the pulled KV rides the step stream to the slice's followers
            # (device-resident tiers are gated off for multi-host slices,
            # so the padded chunks are host bytes here)
            # dynlint: disable=DYN011 multi-host pulls are host-staged frames (device tiers gated off); these are numpy already
            desc = {"kb": np.asarray(padded[0]), "vb": np.asarray(padded[1]),
                    "ids": ids}
            if len(padded) == 4:
                # dynlint: disable=DYN011 same host-staged frame (scale planes)
                desc["ksb"] = np.asarray(padded[2])
                # dynlint: disable=DYN011 same host-staged frame (scale planes)
                desc["vsb"] = np.asarray(padded[3])
            self.step_sink("inject", desc)
        self.kv = self._jit_inject(
            self.kv, *(jnp.asarray(a) for a in padded[:2]),
            jnp.asarray(ids), *(jnp.asarray(a) for a in padded[2:])
        )

    def _finish_pull(self, slot: _Slot, first: Optional[int]) -> None:
        """Scheduler op: all chunks landed — commit the blocks and emit the
        first token (recomputing it if the transfer metadata lacked it)."""
        if slot.finished or slot.cancel_requested:
            return
        prompt_len = slot.prompt_len
        slot.ctx_len = prompt_len
        slot.prefill_pos = prompt_len
        slot.cached_tokens = prompt_len  # skipped compute entirely
        slot.pulling = False
        self._commit_full_blocks(slot)
        slot.first_token_t = time.monotonic()
        if slot.guide is not None:
            # constrained output served via disagg: the prefill worker
            # sampled its first token UNCONSTRAINED (it parks before the
            # guided branch runs), so pushing it would stream a stray
            # token ahead of the JSON document.  Mirror the aggregated
            # guided branch instead: rewind to the last prompt position
            # and let _guided_step re-derive the first token under the
            # constraint (the position's KV rewrite is value-identical).
            self.metrics["cache_hit_tokens"] += prompt_len
            slot.ctx_len = prompt_len - 1
            slot.last_token = slot.seq.tokens[prompt_len - 1]
            return
        if first is None:
            # transfer metadata lacked the first token: recompute from the
            # last prompt position (cache already holds prompt[:-1])
            table_dev = jnp.asarray(slot.block_table)
            s = slot.request.sampling
            toks = np.zeros(self.config.prefill_buckets[0], np.int32)
            toks[0] = slot.seq.tokens[-1]
            positions = (prompt_len - 1) + np.arange(
                self.config.prefill_buckets[0], dtype=np.int32)
            if self.step_sink is not None:
                self.step_sink("prefill", {
                    "toks": toks, "positions": positions,
                    "block_table": slot.block_table.copy(),
                    "pos": np.int32(prompt_len - 1), "chunk": np.int32(1),
                    "seed": np.int32(slot.sampling_seed),
                    "temp": np.float32(s.temperature),
                    "top_k": np.int32(s.top_k),
                    "top_p": np.float32(s.top_p),
                    **({"lidx": np.int32(slot.lora_idx)}
                       if self.lora_bank is not None else {}),
                })
            tok, self.kv = self._jit_prefill(
                self.params, self.kv, jnp.asarray(toks),
                jnp.asarray(positions), table_dev,
                jnp.int32(prompt_len - 1), jnp.int32(1),
                jnp.int32(slot.sampling_seed), jnp.float32(s.temperature),
                jnp.int32(s.top_k), jnp.float32(s.top_p),
                self.lora_bank,
                jnp.int32(slot.lora_idx) if self.lora_bank is not None
                else None,
            )
            first = int(tok)
        self.metrics["cache_hit_tokens"] += prompt_len
        self._push_token(slot, int(first))

    def _park_prefilled(self, slot: _Slot, first_token: int) -> None:
        """Disagg prefill done: keep the KV, hand back transfer metadata."""
        from ..disagg.transfer import make_transfer_params

        seq_id = self._seq_id(slot)
        rid = slot.request.request_id
        self._parked[rid] = _Parked(
            seq_id=seq_id,
            block_ids=list(self.allocator.seq_block_ids(seq_id)),
            prompt_len=slot.ctx_len,
            expires_t=time.monotonic() + self.parked_ttl_s,
        )
        if self.kv_ledger is not None:
            # attribution: this sequence's blocks are now
            # pinned-by-transfer, awaiting the decode side's pull
            self.kv_ledger.park(seq_id)
        slot.finished = True
        if slot.index >= 0:
            self._slots[slot.index] = None
            slot.index = -1
        params = make_transfer_params(
            instance_id=self.transfer_identity.get("instance_id", 0),
            request_id=rid,
            prompt_len=self._parked[rid].prompt_len,
            first_token=first_token,
            block_size=self.config.block_size,
            num_layers=self.model_cfg.n_layers,
        )
        params.update({k: v for k, v in self.transfer_identity.items()
                       if k != "instance_id"})
        out = LLMEngineOutput(
            token_ids=[first_token], finish_reason="stop",
            kv_transfer_params=params,
            metrics={"ttft_s": slot.first_token_t - slot.enqueued_t,
                     # disagg one-shot: the prefill hop's own realized
                     # reuse/queue facts ride its single frame
                     "forensic": self._forensic(slot)},
        )
        if self._loop_ref is not None:
            self._loop_ref.call_soon_threadsafe(slot.out_q.put_nowait, out)
        else:
            slot.out_q.put_nowait(out)

    # -- speculative decoding (spec/) --------------------------------------
    def _spec_step(self) -> None:
        """One speculation round: propose up to k draft tokens per
        eligible slot (n-gram prompt lookup or the draft model), score
        all speculating slots' rows in ONE packed spec_verify program
        (segment-id causal attention over the paged cache — the chunked
        prefill machinery re-aimed at decode), then accept the longest
        distribution-preserving prefix host-side (sampler.py
        spec_accept_tokens) and roll the rejected tail's block growth
        back through the allocator.

        Slots that speculate this step skip the pipelined decode
        dispatch (their emission is synchronous — the verify fetch IS
        the step); everything else decodes as usual, so speculating and
        plain sequences mix freely in one scheduler step under the same
        token budget.  Guided/JSON-constrained slots, LoRA slots, and
        mid-pull disagg slots never speculate.  A slot whose acceptance
        EMA collapsed to k=0 rides the (faster, pipelined) plain decode
        path and re-probes every spec_probe_interval generated tokens —
        a probe is the only time the pipeline is drained on its behalf,
        which is what bounds the near-zero-acceptance regression."""
        self._specced = frozenset()
        if not self._spec_ok:
            return
        c = self.config
        cands = [s for s in self._slots
                 if s is not None and not s.prefilling and not s.pulling
                 and not s.awaiting_first  # first token still deferred
                 and not s.finished and s.guide is None
                 and s.lora_idx == 0]
        if not cands:
            return
        rows = []
        budget = c.chunk_budget
        for s in cands:
            # an earlier candidate's probe drain can finish/preempt LATER
            # slots of this stale snapshot (same hazard as _decode_step's
            # grow loop): re-check before touching the allocator
            if s.finished or self._slots[s.index] is not s:
                continue
            if s.spec_k_cur < 0:
                s.spec_k_cur = c.spec_k
                s.spec_backoff = min(self.SPEC_PROBE_MIN,
                                     c.spec_probe_interval)
                # neutral prior: collapse needs a few rounds of real
                # rejection evidence, not one unlucky first verify
                s.spec_accept_ema = 0.5
            if (s.spec_k_cur == 0 or s.inflight > 0) \
                    and s.generated < s.spec_probe_at:
                continue
            if budget <= 1:
                # budget exhausted BEFORE the drain below: a probe
                # skipped here costs nothing and stays due next step —
                # draining first would flush the decode pipeline every
                # step for a probe that then never runs
                break
            if s.inflight > 0:
                # probe of a slot sitting in the pipelined decode path:
                # its latest tokens are device-side, so the proposer
                # would see a stale tail — drain first
                self._drain_inflight()
                if s.finished or self._slots[s.index] is not s \
                        or s.inflight:
                    continue
            k = max(1, s.spec_k_cur)
            # cap by table capacity (verify touches positions
            # [ctx, ctx+k]) and the step's remaining token budget
            k = min(k, c.max_context - 1 - s.ctx_len, budget - 1)
            k = self._spec_grow(s, k) if k > 0 else 0
            if k <= 0:
                self._spec_feedback(s, 0, 0)
                continue
            drafts = list(self.proposer.propose(
                s.seq.tokens, k, ctx=s.ctx_len, draft_pos=s.draft_pos,
                block_table=s.block_table))[:k]
            if not drafts:
                # nothing to try: a miss for the EMA; trim the
                # speculative growth and let plain decode take the slot
                self._spec_feedback(s, 0, 0)
                self._spec_trim(s)
                continue
            budget -= len(drafts) + 1
            rows.append((s, drafts))
        if not rows:
            return
        from ..spec import plan_spec_verify

        plan = plan_spec_verify(
            rows, block_size=c.block_size,
            max_blocks_per_seq=c.max_blocks_per_seq,
        )
        a = plan.arrays
        if self.step_sink is not None:
            self.step_sink("spec_verify", dict(a))
        ids, vals, lse, self.kv = self._jit_spec_verify(
            self.params, self.kv,
            jnp.asarray(a["toks"]), jnp.asarray(a["positions"]),
            jnp.asarray(a["seg_ids"]), jnp.asarray(a["tables"]),
            jnp.asarray(a["valid"]), jnp.asarray(a["temps_t"]),
        )
        t_obs = obs.begin()
        ids = np.asarray(ids)
        vals = np.asarray(vals)
        lse = np.asarray(lse)
        obs.end("device_wait", t_obs, track=self._obs_track,
                what="spec_verify_fetch")
        self._fpm_sync_t = time.monotonic()
        from .sampler import spec_accept_tokens

        t_obs = obs.begin()
        proposed_total = accepted_total = 0
        specced = set()
        for (s, drafts), off in zip(plan.rows, plan.offsets):
            n = len(drafts) + 1
            sm = s.request.sampling
            # host-side rng stream keyed (seed, position): replayed or
            # migrated requests re-draw identically, like the device
            # sampler's fold_in(seed, step)
            rng = np.random.default_rng(
                (s.sampling_seed * 0x9E3779B1 + s.generated + 1)
                & 0xFFFFFFFF)
            accepted, emitted = spec_accept_tokens(
                ids[off:off + n], vals[off:off + n], lse[off:off + n],
                drafts, greedy=sm.temperature <= 0.0, top_k=sm.top_k,
                top_p=sm.top_p, rng=rng)
            proposed_total += len(drafts)
            accepted_total += accepted
            self._spec_feedback(s, accepted, len(drafts))
            specced.add(s.index)
            # the device token chain no longer feeds this lane: its true
            # last_token is now a host-side spec emission, so a later
            # decode burst must neither chain it nor treat the lane as a
            # pure continuation of the pre-spec descriptor
            self._chain_owner[s.index] = None
            ctx0 = s.ctx_len
            for tok in emitted:
                s.ctx_len += 1
                self.metrics["decode_tokens"] += 1
                self._push_token(s, int(tok))
                if s.finished:
                    break
            # the draft cache matches the real sequence through the
            # accepted prefix (the propose pass wrote draft KV for its k
            # INPUT positions [ctx0, ctx0+k-1]; the rejected tail is
            # overwritten on the next round).  Capped at ctx0+k: after
            # FULL acceptance the last draft token's own KV was never a
            # decode input, so that position must be re-prefilled
            s.draft_pos = min(s.ctx_len, ctx0 + len(drafts))
            if not s.finished:
                self._spec_trim(s)
        obs.end("sample", t_obs, track=self._obs_track,
                what="spec_accept", lanes=len(plan.rows))
        self._specced = frozenset(specced)
        self.metrics["spec_steps"] = self.metrics.get("spec_steps", 0) + 1
        self.metrics["spec_proposed"] = \
            self.metrics.get("spec_proposed", 0) + proposed_total
        self.metrics["spec_accepted"] = \
            self.metrics.get("spec_accepted", 0) + accepted_total
        now = time.monotonic()
        gap = (now - self._fpm_last_spec_t
               if self._fpm_last_spec_t else 0.0)
        if gap > 1.0:
            gap = 0.0  # idle stretch, not verify latency: mark unknown
        # one FPM record per verify dispatch: the acceptance-rate input
        # FpmObserver.spec_acceptance aggregates for the SLA planner;
        # xla_* (cost analysis of the packed verify program) feeds the
        # spec_verify roofline gauges
        rec = {
            "t": now, "kind": "spec_verify", "lanes": len(plan.rows),
            "proposed": proposed_total, "accepted": accepted_total,
            "tokens": plan.tokens, "gap_s": gap,
        }
        vcost = self._jit_spec_verify.cost(len(a["toks"]))
        if vcost is not None:
            rec["xla_flops"] = vcost["flops"]
            rec["xla_bytes"] = vcost["bytes"]
        self.fpm.append(rec)
        self._fpm_last_spec_t = now

    def _spec_grow(self, s: _Slot, k: int) -> int:
        """Grow s's block table to cover verify positions [ctx, ctx+k];
        under allocation pressure shrink k to what the table already
        covers (0 = no speculation this step — plain decode handles the
        base position, preempting if even that fails)."""
        c = self.config
        bs = c.block_size
        nblocks = int(np.count_nonzero(s.block_table))
        while nblocks * bs <= s.ctx_len + k:
            if nblocks >= c.max_blocks_per_seq:
                break
            grow = self.allocator.append_block(self._seq_id(s))
            self._emit_events(grow)
            if grow.block_id is None:
                break
            s.block_table[nblocks] = grow.block_id
            nblocks += 1
        return min(k, nblocks * bs - 1 - s.ctx_len)

    def _spec_trim(self, s: _Slot) -> None:
        """Roll back speculative block growth: trailing blocks beyond the
        materialized context — the rejected drafts' KV slots — return to
        the allocator, so free-block accounting matches plain decode."""
        keep = max(-(-s.ctx_len // self.config.block_size), 1)
        res = self.allocator.trim_blocks(self._seq_id(s), keep)
        self._emit_events(res)
        s.block_table[keep:] = 0

    #: first re-probe distance (generated tokens); failed probes back
    #: off exponentially up to spec_probe_interval, so repetition that
    #: emerges mid-stream is discovered within ~8 tokens while a
    #: hopeless stream pays a pipeline drain only at 8/16/32/... marks
    SPEC_PROBE_MIN = 8

    def _spec_feedback(self, s: _Slot, accepted: int,
                       proposed: int) -> None:
        """Fold one speculation outcome into the slot's adaptivity
        state.  A proposer MISS (proposed == 0) carries no acceptance
        evidence — it was free if the slot wasn't pipelined — but
        re-attempting on a pipelined slot costs a drain, so misses only
        push the probe clock with exponential backoff.  VERIFIED rounds
        update the acceptance EMA: high acceptance runs the full spec_k,
        middling halves it, and an EMA below spec_accept_min collapses
        the slot to 0 (plain pipelined decode) until a probe fires."""
        c = self.config
        if proposed <= 0:
            s.spec_probe_at = s.generated + s.spec_backoff
            s.spec_backoff = min(s.spec_backoff * 2, c.spec_probe_interval)
            return
        rate = accepted / proposed
        s.spec_accept_ema = 0.7 * s.spec_accept_ema + 0.3 * rate
        if s.spec_accept_ema < c.spec_accept_min:
            s.spec_k_cur = 0
            s.spec_probe_at = s.generated + s.spec_backoff
            s.spec_backoff = min(s.spec_backoff * 2, c.spec_probe_interval)
        else:
            s.spec_backoff = min(self.SPEC_PROBE_MIN, c.spec_probe_interval)
            s.spec_k_cur = c.spec_k if s.spec_accept_ema >= 0.5 \
                else max(1, c.spec_k // 2)

    # -- decode -----------------------------------------------------------
    # decode burst size while prefill/admission work is pending: single
    # stepping bounds how long a chunk waits behind decode, but on this
    # platform each dispatch costs ~15-30ms of tunnel RTT — at burst 1
    # the interleave tax dominates the whole prefill phase (round-4 p50
    # TTFT 2.9s).  A burst of 4 amortizes the dispatch 4x while holding
    # a prefill chunk back ~3 extra steps (~8ms of compute).
    INTERLEAVE_BURST = 4

    def _fuse_ladder(self) -> List[int]:
        """The decode-burst sizes adaptive fusion can dispatch, ascending:
        1, then INTERLEAVE_BURST doubling up to decode_fused_steps.  One
        compiled (greedy, k) variant exists per rung (built at __init__,
        warmed by warmup_decode) — the ladder is the closed set of shapes
        serving can reach, so a ramp can never compile mid-serving."""
        fused = self.config.decode_fused_steps
        ladder = [1]
        k = min(self.INTERLEAVE_BURST, fused)
        while k > ladder[-1]:
            ladder.append(k)
            k = min(k * 2, fused)
        return ladder

    def _fused_k(self) -> int:
        """Decode-burst size for this step (the adaptive fusion policy).

        Pending admissions or prefill chunks run between SHORT decode
        bursts (chunked-prefill interleaving — a full burst would hold
        them back k steps): any pending work de-fuses to the interleave
        burst and resets the ramp.  In a decode-only stretch the burst
        ramps up the fusion ladder one rung per step, so the steps right
        after an arrival stay short (TTFT) while steady state reaches
        full decode_fused_steps within log2 steps (throughput).
        decode_fuse_adaptive=False restores the pre-adaptive jump
        straight to decode_fused_steps."""
        c = self.config
        if self._jit_decode_multi is None:
            return 1
        if (self.waiting
                or any(s is not None and (s.prefilling or s.awaiting_first)
                       for s in self._slots)):
            self._decode_only_run = 0
            return min(self.INTERLEAVE_BURST, c.decode_fused_steps)
        if not c.decode_fuse_adaptive:
            return c.decode_fused_steps
        k = min(self.INTERLEAVE_BURST << self._decode_only_run,
                c.decode_fused_steps)
        self._decode_only_run = min(self._decode_only_run + 1, 16)
        return k

    def _decode_step(self) -> None:
        c = self.config
        B = c.max_num_seqs
        t_obs = obs.begin()
        # pipeline: keep at most depth-1 unread bursts after this dispatch;
        # processing the oldest here overlaps its (already-complete or
        # nearly-complete) fetch with the device compute of newer bursts.
        # Sync mode (overlap_scheduling=False) is lockstep: depth 1 and a
        # drain right after dispatch, so tokens emit the step they were
        # computed — the byte-identity reference the overlap tests pin.
        depth = max(1, c.decode_pipeline_depth) if self._overlap else 1
        while len(self._inflight) >= depth:
            self._process_oldest_burst()
        k = self._fused_k()
        # slots that speculated this step already emitted synchronously
        # (engine/_spec_step); dispatching them again would double-step.
        # awaiting_first slots have no last_token yet (deferred prefill
        # readback) — they join decode the step after their flush.
        active = [s for s in self._slots
                  if s is not None and not s.prefilling
                  and not s.awaiting_first
                  and s.guide is None and s.index not in self._specced]
        if not active:
            return
        # Every active slot MUST have a block for its next device position
        # ctx_len + inflight (preempt if even that fails); blocks for the
        # rest of the burst are speculative — under allocation pressure
        # degrade to k=1 instead of preempting a sequence for blocks it
        # won't need for k-1 more steps.
        for slot in active:
            # an intra-loop drain (below) can finish LATER slots of this
            # stale snapshot: growing a freed sequence would KeyError
            if slot.finished or self._slots[slot.index] is not slot:
                continue
            eff = slot.ctx_len + slot.inflight
            nblocks = int(np.count_nonzero(slot.block_table))
            if eff >= nblocks * c.block_size:
                if nblocks >= c.max_blocks_per_seq:
                    # capacity: the in-flight tokens already reach the end
                    # of the table — drain so the length-finish fires
                    # before any further dispatch for this slot
                    self._drain_inflight()
                    return
                grow = self.allocator.append_block(self._seq_id(slot))
                self._emit_events(grow)
                if grow.block_id is None:
                    # drain first: processing may finish the slot or free
                    # enough blocks to retry; preemption is the last resort
                    self._drain_inflight()
                    if slot.finished or self._slots[slot.index] is not slot:
                        continue
                    grow = self.allocator.append_block(self._seq_id(slot))
                    self._emit_events(grow)
                    if grow.block_id is None:
                        self._preempt(slot)
                        continue
                slot.block_table[nblocks] = grow.block_id
                nblocks += 1
            while k > 1 and eff + k - 1 >= nblocks * c.block_size:
                if nblocks >= c.max_blocks_per_seq:
                    # table is full: burst positions past it would clamp to
                    # the last column and overwrite that block's KV — run
                    # single-step and let _finish_reason handle capacity
                    k = 1
                    break
                grow = self.allocator.append_block(self._seq_id(slot))
                self._emit_events(grow)
                if grow.block_id is None:
                    k = 1  # pressure: this step runs single-step
                    break
                slot.block_table[nblocks] = grow.block_id
                nblocks += 1

        active = [s for s in self._slots
                  if s is not None and not s.prefilling
                  and not s.awaiting_first
                  and s.guide is None and s.index not in self._specced]
        if not active:
            return

        # from here to the dispatch call is host work building + enqueuing
        # the NEXT burst; with unread bursts in flight the device is still
        # executing, so this is the overlapped enqueue-ahead phase, not
        # scheduler overhead (obs taxonomy: `enqueue_ahead`, nested inside
        # decode_dispatch so the report's innermost-span attribution keeps
        # the wall partition exact).
        t_ea = obs.begin() if (self._overlap and self._inflight) else 0.0
        # NOTE on buffer reuse: these descriptor arrays CANNOT be pooled /
        # double-buffered in place — jax.device_put may alias numpy memory
        # zero-copy (it does on CPU), continuation bursts keep the aliased
        # device descriptor live indefinitely, and the step sink hands the
        # same arrays to the loop thread.  Fresh arrays per full dispatch
        # are the double buffer: the previous generation stays pinned by
        # the in-flight burst while this one is built.
        tokens = np.zeros(B, np.int32)
        use_chain = np.zeros(B, bool)
        positions = np.zeros(B, np.int32)
        ctx_lens = np.zeros(B, np.int32)
        tables = np.zeros((B, c.max_blocks_per_seq), np.int32)
        seeds = np.zeros(B, np.int32)
        steps = np.zeros(B, np.int32)
        temps = np.zeros(B, np.float32)
        top_ks = np.zeros(B, np.int32)
        top_ps = np.ones(B, np.float32)
        valid = np.zeros(B, bool)  # padding rows must not eat MoE capacity
        for s in active:
            i = s.index
            tokens[i] = s.last_token
            # a lane whose previous burst is unread takes its input token
            # from the device chain; host last_token would be k steps stale
            use_chain[i] = (
                self._chain_tokens is not None
                and self._chain_owner[i] == (self._seq_id(s), s.epoch)
                and s.inflight > 0
            )
            positions[i] = s.ctx_len + s.inflight
            ctx_lens[i] = s.ctx_len + s.inflight
            tables[i] = s.block_table
            seeds[i] = s.sampling_seed
            steps[i] = s.generated + s.inflight + 1
            temps[i] = s.request.sampling.temperature
            top_ks[i] = s.request.sampling.top_k
            top_ps[i] = s.request.sampling.top_p
            valid[i] = True

        # ONE descriptor for both the step stream and the local dispatch —
        # a key added to one but not the other would silently desynchronize
        # follower replay from the leader
        a = {
            "tokens": tokens, "use_chain": use_chain,
            "positions": positions, "tables": tables, "ctx_lens": ctx_lens,
            "seeds": seeds, "steps": steps, "temps": temps,
            "top_ks": top_ks, "top_ps": top_ps, "valid": valid,
        }
        if self.lora_bank is not None:
            lidx = np.zeros(B, np.int32)
            for s in active:
                lidx[s.index] = s.lora_idx
            a["lidx"] = lidx
        cont_burst = self._is_continuation(a, active, k)
        if cont_burst:
            # steady state: nothing changed but the clock — advance the
            # device-resident descriptor in-program, upload nothing
            prev = self._last_desc
            adv = prev["k"]
            greedy = bool(np.all(a["temps"] <= 0.0))
            if self.step_sink is not None:
                self.step_sink("decode_cont", {
                    "k": np.int32(k), "advance": np.int32(adv),
                    "greedy": np.int32(greedy),
                })
            burst = self._dispatch_decode_cont(k, adv, greedy)
            for name in ("positions", "ctx_lens", "steps"):
                prev[name] = prev[name] + adv
            prev["k"] = k
            self.metrics["cont_bursts"] = \
                self.metrics.get("cont_bursts", 0) + 1
        else:
            if self.step_sink is not None:
                # adaptive fusion: the burst size rides the descriptor so
                # followers dispatch the identical (greedy, k) program
                self.step_sink(
                    "decode_multi" if k > 1 else "decode",
                    {**a, "k": np.int32(k)} if k > 1 else a)
            burst = self._dispatch_decode(k, a)
            self._last_desc = {**a, "k": k}
            self._last_desc.pop("tokens", None)
            self._last_desc.pop("use_chain", None)
        # start the device->host copy NOW so the fetch in
        # _process_oldest_burst (>= 1 iteration later) finds the data
        # already local — a fresh fetch pays the full transport RTT
        # (~150 ms through a tunneled device) even after compute finished
        try:
            burst.copy_to_host_async()
        except AttributeError:  # non-jax stand-ins in tests
            pass
        obs.end("enqueue_ahead", t_ea, track=self._obs_track, k=k)
        lanes = {}
        for s in active:
            s.inflight += k
            lanes[s.index] = (self._seq_id(s), s.epoch)
            self._chain_owner[s.index] = lanes[s.index]
        self._inflight.append({"burst": burst, "k": k, "lanes": lanes})
        extra = self._obs_decode_extra or {}
        self._obs_decode_extra = None
        obs.end("decode_dispatch", t_obs, track=self._obs_track,
                cont=cont_burst, k=k, lanes=len(active), **extra)
        if not self._overlap:
            # lockstep reference mode: block on the burst and emit now
            self._drain_inflight()

    GUIDED_TOPM = 32
    GUIDED_TOPM_WIDE = 256

    @staticmethod
    def _decode_topk_impl(family, model_cfg, mesh, m, params, kv, tokens,
                          positions, tables, ctx_lens, valid):
        """One decode step returning the top-M candidate ids + logits for
        every lane (guided decoding samples on HOST from this candidate
        set instead of shipping a 128k-vocab mask per token)."""
        logits, kv = family.decode(
            params, model_cfg, kv, tokens, positions, tables, ctx_lens,
            valid=valid, mesh=mesh,
        )
        vals, ids = jax.lax.top_k(logits.astype(jnp.float32), m)
        return ids, vals, kv

    def _topk_jit(self):
        """ONE lazy-init site for the guided top-M program — leader and
        follower must compile the identical collective program."""
        if getattr(self, "_jit_decode_topk", None) is None:
            self._jit_decode_topk = self.compile_watch.wrap(jax.jit(
                partial(self._decode_topk_impl, self.family,
                        self.model_cfg, self.mesh, self.GUIDED_TOPM),
                donate_argnums=(1,),
            ), "decode_topk")
        return self._jit_decode_topk

    def _topk_wide_jit(self):
        """Widened-M retry program (GUIDED_TOPM_WIDE candidates): compiled
        lazily on the first time a guided slot's top-M set has no valid
        continuation, before giving up and force-closing the document."""
        if getattr(self, "_jit_decode_topk_wide", None) is None:
            self._jit_decode_topk_wide = self.compile_watch.wrap(jax.jit(
                partial(self._decode_topk_impl, self.family,
                        self.model_cfg, self.mesh, self.GUIDED_TOPM_WIDE),
                donate_argnums=(1,),
            ), "decode_topk_wide")
        return self._jit_decode_topk_wide

    def _guided_codec(self):
        """Token<->text codec for guided decoding; workers install the
        model's real tokenizer, presets fall back to the same mock
        byte tokenizer their model cards advertise."""
        codec = getattr(self, "guided_codec", None)
        if codec is None:
            from ..frontend.tokenizer import MockTokenizer

            codec = self.guided_codec = MockTokenizer(
                self.model_cfg.vocab_size)
        return codec

    def _guided_step(self) -> None:
        """One constrained token for every guided slot (guide != None).

        Each slot steps alone through the top-M program: candidates are
        tried in sampled order (deterministic gumbel over the top-M
        logits) and the first whose decoded text keeps the output a
        valid JSON prefix wins; EOS is admissible only once the document
        is complete.  When no candidate fits — or the token budget is
        about to run out mid-document — the canonical completion closes
        the document, so the response is ALWAYS schema-valid."""
        # awaiting_first: a guided+disagg slot defers its first-token
        # readback like any parked-to-be prefill (its completion PARKS
        # the KV at the next flush) — stepping it here meanwhile would
        # write a constrained token's KV past the prompt and corrupt
        # the parked prompt_len the decode side pulls
        gslots = [s for s in self._slots
                  if s is not None and not s.prefilling
                  and not s.awaiting_first
                  and s.guide is not None and not s.finished]
        if not gslots:
            return
        c = self.config
        # ONE init site (_topk_jit): a duplicate raw jax.jit here would
        # bypass the compile watchdog's wrapper — the guided fork's
        # 8-14s mid-serving compile is exactly what it must see
        self._topk_jit()
        codec = self._guided_codec()
        B = c.max_num_seqs
        t_obs = obs.begin()
        for slot in gslots:
            # block for the next position (no burst speculation needed)
            nblocks = int(np.count_nonzero(slot.block_table))
            if slot.ctx_len >= nblocks * c.block_size:
                if nblocks >= c.max_blocks_per_seq:
                    self._guided_finish(slot, codec, forced=True)
                    continue
                grow = self.allocator.append_block(self._seq_id(slot))
                self._emit_events(grow)
                if grow.block_id is None:
                    self._preempt(slot)
                    continue
                slot.block_table[nblocks] = grow.block_id
            a = {
                "tokens": np.zeros(B, np.int32),
                "positions": np.zeros(B, np.int32),
                "tables": np.zeros((B, c.max_blocks_per_seq), np.int32),
                "ctx_lens": np.zeros(B, np.int32),
                "valid": np.zeros(B, bool),
            }
            i = slot.index
            a["tokens"][i] = slot.last_token
            a["positions"][i] = slot.ctx_len
            a["ctx_lens"][i] = slot.ctx_len
            a["tables"][i] = slot.block_table
            a["valid"][i] = True
            if self.step_sink is not None:
                self.step_sink("decode_topk", a)
            ids, vals, self.kv = self._jit_decode_topk(
                self.params, self.kv, jnp.asarray(a["tokens"]),
                jnp.asarray(a["positions"]), jnp.asarray(a["tables"]),
                jnp.asarray(a["ctx_lens"]), jnp.asarray(a["valid"]),
            )
            slot.ctx_len += 1  # this step's KV write is in the cache
            s = slot.request.sampling
            text = codec.decode(slot.guided_out)

            def choose(cand_ids, cand_logits):
                if s.temperature <= 0.0:
                    order = np.argsort(-cand_logits)
                else:
                    g = np.random.default_rng(
                        (slot.sampling_seed + slot.generated)
                        & 0xFFFFFFFF).gumbel(size=cand_logits.shape)
                    order = np.argsort(-(cand_logits / s.temperature + g))
                for j in order:
                    tok = int(cand_ids[j])
                    if tok in self.eos_ids:
                        if slot.guide.done(text):
                            return ("eos", tok)
                        continue
                    if slot.guide.ok(codec.decode(slot.guided_out + [tok])):
                        return ("tok", tok)
                return None

            t_d = obs.begin()
            cand_ids, cand_vals = np.asarray(ids[i]), np.asarray(vals[i])
            obs.end("device_wait", t_d, track=self._obs_track,
                    what="guided_fetch")
            chosen = choose(cand_ids, cand_vals)
            if chosen is None:
                # nothing in the top-M set extends the document: retry
                # once with a widened candidate set before giving up —
                # an uncooperative model may still have a valid token in
                # the tail of its distribution (the step re-runs the
                # same position; its KV rewrite is value-identical)
                self.metrics["guided_widened_retries"] = \
                    self.metrics.get("guided_widened_retries", 0) + 1
                if self.step_sink is not None:
                    self.step_sink("decode_topk_wide", a)
                wids, wvals, self.kv = self._topk_wide_jit()(
                    self.params, self.kv, jnp.asarray(a["tokens"]),
                    jnp.asarray(a["positions"]), jnp.asarray(a["tables"]),
                    jnp.asarray(a["ctx_lens"]), jnp.asarray(a["valid"]),
                )
                t_d = obs.begin()
                wid_i, wval_i = np.asarray(wids[i]), np.asarray(wvals[i])
                obs.end("device_wait", t_d, track=self._obs_track,
                        what="guided_fetch")
                chosen = choose(wid_i, wval_i)
            if chosen is None:
                # even the widened set has no valid continuation: close
                # the document canonically (and say so in the response)
                self._guided_finish(slot, codec, forced=True)
                continue
            kind, tok = chosen
            if kind == "eos":
                self._guided_emit(slot, tok, "stop")
                continue
            slot.guided_out.append(tok)
            done = slot.guide.done(codec.decode(slot.guided_out))
            self._guided_emit(slot, tok, "stop" if done else None)
            if not slot.finished \
                    and slot.generated >= slot.request.stop.max_tokens:
                # budget exhausted mid-document: schema validity beats
                # the token budget — close canonically (a few tokens
                # over) instead of emitting truncated invalid JSON
                self._guided_finish(slot, codec, forced=True)
        obs.end("sample", t_obs, track=self._obs_track, what="guided",
                lanes=len(gslots))

    def _guided_emit(self, slot: _Slot, tok: int,
                     finish: Optional[str]) -> None:
        """Stream one guided token with an EXPLICIT finish decision (the
        generic _finish_reason would truncate at max_tokens mid-document;
        the guided path closes the document instead)."""
        now = time.monotonic()
        if slot.last_push_t > 0.0:
            gap = now - slot.last_push_t
            self.itl_ema_s = gap if self.itl_ema_s == 0.0 \
                else 0.95 * self.itl_ema_s + 0.05 * gap
        slot.last_push_t = now
        slot.seq.append(tok)
        slot.last_token = tok
        slot.generated += 1
        self.metrics["decode_tokens"] += 1
        self._commit_full_blocks(slot)
        out = LLMEngineOutput(
            token_ids=[tok], finish_reason=finish,
            # same first/finish forensic stamping as _push_token
            metrics=({"forensic": self._forensic(slot)}
                     if (finish is not None or slot.generated == 1)
                     else None),
        )
        if self._loop_ref is not None:
            self._loop_ref.call_soon_threadsafe(slot.out_q.put_nowait, out)
        else:
            slot.out_q.put_nowait(out)
        if finish is not None:
            slot.finished = True
            if slot.index >= 0:
                self._slots[slot.index] = None
                slot.index = -1
            self._emit_events(self.allocator.free(self._seq_id(slot)))

    def _guided_finish(self, slot: _Slot, codec,
                       forced: bool = False) -> None:
        """Emit the canonical completion closing the document and finish
        the stream.  A non-empty completion means the engine, not the
        model, wrote the document's tail — surfaced per request in the
        final chunk's metrics (`guided_forced_close_tokens`) so clients
        can tell schema-valid-but-model-independent output from a real
        completion (the reference's token-mask approach cannot emit an
        invalid token in the first place; the top-M rescoring design
        trades that guarantee for TPU-side simplicity and must report
        when the trade bites)."""
        text = codec.decode(slot.guided_out)
        try:
            completion = slot.guide.complete(text)
        except ValueError:
            completion = ""
        toks = codec.encode(completion) if completion else []
        slot.guided_out.extend(toks)
        metrics: Dict[str, Any] = {"forensic": self._forensic(slot)}
        if toks or forced:
            self.metrics["guided_forced_closes"] = \
                self.metrics.get("guided_forced_closes", 0) + 1
            metrics["guided_forced_close_tokens"] = len(toks)
        out = LLMEngineOutput(token_ids=list(toks), finish_reason="stop",
                              metrics=metrics)
        if self._loop_ref is not None:
            self._loop_ref.call_soon_threadsafe(slot.out_q.put_nowait, out)
        else:
            slot.out_q.put_nowait(out)
        slot.finished = True
        if slot.index >= 0:
            self._slots[slot.index] = None
            slot.index = -1
        self._emit_events(self.allocator.free(self._seq_id(slot)))

    def _dispatch_decode(self, k: int, a: Dict[str, np.ndarray]):
        """Dispatch one full decode burst (shared by the scheduler and the
        multihost follower replay, so chain state stays symmetric).
        Returns the UNREAD burst device array [k, B], updates the
        device-side token chain, and persists the descriptor as the
        device pack continuations advance from (advance=0 here: the host
        arrays are already current)."""
        # dynlint: disable=DYN011 a["temps"] is the host-side numpy descriptor, not a device array
        greedy = bool(np.all(np.asarray(a["temps"]) <= 0.0))
        chain = self._chain_tokens
        if chain is None:
            chain = jax.device_put(
                jnp.zeros((self.config.max_num_seqs,), jnp.int32),
                self._desc_sharding)
        # COMMITTED uploads: continuation bursts feed the program's own
        # (committed) outputs back in, and a committed-vs-uncommitted
        # split on the same avals forks the jit cache — the fork's
        # compile then lands mid-serving (measured at 8-14s per fork on
        # the tunneled chip)
        sh = self._desc_sharding
        dd = {
            name: jax.device_put(a[name], sh)
            for name in ("tokens", "use_chain", "positions", "tables",
                         "ctx_lens", "seeds", "steps", "temps", "top_ks",
                         "top_ps", "valid")
        }
        dd["lidx"] = (jax.device_put(a["lidx"], sh) if "lidx" in a
                      else None)
        return self._run_decode(k, greedy, dd, chain, advance=0)

    def _dispatch_decode_cont(self, k: int, advance: int, greedy: bool):
        """Dispatch a continuation burst from the persisted device pack —
        zero host->device array uploads (the descriptor advances inside
        the SAME compiled program, advance=k).  Shared by the scheduler
        and follower replay (followers hold their own _dev_desc from
        replaying the preceding full burst).  All lanes chain (the host
        proved every active lane's last token is the device chain's)."""
        dd = self._dev_desc
        if dd.get("_all_chain") is None:
            dd["_all_chain"] = jax.device_put(
                jnp.ones((self.config.max_num_seqs,), bool),
                self._desc_sharding)
        dd = dict(dd, use_chain=dd["_all_chain"])
        self._dev_desc = dd
        return self._run_decode(k, greedy, dd, self._chain_tokens,
                                advance=advance)

    def _run_decode(self, k: int, greedy: bool, dd: Dict[str, Any],
                    chain, advance: int):
        # committed per-value device constants for the advance clock: a
        # raw python int is an UnspecifiedValue in the jit cache key and
        # forks the executable (see _dispatch_decode)
        adv = self._adv_consts.get(advance)
        if adv is None:
            adv = self._adv_consts[advance] = jax.device_put(
                jnp.int32(advance), self._desc_sharding)
        args = (
            self.params, self.kv, chain, dd["use_chain"], dd["tokens"],
            dd["positions"], dd["tables"], dd["ctx_lens"], dd["seeds"],
            dd["steps"], dd["temps"], dd["top_ks"], dd["top_ps"],
            dd["valid"], adv,
            self.lora_bank, dd["lidx"],
        )
        fn = self._jit_decode_multi[(greedy, k)] if k > 1 \
            else self._jit_decode[greedy]
        burst, self.kv, pos, ctx, steps = fn(*args)
        dd["positions"], dd["ctx_lens"], dd["steps"] = pos, ctx, steps
        self._chain_tokens = burst[k - 1]
        self._dev_desc = dd
        now = time.monotonic()
        gap = (now - self._fpm_last_decode_t
               if self._fpm_last_decode_t else 0.0)
        if gap > 1.0:
            gap = 0.0  # idle period, not decode latency: mark unknown
        rec = {
            "t": now, "kind": "decode", "k": k,
            "lanes": sum(1 for s in self._slots
                         if s is not None and not s.prefilling),
            # dispatch-to-dispatch gap: with the pipeline saturated this
            # IS the burst's wall time (k tokens per lane per gap);
            # 0.0 = unknown (first burst after an idle stretch)
            "gap_s": gap,
        }
        # roofline: the burst program's own cost analysis (fixed shape —
        # one entry per decode variant); covers all k fused steps
        dcost = fn.cost()
        if dcost is not None:
            rec["xla_flops"] = dcost["flops"]
            rec["xla_bytes"] = dcost["bytes"]
        self.fpm.append(rec)
        if obs.enabled():
            self._obs_decode_extra = {
                key: rec[key] for key in ("gap_s", "xla_flops",
                                          "xla_bytes") if key in rec}
        self._fpm_last_decode_t = now
        return burst

    def _is_continuation(self, a: Dict[str, np.ndarray], active,
                         k: int) -> bool:
        """True when this burst is provably the pure continuation of the
        last one: same k, same membership/tables/sampling, every lane's
        input token available in the device chain, and positions/steps
        exactly one advance ahead — so the device pack can evolve in
        place.  Requiring k == prev k keeps the compiled-variant set at
        (greedy, k) pairs the warm-up already hits; a k transition
        (prefill interleaving) takes the full path instead of compiling a
        fresh program mid-serving."""
        prev = self._last_desc
        if prev is None or self._dev_desc is None \
                or self._chain_tokens is None or k != prev["k"]:
            return False
        for s in active:
            if self._chain_owner[s.index] != (self._seq_id(s), s.epoch):
                return False
        m = a["valid"]
        adv = prev["k"]
        return (
            np.array_equal(a["valid"], prev["valid"])
            and ("lidx" in a) == (prev.get("lidx") is not None)
            and np.array_equal(a["positions"][m], prev["positions"][m] + adv)
            and np.array_equal(a["ctx_lens"][m], prev["ctx_lens"][m] + adv)
            and np.array_equal(a["steps"][m], prev["steps"][m] + adv)
            and np.array_equal(a["tables"][m], prev["tables"][m])
            and np.array_equal(a["seeds"][m], prev["seeds"][m])
            and np.array_equal(a["temps"][m], prev["temps"][m])
            and np.array_equal(a["top_ks"][m], prev["top_ks"][m])
            and np.array_equal(a["top_ps"][m], prev["top_ps"][m])
            and ("lidx" not in a
                 or np.array_equal(a["lidx"][m], prev["lidx"][m]))
        )

    def _process_oldest_burst(self) -> None:
        """Read back the oldest dispatched burst and apply it: stream
        tokens, advance ctx, commit blocks, detect finishes.  Lanes whose
        slot finished/preempted/cancelled since dispatch are discarded
        (their KV writes went to blocks that are never committed past the
        finish, or to since-freed blocks that device program order
        guarantees were overwritten only by later dispatches)."""
        e = self._inflight.popleft()
        t_obs = obs.begin()
        arr = np.asarray(e["burst"])  # [k, B]
        obs.end("device_wait", t_obs, track=self._obs_track, k=e["k"],
                what="burst_fetch")
        self._fpm_sync_t = time.monotonic()
        for i, ident in e["lanes"].items():
            s = self._slots[i] if i < len(self._slots) else None
            if s is None or (self._seq_id(s), s.epoch) != ident \
                    or s.finished:
                continue
            s.inflight -= e["k"]
            for j in range(e["k"]):
                s.ctx_len += 1
                self.metrics["decode_tokens"] += 1
                self._push_token(s, int(arr[j, i]))
                if s.finished:
                    # mid-burst finish: trailing sampled tokens discarded
                    # (their KV writes landed in this slot's own blocks,
                    # which are never committed past the finish ctx_len)
                    break

    def _drain_inflight(self) -> None:
        while self._inflight:
            self._process_oldest_burst()

    def _commit_full_blocks(self, slot: _Slot) -> None:
        """Register newly-completed full blocks under their PLH.

        A block is only committed once every one of its tokens' K/V is
        materialized in the cache (covered by ctx_len).  The sampled token
        that *completes* a block has its K/V written on the NEXT decode
        step, so that block commits one step later; if the request finishes,
        is cancelled, or is preempted first, the trailing block is never
        registered — otherwise a later prompt could prefix-match a block
        whose final position holds zeros."""
        materialized = slot.ctx_len // self.config.block_size
        limit = min(slot.seq.num_full_blocks, materialized)
        while slot.committed_blocks < limit:
            idx = slot.committed_blocks
            h = slot.seq.block_hashes[idx]
            res = self.allocator.commit_block(self._seq_id(slot), idx, h)
            self._emit_events(res)
            slot.committed_blocks += 1

    def _forensic(self, slot: _Slot) -> Dict[str, Any]:
        """Worker-side forensic facts for the stream's first-token and
        finish frames (frontend/request_trace.py on_worker_stamp):
        REALIZED prefix-cache reuse (what this worker actually served
        from cache — the router's prediction-staleness feedback), the
        slot's waiting-queue position at enqueue, and step counts.
        Wire-safe scalars only; a handful of bytes on two frames per
        request is the plane's whole stream overhead."""
        return {
            "cached_tokens": slot.cached_tokens,
            "queue_pos": slot.queue_pos,
            "prefill_chunks": slot.prefill_chunks,
            "generated": slot.generated,
        }

    def _push_token(self, slot: _Slot, tok: int) -> None:
        """Append a generated token, stream it, handle finish."""
        now = time.monotonic()
        if slot.last_push_t > 0.0:
            # per-slot gap EMA; burst-internal ~0 gaps and between-burst
            # step gaps average out to the true mean inter-token latency
            gap = now - slot.last_push_t
            self.itl_ema_s = gap if self.itl_ema_s == 0.0 \
                else 0.95 * self.itl_ema_s + 0.05 * gap
        slot.last_push_t = now
        slot.seq.append(tok)
        slot.last_token = tok
        slot.generated += 1
        self._commit_full_blocks(slot)
        finish = self._finish_reason(slot, tok)
        # forensic stamp on the FIRST token frame and the finish frame
        # (frontend RequestTracker.on_worker_stamp): realized prefix
        # reuse lands with the first token — when the router's
        # predicted-vs-realized feedback wants it — and the finish
        # frame's step counts supersede it as the record's truth
        if finish:
            metrics = {"kv_usage": self.kv_usage(),
                       "cached_tokens": slot.cached_tokens,
                       "ttft_s": slot.first_token_t - slot.enqueued_t,
                       "forensic": self._forensic(slot)}
        elif slot.generated == 1:
            metrics = {"forensic": self._forensic(slot)}
        else:
            metrics = None
        out = LLMEngineOutput(
            token_ids=[tok],
            finish_reason=finish,
            metrics=metrics,
        )
        if self._loop_ref is not None:
            self._loop_ref.call_soon_threadsafe(slot.out_q.put_nowait, out)
        if finish is not None:
            slot.finished = True
            if slot.index >= 0:
                self._slots[slot.index] = None
            self._emit_events(self.allocator.free(self._seq_id(slot)))

    def _preempt(self, slot: _Slot) -> None:
        """KV OOM: drop the slot's blocks and re-enqueue with full replay."""
        self.metrics["preemptions"] += 1
        self._slots[slot.index] = None
        self._emit_events(self.allocator.free(self._seq_id(slot)))
        slot.index = -1
        slot.ctx_len = 0
        slot.prefill_pos = 0
        slot.prompt_len = 0
        slot.committed_blocks = 0
        slot.block_table[:] = 0
        # stale in-flight bursts for this slot must be discarded on
        # processing (its lanes are keyed by (seq_id, epoch))
        slot.epoch += 1
        slot.inflight = 0
        # the draft-model cache for the freed blocks is stale: replay
        # re-prefills the draft from position 0 (spec/draft.py)
        slot.draft_pos = 0
        with self._qlock:
            self.waiting.insert(0, slot)

    def _finish_reason(self, slot: _Slot, tok: int) -> Optional[str]:
        st = slot.request.stop
        if not st.ignore_eos and tok in self.eos_ids:
            return "stop"
        if tok in (st.stop_token_ids or []):
            return "stop"
        if slot.generated >= st.max_tokens:
            return "length"
        if slot.ctx_len + 1 >= self.config.max_context:
            return "length"
        return None
