"""Chunked-prefill packing planner: host-side logic that turns the set of
prefilling slots plus a per-step token budget into ONE packed prefill
dispatch (ops/packed_prefill.py).

This replaces the per-bucket padded programs' shape zoo with a single
family of packed shapes: the stream length buckets pow2 up to the chunk
budget, the segment-row count pow2 up to max_prefill_seqs, and the table
width pow2 up to max_blocks_per_seq — every admission wave with the same
(bucket, rows, width) triple hits the same compiled program, and every
token in the stream is a real prompt token (the padding the batched path
multiplied per row now exists only in the pow2 tail).

Budget split is a water-fill: slots are served smallest-need first so
short prompts finish in one chunk and their leftover budget extends the
long prompts' chunks — donation is free now because a longer chunk no
longer re-buckets every co-scheduled row (the constraint that forced the
old equal-share split)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np


def _pow2(n: int, lo: int = 1) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


@dataclass
class PackedPlan:
    """One packed dispatch: `slots[i]` contributes `chunks[i]` tokens as
    segment row i; `arrays` are the jit inputs (numpy, host-built)."""

    slots: List          # engine _Slot objects, segment-row order
    chunks: List[int]    # tokens taken from each slot this dispatch
    arrays: Dict[str, np.ndarray]
    tokens: int          # total real tokens in the stream
    bucket: int          # padded stream length


def waterfill(needs: List[int], budget: int) -> List[int]:
    """Split `budget` tokens across `needs`, smallest need first, so
    fully-served slots donate their leftover share to the rest."""
    n = len(needs)
    chunks = [0] * n
    remaining = budget
    left = n
    for i in sorted(range(n), key=lambda j: needs[j]):
        share = remaining // left if left else 0
        take = min(needs[i], share)
        chunks[i] = take
        remaining -= take
        left -= 1
    return chunks


def plan_packed_prefill(
    pslots: List,
    budget: int,
    *,
    block_size: int,
    max_blocks_per_seq: int,
    min_bucket: int,
    with_lora: bool,
) -> Optional[PackedPlan]:
    """Build the packed arrays for one prefill dispatch, or None when no
    slot can take even one token of the budget."""
    needs = [s.prompt_len - s.prefill_pos for s in pslots]
    chunks = waterfill(needs, max(budget, 1))
    used = [(s, c) for s, c in zip(pslots, chunks) if c > 0]
    if not used:
        return None
    n = len(used)
    total = sum(c for _, c in used)
    bucket = _pow2(total, lo=min_bucket)
    S = _pow2(n)
    mbp = min(
        _pow2(max(-(-(s.prefill_pos + c) // block_size) for s, c in used)),
        max_blocks_per_seq,
    )

    toks = np.zeros(bucket, np.int32)
    positions = np.zeros(bucket, np.int32)
    seg_ids = np.zeros(bucket, np.int32)
    valid = np.zeros(bucket, bool)
    tables = np.zeros((S, mbp), np.int32)
    last_idx = np.zeros(S, np.int32)
    seeds = np.zeros(S, np.int32)
    temps = np.zeros(S, np.float32)
    top_ks = np.zeros(S, np.int32)
    top_ps = np.ones(S, np.float32)
    lidx = np.zeros(bucket, np.int32) if with_lora else None

    off = 0
    for i, (slot, chunk) in enumerate(used):
        pos = slot.prefill_pos
        toks[off:off + chunk] = slot.seq.tokens[pos:pos + chunk]
        positions[off:off + chunk] = pos + np.arange(chunk, dtype=np.int32)
        seg_ids[off:off + chunk] = i
        valid[off:off + chunk] = True
        tables[i] = slot.block_table[:mbp]
        last_idx[i] = off + chunk - 1
        s = slot.request.sampling
        seeds[i] = slot.sampling_seed
        temps[i] = s.temperature
        top_ks[i] = s.top_k
        top_ps[i] = s.top_p
        if lidx is not None:
            lidx[off:off + chunk] = slot.lora_idx
        off += chunk

    arrays = {
        "toks": toks, "positions": positions, "seg_ids": seg_ids,
        "tables": tables, "last_idx": last_idx, "valid": valid,
        "seeds": seeds, "temps": temps, "top_ks": top_ks, "top_ps": top_ps,
    }
    if lidx is not None:
        arrays["lidx"] = lidx
    return PackedPlan(
        slots=[s for s, _ in used], chunks=[c for _, c in used],
        arrays=arrays, tokens=total, bucket=bucket,
    )
