"""Host-side physical block allocator with PLH prefix caching.

The engine-side analogue of the logical block lifecycle in the reference's
KVBM (lib/kvbm-logical: Reset→Partial→Complete→Registered,
docs/design-docs/kvbm-design.md:118-141), mapped onto physical block ids in
TPU HBM.  Full blocks are registered under their PositionalLineageHash for
dedup/reuse; refcount-0 registered blocks stay cached in LRU order until
evicted.  Block id 0 is the garbage block (never allocated) — see
ops/paged_attention.py.

Every mutation returns the KV events (stored/removed hashes) the worker must
publish, keeping the router's view consistent with HBM reality.

Accounting contract (obs/kv_ledger.py): every refcount/free-list
transition is ALSO recorded onto the engine's KV ledger at its
definition site here — one ``if led is None`` pointer compare per
mutation when the plane is off (``DYN_KV_LEDGER=0``).  This module and
kvbm/pools.py are the ONLY places allowed to mutate the allocator/pool
books (dynlint DYN013): a mutation elsewhere is exactly the silent
leak/double-free class the ledger's auditor exists to catch.  The
``engine.kv_account`` chaos seam deliberately seeds each violation
class (leak / double-free / orphan / refcount-drift) so the auditor's
detection is regression-provable.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .. import chaos


@dataclass
class AllocResult:
    block_ids: List[int]
    cached_blocks: int  # leading blocks reused from the prefix cache
    stored: List[int] = field(default_factory=list)
    removed: List[int] = field(default_factory=list)


@dataclass
class GrowResult:
    block_id: Optional[int] = None  # newly appended block, if requested
    stored: List[int] = field(default_factory=list)
    removed: List[int] = field(default_factory=list)


class BlockAllocator:
    def __init__(self, num_blocks: int, enable_prefix_caching: bool = True,
                 ledger=None):
        # id 0 reserved as the garbage block
        self.num_blocks = num_blocks
        self.enable_prefix_caching = enable_prefix_caching
        self.ledger = ledger  # obs/kv_ledger.KvLedger | None (off)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._hash_to_block: Dict[int, int] = {}
        self._block_ref: Dict[int, int] = {}
        self._block_hash: Dict[int, int] = {}
        self._lru: "OrderedDict[int, None]" = OrderedDict()  # hash, rc==0
        self._seq_blocks: Dict[str, List[int]] = {}

    # -- introspection ----------------------------------------------------
    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_evictable(self) -> int:
        return len(self._lru)

    def usage(self) -> float:
        usable = self.num_blocks - 1
        return (usable - self.num_free) / max(1, usable)

    def lookup(self, hashes: Sequence[int]) -> int:
        if not self.enable_prefix_caching:
            return 0
        n = 0
        for h in hashes:
            if h in self._hash_to_block:
                n += 1
            else:
                break
        return n

    def seq_block_ids(self, seq_id: str) -> List[int]:
        return self._seq_blocks.get(seq_id, [])

    def coldest_evictable(self, n: int, exclude=(),
                          scan_limit: Optional[int] = None
                          ) -> List[Tuple[int, int]]:
        """Up to n (hash, block_id) pairs from the cold end of the LRU,
        skipping `exclude` hashes — offload candidates (the blocks the next
        evictions would destroy).  Does not mutate.

        scan_limit bounds the walk: once the cold end is fully excluded
        (already offloaded), an unbounded scan would cost O(num_blocks) of
        Python per scheduler step for an empty result.  Candidates cluster at
        the cold end and excluded entries there are evicted by allocation, so
        a bounded scan still finds fresh cold blocks as the head refreshes."""
        out: List[Tuple[int, int]] = []
        for i, h in enumerate(self._lru):
            if scan_limit is not None and i >= scan_limit:
                break
            if h in exclude:
                continue
            out.append((h, self._hash_to_block[h]))
            if len(out) >= n:
                break
        return out

    # -- internals --------------------------------------------------------
    def _evict_one(self, removed: List[int]) -> Optional[int]:
        if not self._lru:
            return None
        h, _ = self._lru.popitem(last=False)
        bid = self._hash_to_block.pop(h)
        self._block_ref.pop(bid, None)
        self._block_hash.pop(bid, None)
        removed.append(h)
        led = self.ledger
        if led is not None:
            led.evict(bid, h)
        return bid

    def _take_block(self, removed: List[int]) -> Optional[int]:
        if self._free:
            return self._free.pop()
        return self._evict_one(removed)

    def _pin(self, h: int) -> int:
        bid = self._hash_to_block[h]
        if self._block_ref.get(bid, 0) == 0:
            self._lru.pop(h, None)
        self._block_ref[bid] = self._block_ref.get(bid, 0) + 1
        return bid

    def _unpin(self, h: int) -> None:
        bid = self._hash_to_block[h]
        rc = self._block_ref.get(bid, 1) - 1
        self._block_ref[bid] = rc
        if rc == 0:
            self._lru[h] = None
            self._lru.move_to_end(h)

    def _release_one(self, bid: int, seq_id: Optional[str],
                     released: Optional[List[int]] = None) -> Optional[int]:
        """Shared free()/trim_blocks() tail: drop one block whose rc hit
        0 — back to the prefix cache when registered, else to the free
        list — with the matching ledger records.  Returns the hash whose
        registration was destroyed (a `removed` KV event), if any."""
        led = self.ledger
        h = self._block_hash.get(bid)
        if h is not None and self._hash_to_block.get(h) == bid \
                and self.enable_prefix_caching:
            self._block_ref[bid] = 0
            self._lru[h] = None
            self._lru.move_to_end(h)
            if led is not None:
                led.unpin(bid, seq_id)
                led.cache(bid, seq_id)
            return None
        self._block_ref.pop(bid, None)
        self._block_hash.pop(bid, None)
        self._free.append(bid)
        if released is not None:
            released.append(bid)
        if led is not None:
            led.release(bid, seq_id)
        if h is not None and self._hash_to_block.get(h) == bid:
            del self._hash_to_block[h]
            return h
        return None

    # -- lifecycle --------------------------------------------------------
    def allocate(self, seq_id: str, hashes: Sequence[int],
                 total_blocks: int) -> Optional[AllocResult]:
        """Admit a sequence needing `total_blocks` blocks, the first
        len(hashes) of which are full blocks with known PLHs."""
        led = self.ledger
        hit = self.lookup(hashes)
        res = AllocResult(block_ids=[], cached_blocks=hit)
        # pin the hits FIRST so the capacity check below counts only LRU
        # entries that are actually evictable (pinning removes hits from it)
        for h in hashes[:hit]:
            bid = self._pin(h)
            res.block_ids.append(bid)
            if led is not None:
                led.pin(bid, seq_id)
        n_new = total_blocks - hit
        if n_new > self.num_free + self.num_evictable:
            for h in hashes[:hit]:
                self._unpin(h)
                if led is not None:
                    bid = self._hash_to_block[h]
                    led.unpin(bid, seq_id)
                    if self._block_ref.get(bid, 0) == 0:
                        led.cache(bid, seq_id)
            return None
        # from here the loop cannot run out of blocks (single-threaded
        # scheduler owns the allocator)
        for i in range(hit, total_blocks):
            bid = self._take_block(res.removed)
            assert bid is not None, "capacity invariant violated"
            self._block_ref[bid] = 1
            res.block_ids.append(bid)
            if led is not None:
                led.alloc(bid, seq_id)
        # chaos seam (engine.kv_account): an extra, unledgered refcount —
        # the precursor drift state the auditor must flag before it grows
        # into a leak
        if chaos.active() is not None and res.block_ids \
                and chaos.hit("engine.kv_account",
                              key=f"refcount_drift:{seq_id}") == "drop":
            self._block_ref[res.block_ids[-1]] += 1
        # Registration of the non-hit full blocks is DEFERRED to
        # commit_block, once prefill has materialized their K/V: registering
        # here would let a concurrent same-prefix request prefix-match
        # blocks whose cache contents are still zeros (the engine interleaves
        # prefill chunks with other admissions).
        self._seq_blocks[seq_id] = list(res.block_ids)
        return res

    def append_block(self, seq_id: str) -> GrowResult:
        """Grow a sequence by one (partial) block for decode."""
        res = GrowResult()
        bid = self._take_block(res.removed)
        if bid is None:
            return res  # caller must handle OOM (preempt)
        self._block_ref[bid] = 1
        self._seq_blocks[seq_id].append(bid)
        res.block_id = bid
        if self.ledger is not None:
            self.ledger.alloc(bid, seq_id)
        return res

    def trim_blocks(self, seq_id: str, keep: int) -> GrowResult:
        """Free a sequence's trailing blocks beyond its first `keep`
        (speculative-decode rollback: blocks grown to hold rejected draft
        tokens' KV return to the free list, so the accounting matches
        plain decode).  Trailing blocks are partial and unregistered by
        construction — registered/shared blocks only ever sit in the
        committed prefix, which the engine never trims past — but the
        release mirrors free()'s full handling for safety."""
        res = GrowResult()
        blocks = self._seq_blocks.get(seq_id)
        if blocks is None:
            return res
        led = self.ledger
        while len(blocks) > max(keep, 0):
            bid = blocks.pop()
            rc = self._block_ref.get(bid, 1) - 1
            if rc > 0:
                self._block_ref[bid] = rc
                if led is not None:
                    led.unpin(bid, seq_id)
                continue
            gone = self._release_one(bid, seq_id)
            if gone is not None:
                res.removed.append(gone)
        return res

    def commit_block(self, seq_id: str, block_index: int, h: int) -> GrowResult:
        """A sequence's partial block became full: register its PLH."""
        res = GrowResult()
        if not self.enable_prefix_caching:
            return res
        bid = self._seq_blocks[seq_id][block_index]
        if h not in self._hash_to_block:
            self._hash_to_block[h] = bid
            self._block_hash[bid] = h
            res.stored.append(h)
            led = self.ledger
            if led is not None:
                # lineage parent: the preceding block's registered hash
                # (None for the root) — what the ledger's fragmentation
                # attribution walks to find dead cached tails
                parent = None
                if block_index > 0:
                    parent = self._block_hash.get(
                        self._seq_blocks[seq_id][block_index - 1])
                led.commit(bid, h, parent=parent, seq=seq_id)
        return res

    def free(self, seq_id: str) -> GrowResult:
        """Release a sequence; registered blocks stay cached (LRU)."""
        res = GrowResult()
        blocks = self._seq_blocks.pop(seq_id, [])
        led = self.ledger
        if chaos.active() is not None and blocks:
            blocks = self._chaos_corrupt(seq_id, blocks)
        released: List[int] = []
        for bid in blocks:
            rc = self._block_ref.get(bid, 1) - 1
            if rc > 0:
                self._block_ref[bid] = rc
                if led is not None:
                    led.unpin(bid, seq_id)
                continue
            gone = self._release_one(bid, seq_id, released)
            if gone is not None:
                res.removed.append(gone)
        # chaos seam: return an already-freed id to the free list a
        # second time — the classic double-free the auditor must flag
        if chaos.active() is not None and released \
                and chaos.hit("engine.kv_account",
                              key=f"double_free:{seq_id}") == "drop":
            self._free.append(released[0])
        if led is not None:
            led.seq_freed(seq_id)
        return res

    def _chaos_corrupt(self, seq_id: str, blocks: List[int]) -> List[int]:
        """engine.kv_account seam, "drop" action: seed the accounting
        faults the ledger auditor exists to catch.  Each key names the
        violation class a rule's ``match=`` selects."""
        blocks = list(blocks)
        if blocks and chaos.hit("engine.kv_account",
                                key=f"leak:{seq_id}") == "drop":
            # "forget" the trailing block: free() never releases it and
            # the ledger keeps a dead owner — capacity silently lost
            blocks.pop()
        if blocks and chaos.hit("engine.kv_account",
                                key=f"orphan:{seq_id}") == "drop":
            # release a block BEHIND the ledger's back (the rogue-code
            # path DYN013 forbids): the books now point at a ghost
            bid = blocks.pop()
            self._block_ref.pop(bid, None)
            h = self._block_hash.pop(bid, None)
            if h is not None and self._hash_to_block.get(h) == bid:
                del self._hash_to_block[h]
            self._free.append(bid)
        return blocks

    def clear_cached(self) -> List[int]:
        """Drop every *unreferenced* cached block (active sequences keep
        theirs).  Safe to run between scheduler steps."""
        removed: List[int] = []
        led = self.ledger
        while self._lru:
            bid = self._evict_one(removed)
            if bid is not None:
                self._free.append(bid)
                if led is not None:
                    led.release(bid)
        return removed
