"""JAX engine worker: serves the engine under the standard worker contract.

Same contract as the mocker worker (ref model:
components/src/dynamo/vllm/worker_factory.py): generate / clear_kv_blocks /
kv_events_replay endpoints, MDC publication, KV events, periodic load
metrics.  The router cannot tell a JAX engine from a simulated one — which is
the point of the contract.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from ..protocols import LLMEngineOutput, ModelDeploymentCard, PreprocessedRequest
from ..protocols.model_card import deregister_model, register_model
from ..router.events import KvEventPublisher
from ..runtime import DistributedRuntime
from ..runtime.discovery import new_instance_id
from .config import EngineConfig
from .core import JaxEngine

logger = logging.getLogger(__name__)

LOAD_SUBJECT_PREFIX = "load_metrics"


class JaxEngineWorker:
    def __init__(self, runtime: DistributedRuntime, config: EngineConfig,
                 namespace: str = "dynamo", component: str = "backend",
                 migration_limit: int = 3,
                 tokenizer_cfg: Optional[dict] = None,
                 params=None):
        self.runtime = runtime
        self.config = config
        self.namespace = namespace
        self.component = component
        self.migration_limit = migration_limit
        self.tokenizer_cfg = tokenizer_cfg or {
            "type": "mock", "vocab_size": config.resolve_model().vocab_size
        }
        self._params = params
        self.engine: Optional[JaxEngine] = None
        self.publisher: Optional[KvEventPublisher] = None
        self.served = None
        self._aux_served = []
        self._load_task: Optional[asyncio.Task] = None

    @property
    def card(self) -> ModelDeploymentCard:
        m = self.config.resolve_model()
        return ModelDeploymentCard(
            name=self.config.served_name,
            namespace=self.namespace,
            component=self.component,
            endpoint="generate",
            tokenizer=self.tokenizer_cfg,
            context_length=min(m.max_context, self.config.max_context),
            kv_cache_block_size=self.config.block_size,
            migration_limit=self.migration_limit,
            runtime_config={
                "total_kv_blocks": self.config.num_blocks,
                "max_num_seqs": self.config.max_num_seqs,
                "model_preset": self.config.model,
                "tp": self.config.tp,
                "dp": self.config.dp,
            },
        )

    async def start(self) -> "JaxEngineWorker":
        rt = self.runtime
        instance_id = new_instance_id()
        self.publisher = KvEventPublisher(
            rt, self.namespace, self.component, worker_id=instance_id
        )

        async def kv_event_sink(stored, removed):
            if stored:
                await self.publisher.stored(stored)
            if removed:
                await self.publisher.removed(removed)

        self.engine = JaxEngine(self.config, params=self._params,
                                kv_event_sink=kv_event_sink)

        async def generate_handler(payload, ctx):
            request = PreprocessedRequest.from_dict(payload)
            async for out in self.engine.generate(request, token=ctx.token):
                yield out.to_dict()

        async def clear_handler(payload, ctx):
            n = await self.engine.clear_kv_blocks()
            yield {"cleared_blocks": n}

        comp = rt.namespace(self.namespace).component(self.component)
        self.served = await comp.endpoint("generate").serve_endpoint(
            generate_handler,
            metadata={"model": self.config.served_name},
            instance_id=instance_id,
        )
        self._aux_served = [
            await comp.endpoint("clear_kv_blocks").serve_endpoint(
                clear_handler, instance_id=instance_id),
            await comp.endpoint("kv_events_replay").serve_endpoint(
                self.publisher.replay_handler, instance_id=instance_id),
        ]
        await register_model(rt, self.card, instance_id)
        self._load_task = asyncio.create_task(self._load_loop())
        logger.info("jax engine worker %d serving %s (tp=%d)",
                    instance_id, self.config.served_name, self.config.tp)
        return self

    async def _load_loop(self) -> None:
        subject = f"{LOAD_SUBJECT_PREFIX}.{self.namespace}.{self.component}"
        while True:
            await asyncio.sleep(0.5)
            if self.engine is None or self.served is None:
                continue
            await self.runtime.event_plane.publish(subject, {
                "worker_id": self.served.instance_id,
                "active_seqs": self.engine.num_active_seqs,
                "kv_usage": self.engine.kv_usage(),
                "kv_total_blocks": self.config.num_blocks,
                "engine_metrics": dict(self.engine.metrics),
            })

    async def close(self) -> None:
        if self._load_task is not None:
            self._load_task.cancel()
        if self.engine is not None:
            await self.engine.close()
        if self.served is not None:
            await deregister_model(self.runtime, self.card,
                                   self.served.instance_id)
        for served in self._aux_served:
            await served.shutdown()
        if self.served is not None:
            await self.served.shutdown()
