"""JAX engine worker: serves the engine under the standard worker contract.

Same contract as the mocker worker (ref model:
components/src/dynamo/vllm/worker_factory.py): generate / clear_kv_blocks /
kv_events_replay endpoints, MDC publication, KV events, periodic load
metrics.  The router cannot tell a JAX engine from a simulated one — which is
the point of the contract.
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Optional

import jax
import numpy as np

from .. import obs
from ..protocols import LLMEngineOutput, ModelDeploymentCard, PreprocessedRequest
from ..protocols.model_card import deregister_model, register_model
from ..router.events import KvEventPublisher
from ..runtime import DistributedRuntime
from ..runtime.discovery import new_instance_id
from .config import EngineConfig
from .core import JaxEngine

logger = logging.getLogger(__name__)

LOAD_SUBJECT_PREFIX = "load_metrics"


class JaxEngineWorker:
    def __init__(self, runtime: DistributedRuntime, config: EngineConfig,
                 namespace: str = "dynamo", component: str = "backend",
                 migration_limit: int = 3,
                 tokenizer_cfg: Optional[dict] = None,
                 params=None, mh=None, slice_id: int = 0):
        """mh: MultihostContext for N-host SPMD slices (default: detect).
        Only the slice leader (rank 0) registers the model and serves
        endpoints — ONE routing identity per slice; followers replay the
        leader's broadcast step stream (parallel/multihost.py).  slice_id
        disambiguates multiple slices of one component (xPyD)."""
        from ..parallel.multihost import MultihostContext

        self.runtime = runtime
        self.config = config
        self.namespace = namespace
        self.component = component
        self.migration_limit = migration_limit
        self.mh = mh or MultihostContext.detect()
        self.slice_id = slice_id
        self._broadcaster = None
        self._follower = None
        self._follower_task = None
        self._chat_template: Optional[str] = None
        if tokenizer_cfg is None:
            if config.model_path:
                import os

                from ..models.loader import load_chat_template

                eos_ids = config.resolve_eos_ids()
                # ship the tokenizer as an inline blob so frontends on
                # other hosts can build it (a worker-local path would not
                # resolve there)
                tok_json = os.path.join(config.model_path, "tokenizer.json")
                with open(tok_json) as f:
                    tokenizer_cfg = {
                        "type": "hf", "json": f.read(),
                        "eos_id": eos_ids[0] if eos_ids else None,
                    }
                self._chat_template = load_chat_template(config.model_path)
            else:
                tokenizer_cfg = {
                    "type": "mock",
                    "vocab_size": config.resolve_model().vocab_size,
                }
        self.tokenizer_cfg = tokenizer_cfg
        self._params = params
        self.engine: Optional[JaxEngine] = None
        self.publisher: Optional[KvEventPublisher] = None
        self.served = None
        self._aux_served = []
        self._load_task: Optional[asyncio.Task] = None
        # local FPM aggregation window: the load loop feeds it, and the
        # /debug/state dump reads compile-family stats and ITL p95 off
        # it between ticks (fleet straggler detection input)
        from ..planner.metrics import FpmWindow

        self._fpm_window = FpmWindow()
        self._debug_source_name: Optional[str] = None

    @property
    def card(self) -> ModelDeploymentCard:
        m = self.config.resolve_model()
        return ModelDeploymentCard(
            name=self.config.served_name,
            namespace=self.namespace,
            component=self.component,
            endpoint="generate",
            tokenizer=self.tokenizer_cfg,
            chat_template=self._chat_template,
            context_length=min(m.max_context, self.config.max_context),
            kv_cache_block_size=self.config.block_size,
            migration_limit=self.migration_limit,
            runtime_config={
                "total_kv_blocks": self.config.num_blocks,
                "max_num_seqs": self.config.max_num_seqs,
                "model_preset": self.config.model,
                "tp": self.config.tp,
                "dp": self.config.dp,
                "role": self.config.role,
                # EFFECTIVE KV storage dtype (quant/kv.py): the engine
                # may fall back to bf16 for families without a quantized
                # path (MLA), and routers/planners must see what is
                # actually served — e.g. the planner warns when an ITL
                # profile measured at one dtype steers a worker at the
                # other (planner/perf_model.py)
                "kv_cache_dtype": (self.engine.kv_dtype
                                   if self.engine is not None
                                   else self.config.kv_cache_dtype),
                # chunked-prefill scheduling knobs (engine/prefill.py):
                # routers/planners can see each worker's chunk budget
                "prefill_chunk_tokens": self.config.chunk_budget,
                "prefill_packed": self.config.prefill_packed,
                # EFFECTIVE attention impls (engine-level overrides
                # applied to the model config): a fleet debugger sees
                # which workers run the Pallas kernels vs the XLA
                # reference paths without reading worker flags
                "attn_impl": (self.engine.model_cfg.attn_impl
                              if self.engine is not None
                              else (self.config.attn_impl or "auto")),
                "packed_attn_impl": (
                    getattr(self.engine.model_cfg, "packed_attn_impl",
                            "auto")
                    if self.engine is not None
                    else (self.config.packed_attn_impl or "auto")),
                # EFFECTIVE fused-sampling epilogue mode (engine-level
                # resolution: MLA families fall back to "off"), same
                # fleet-visibility contract as the attn impls
                "sampling_epilogue": (self.engine.sampling_epilogue
                                      if self.engine is not None
                                      else self.config.sampling_epilogue),
                # overlapped scheduler (engine/core.py): whether this
                # worker pipelines host scheduling behind device
                # execution — sync-mode workers show distinctly worse
                # served/raw ratios, and a fleet debugger should see the
                # mode without reading worker flags
                "overlap_scheduling": self.config.overlap_scheduling,
                # speculative decoding (spec/): planners/routers see the
                # proposer and max draft length; live acceptance rides
                # the FPM stream (spec_verify records).  Gated on the
                # ENGINE's state, not the raw config: an MLA family
                # silently falls back to plain decode and must not
                # advertise a capability it doesn't serve
                **({"speculative": {"proposer": self.config.spec_decode,
                                    "k": self.config.spec_k}}
                   if self.engine is not None and self.engine.spec_enabled
                   else {}),
                **({"reasoning_parser": self.config.reasoning_parser}
                   if self.config.reasoning_parser else {}),
                # timeline tracing capability (obs/): planners/routers
                # can see which workers will emit spans for a trace_id
                **({"tracing": True} if obs.enabled() else {}),
            },
        )

    async def start(self) -> "JaxEngineWorker":
        rt = self.runtime
        if not self.mh.is_leader:
            return await self._start_follower()
        instance_id = new_instance_id()
        self.publisher = KvEventPublisher(
            rt, self.namespace, self.component, worker_id=instance_id
        )
        step_sink = None
        if self.mh.world > 1:
            from ..parallel.multihost import StepBroadcaster, ready_subject

            # all KV-mutating paths ride the step stream (prefill/decode,
            # KVBM gather/inject, disagg inject) — followers replay the
            # full jit sequence, so tiers and disagg roles compose with
            # multi-host (the north-star topology)
            self._broadcaster = await StepBroadcaster(
                rt, self.namespace, self.component, self.slice_id,
                on_fatal=rt.root_token.kill,
            ).start()
            loop = asyncio.get_running_loop()
            bc = self._broadcaster

            def step_sink(kind, arrays):
                # scheduler thread -> loop thread; FIFO preserves exec order
                loop.call_soon_threadsafe(bc.publish_step, kind, arrays)

            # startup barrier: serve only after every follower has ACKED A
            # HELLO SENTINEL received on the step subject itself — proof
            # its subscription is attached to this leader's stream (a step
            # published to nobody is a permanent gap).  Hellos repeat while
            # collecting, so followers re-ack for a restarted leader too.
            ready_ranks: set = {0}
            barrier = asyncio.Event()

            async def collect_ready():
                cancel = asyncio.Event()
                async for _s, msg in rt.event_plane.subscribe(
                    ready_subject(self.namespace, self.component,
                                  self.slice_id),
                    cancel=cancel,
                ):
                    ready_ranks.add(int(msg.get("rank", -1)))
                    if len(ready_ranks) >= self.mh.world:
                        barrier.set()
                        cancel.set()
                        return

            async def hello_loop():
                # hellos repeat anyway, so a transiently failing publish
                # (e.g. a FileDiscovery write under zmq) just costs a beat —
                # but it must not silently kill the loop, or the barrier
                # times out blaming the followers
                while not barrier.is_set():
                    try:
                        await bc.hello()
                    except Exception:
                        logger.warning("barrier hello publish failed",
                                       exc_info=True)
                    await asyncio.sleep(0.2)

            collector = asyncio.create_task(collect_ready())
            heller = asyncio.create_task(hello_loop())
            try:
                await asyncio.wait_for(
                    barrier.wait(),
                    float(os.environ.get("DYN_MH_BARRIER_TIMEOUT_S", "60")),
                )
            except asyncio.TimeoutError:
                collector.cancel()
                raise RuntimeError(
                    f"multi-host barrier timeout: followers ready "
                    f"{sorted(ready_ranks)} of world {self.mh.world}"
                )
            finally:
                heller.cancel()

        def kv_event_sink(stored, removed, tier="g1"):
            # synchronous enqueue on the loop thread: event ids are assigned
            # in mutation order and a single drain task publishes FIFO.
            # `tier` is the tier of the mutation that made the block enter
            # (stored) or fully leave (removed) the worker — events are
            # already netted across tiers by the engine's consolidator.
            self.publisher.enqueue_batch(stored=stored, removed=removed,
                                         tier=tier)

        self.engine = JaxEngine(
            self.config, params=self._params,
            kv_event_sink=kv_event_sink,
            # the leader pulls over the request plane; the injected blocks
            # then ride the step stream to the slice's followers
            kv_pull_fn=self._kv_pull,
            step_sink=step_sink,
        )
        self.engine.transfer_identity = {
            "instance_id": instance_id,
            "namespace": self.namespace,
            "component": self.component,
        }
        # guided decoding validates candidate text with the MODEL'S
        # tokenizer (engine falls back to the byte mock only for mock
        # cards — where the frontend uses the same mock)
        from ..frontend.tokenizer import tokenizer_from_mdc

        try:
            self.engine.guided_codec = tokenizer_from_mdc(
                self.tokenizer_cfg)
        except Exception:
            logger.warning("guided codec unavailable; guided decoding "
                           "will use the byte fallback", exc_info=True)
        self._pull_clients = {}
        from ..disagg.device_transfer import SenderChunkRegistry

        self._chunk_refs = SenderChunkRegistry()
        self._broker_id: Optional[int] = None

        async def generate_handler(payload, ctx):
            request = PreprocessedRequest.from_dict(payload)
            ntok = 0
            # log<->trace correlation: every log record this worker
            # emits while serving the stream carries the propagated
            # trace_id (runtime/logging.py TraceIdFilter)
            bind_tok = obs.bind_trace_id(
                obs.trace_id_from_annotations(request.annotations))
            # worker-side request span: stitches to the frontend's
            # `request` span and request_end record via the propagated
            # trace_id (obs cross-process stitching)
            t_obs = obs.begin()
            try:
                async for out in self.engine.generate(request,
                                                      token=ctx.token):
                    ntok += len(out.token_ids)
                    yield out.to_dict()
            finally:
                obs.end("worker_request", t_obs,
                        trace_id=obs.trace_id_from_annotations(
                            request.annotations) if t_obs else None,
                        request_id=request.request_id, tokens=ntok)
                # trace join: the frontend's traceparent annotation makes
                # this worker's structured log line greppable by trace_id
                tp = next((a.split(":", 1)[1] for a in request.annotations
                           if a.startswith("traceparent:")), None)
                if tp is not None:
                    logger.info("request served", extra={
                        "request_id": request.request_id,
                        "traceparent": tp, "output_tokens": ntok})
                obs.unbind_trace_id(bind_tok)

        async def clear_handler(payload, ctx):
            n = await self.engine.clear_kv_blocks()
            yield {"cleared_blocks": n}

        async def kvbm_pull_handler(payload, ctx):
            """Cross-worker G2 pull (kvbm/remote.py): stream this worker's
            host-tier copies of the requested block run; a None hash marks
            where the run broke (peer eviction)."""
            from ..kvbm.remote import encode_block

            hashes = list(payload.get("hashes", []))[:128]
            blocks = await self.engine.read_host_blocks(hashes)
            for h, *arrays in blocks:
                yield encode_block(h, *arrays)
            if len(blocks) < len(hashes):
                yield {"h": None}

        async def kv_pull_handler(payload, ctx):
            """Receiver-paced pull ops (disagg/transfer.py wire protocol):
            open -> header, chunk -> one gathered slab (host bytes, or a
            transfer-server uuid when the receiver asks via=transfer),
            close -> release.  Each chunk is ONE scheduler op on this
            engine, so prefill/decode for other requests interleave with
            the extraction instead of stalling behind a whole-prompt
            gather."""
            from ..disagg.transfer import encode_chunk_frame, make_header

            op = payload.get("op")
            rid = payload["request_id"]
            if op == "open":
                n_blocks, prompt_len = await self.engine.parked_info(rid)
                layout = self.engine.kv_wire_layout(n_blocks)
                yield make_header(prompt_len, layout,
                                  transfer_addr=self._transfer_addr())
            elif op == "chunk":
                b0 = int(payload["start"])
                n = int(payload["count"])
                if payload.get("via") == "transfer" \
                        and self._transfer_addr() is not None:
                    from ..disagg import device_transfer

                    arrs = await self.engine.extract_parked_chunk(
                        rid, b0, n, to_host=False)
                    # canonical single-shard wire form (the server needs
                    # identical shard structure on both ends); the
                    # tp-gather onto one device rides ICI.  int8 caches
                    # park 4 arrays (data + scale planes).
                    dev = self.engine.mesh.devices.flat[0]
                    arrs = tuple(jax.device_put(a, dev) for a in arrs)
                    uid = device_transfer.next_uuid()
                    device_transfer.get_transfer_server().await_pull(
                        uid, list(arrs))
                    # ref held until the next chunk/close (receiver pacing
                    # proves consumption) so the arrays outlive the pull
                    self._chunk_refs.park(rid, uid, arrs)
                    yield {"uuid": uid}
                else:
                    arrs = await self.engine.extract_parked_chunk(
                        rid, b0, n)
                    yield encode_chunk_frame(b0, *arrs)
            elif op == "close":
                self._chunk_refs.release(rid)
                await self.engine.release_parked(rid)
                yield {}
            else:
                raise ValueError(f"unknown kv_pull op {op!r}")

        comp = rt.namespace(self.namespace).component(self.component)
        from ..protocols.llm import CANARY_GENERATE_PAYLOAD

        self.served = await comp.endpoint("generate").serve_endpoint(
            generate_handler,
            metadata={"model": self.config.served_name},
            instance_id=instance_id,
            health_check_payload=CANARY_GENERATE_PAYLOAD,
        )
        self._aux_served = [
            await comp.endpoint("clear_kv_blocks").serve_endpoint(
                clear_handler, instance_id=instance_id),
            await comp.endpoint("kv_events_replay").serve_endpoint(
                self.publisher.replay_handler, instance_id=instance_id),
            await comp.endpoint("kv_pull").serve_endpoint(
                kv_pull_handler, instance_id=instance_id),
        ]
        if self.engine.kvbm is not None and self.config.kvbm_remote:
            from ..kvbm.remote import RemoteBlockIndex, RemoteKvbmPuller

            self._aux_served.append(
                await comp.endpoint("kvbm_pull").serve_endpoint(
                    kvbm_pull_handler, instance_id=instance_id))
            self._kvbm_index = await RemoteBlockIndex(
                rt, self.namespace, self.component, instance_id).start()
            self._kvbm_pull_client = await (
                comp.endpoint("kvbm_pull").client().start())
            puller = RemoteKvbmPuller(
                self._kvbm_index, self._kvbm_pull_client,
                max_blocks=self.config.kvbm_remote_max_blocks,
            )
            # corrupt pulled frames attribute like every other tier's
            # corruptions (ledger kind `corrupt`, tier="remote") and the
            # index marks the serving peer suspect
            puller.on_corruption = self.engine._note_kv_corruption
            self.engine.remote_kvbm_fetch = puller.fetch_run
        if self.engine.supports_embedding:
            # embed rides the step broadcast like every other collective
            # program, so multi-host slices serve it too
            async def embed_handler(payload, ctx):
                vec = await self.engine.embed(payload["token_ids"])
                yield {"embedding": vec.tolist(), "dim": int(vec.shape[0])}

            self._aux_served.append(
                await comp.endpoint("embed").serve_endpoint(
                    embed_handler, instance_id=instance_id))
        # tier-1 d2d: co-resident engines pull device-to-device through
        # the process broker (single-host slices only — followers need the
        # payload on the step stream as host bytes).  Registered only once
        # every endpoint is up, so a failed start never leaks a
        # half-initialized engine into the process-global registry.
        from ..disagg import broker

        broker.register_engine(instance_id, self.engine)
        self._broker_id = instance_id
        if self.config.warmup and self.mh.world == 1:
            # compile all decode variants BEFORE the model becomes
            # discoverable, so no request ever waits on a decode compile.
            # Multi-host slices skip it: warmup dispatches are collective
            # programs the followers would never replay (they only run
            # what arrives on the step stream), so a leader-side warmup
            # would hang the slice's collective schedule.
            await asyncio.to_thread(self.engine.warmup_decode)
        await register_model(rt, self.card, instance_id)
        self._load_task = asyncio.create_task(self._load_loop())
        # SLA-aware admission input (engine/core.py set_slo_burn): feed
        # the frontends' published SLO burn rate (obs/slo.py
        # SloPlane.publish -> slo_metrics.{ns}) into the engine, where a
        # sustained burn makes prefill chunks yield budget to decode.
        # Stale signals decay engine-side (slo_burn_stale_s), so a
        # frontend restart or a disabled SLO plane is harmless.
        self._slo_cancel = asyncio.Event()
        self._slo_task = asyncio.create_task(self._slo_loop())
        # fleet introspection: this worker's live state on /debug/state
        self._debug_source_name = f"worker:{instance_id}"
        rt.register_debug_source(self._debug_source_name, self.debug_state)
        # KV-accounting plane: the block-lifecycle ledger's attribution
        # + an on-demand audit on /debug/kv (obs/kv_ledger.py)
        self._kv_source_name = f"kv:{instance_id}"
        rt.register_kv_source(self._kv_source_name, self.kv_debug)
        logger.info("jax engine worker %d serving %s (tp=%d)",
                    instance_id, self.config.served_name, self.config.tp)
        return self

    async def kv_debug(self) -> dict:
        """/debug/kv source: the ledger dump with a FRESH reconciliation
        sweep (audit on demand — the third cadence next to
        request-finish and idle-tick)."""
        eng = self.engine
        base = {
            "kind": "engine",
            "instance_id": (self.served.instance_id
                            if self.served is not None else None),
            "namespace": self.namespace,
            "component": self.component,
        }
        if eng is None or eng.kv_ledger is None:
            return {**base, "schema": "dynamo.kv_ledger.v1",
                    "enabled": False}
        audit = await eng.audit_kv()
        out = {**base, **eng.kv_ledger.dump(), "audit": audit,
               "kv": eng.kv_occupancy()}
        if eng.kvbm is not None:
            # degraded-mode picture: breaker state per tier + the
            # manager's I/O/quarantine counters (obs/fleet.py folds
            # tier_state across workers into the fleet summary)
            out["tier_state"] = eng.kvbm.tier_states()
            out["kvbm_stats"] = dict(eng.kvbm.stats)
            out["integrity"] = {
                f"{tier}:{action}": n
                for (tier, action), n in
                eng.kv_integrity_counters().items()}
        if (eng.kvbm is not None and eng.kvbm.g4 is not None
                and eng.kvbm.breaker.state("g4") != "open"):
            # G4 residency picture: blob count + this worker's lineage
            # verdicts over a bounded sample (the sweep applies the same
            # policy; here it's read-only for the fleet aggregator)
            from ..kvbm.residency import LineageResidency

            try:
                keys = []
                for h in eng.kvbm.g4.keys():
                    keys.append(h)
                    if len(keys) >= 2048:
                        break
                res = LineageResidency(eng.kv_ledger, pool=eng.kvbm.g4)
                out["g4"] = {"blobs_sampled": len(keys),
                             "residency": res.verdicts(keys)}
            except OSError:
                pass  # shared dir raced a sweep; next scrape reads it
        return out

    def debug_state(self) -> dict:
        """Live scheduler/KV/drain snapshot for /debug/state and the
        fleet aggregator (obs/fleet.py).  Read-only over structures the
        scheduler thread mutates — copies first, tolerates a torn read
        (a debug dump must never take the step lock)."""
        eng = self.engine
        if eng is None:
            return {"kind": "engine", "role": "follower",
                    "rank": self.mh.rank}
        slots = []
        for s in list(eng._slots):
            if s is None:
                continue
            slots.append({
                "request_id": s.request.request_id,
                "prompt_len": s.prompt_len,
                "generated": s.generated,
                "prefilling": s.prefilling,
                "pulling": s.pulling,
                "inflight": s.inflight,
                "cached_tokens": s.cached_tokens,
            })
        waiting = [s.request.request_id for s in list(eng.waiting)]
        fw = self._fpm_window
        return {
            "kind": "engine",
            "instance_id": (self.served.instance_id
                            if self.served is not None else None),
            "namespace": self.namespace,
            "component": self.component,
            "model": self.config.served_name,
            "role": self.config.role,
            "draining": eng.draining,
            "active_seqs": eng.num_active_seqs,
            "waiting": waiting,
            "slots": slots,
            "tokens_in_flight": sum(
                s["prompt_len"] + s["generated"] for s in slots),
            "kv": eng.kv_occupancy(),
            "kv_usage": eng.kv_usage(),
            "kv_cache_dtype": eng.kv_dtype,
            "itl_ema_s": eng.itl_ema_s,
            "itl_p95_s": fw.decode_itl_p95_s(),
            "compile": fw.compile_stats(),
            "engine_metrics": dict(eng.metrics),
            "config": dict(self.card.runtime_config),
        }

    async def _start_follower(self) -> "JaxEngineWorker":
        """Follower process of an N-host slice: hold the same engine state
        (local weight/KV shards), replay the leader's step stream, expose
        NO network identity.  A step gap is fatal by design — the process
        must restart to rejoin the slice's collective schedule, so replay
        failure kills this runtime's root token (the process exits)."""
        from ..parallel.multihost import StepFollower, ready_subject

        # Followers hold no KVBM tiers: their self.kv evolves purely from
        # the replayed stream (onboard/pull payloads arrive as inject
        # steps), and pools would fight over the same disk dir on shared
        # hosts.  dataclasses.replace keeps the compute config identical.
        from dataclasses import replace as _dc_replace

        fcfg = _dc_replace(self.config, host_cache_blocks=0,
                           disk_cache_dir=None, disk_cache_blocks=0)
        self.engine = JaxEngine(fcfg, params=self._params)
        self._follower = StepFollower(
            self.runtime, self.namespace, self.component, self.slice_id
        )

        async def replay():
            async for kind, arrays, _meta in self._follower.steps():
                self.engine.apply_step(kind, arrays)

        self._follower_task = asyncio.create_task(replay())

        def on_done(task: asyncio.Task) -> None:
            if task.cancelled():
                return
            exc = task.exception()
            if exc is not None:
                logger.critical(
                    "follower rank %d replay died (%s); restarting is the "
                    "only way to rejoin the slice", self.mh.rank, exc,
                )
                self.runtime.root_token.kill()

        self._follower_task.add_done_callback(on_done)

        async def announce():
            # barrier ack: one ack per hello sentinel.  A hello in hand
            # proves our step subscription is attached to the leader's
            # stream, so the leader can never pass the barrier and publish
            # step 0 into the void.  Hellos stop once the barrier passes
            # (no steady-state event noise) and resume from a restarted
            # leader — whose step 0 then crash-restarts us via StepGapError,
            # which is how a slice rejoins.
            subject = ready_subject(self.namespace, self.component,
                                    self.slice_id)
            try:
                while True:
                    await self._follower.hello.wait()
                    self._follower.hello.clear()
                    try:
                        await self.runtime.event_plane.publish(
                            subject, {"rank": self.mh.rank})
                    except Exception:
                        # hellos repeat; a dropped ack self-heals next beat
                        logger.warning("barrier ack publish failed",
                                       exc_info=True)
            except asyncio.CancelledError:
                pass

        self._announce_task = asyncio.create_task(announce())
        logger.info("follower rank %d/%d replaying %s/%s slice %d",
                    self.mh.rank, self.mh.world, self.namespace,
                    self.component, self.slice_id)
        return self

    def _transfer_addr(self) -> Optional[str]:
        """Advertise the tier-2 transfer server: single-host slices only
        (a multi-host slice's gathered chunk is distributed across
        processes; one process cannot serve it) and only when the backend
        supports it."""
        if self.mh.world > 1:
            return None
        from ..disagg.device_transfer import get_transfer_server

        srv = get_transfer_server()
        return srv.address() if srv is not None else None

    async def _kv_pull(self, params: dict):
        """Decode-side pull source, best tier first (disagg/transfer.py):

        1. same process  -> broker source: chunks stay device-resident
           (device_put across meshes = the ICI move)
        2. cross process -> negotiated request-plane source: payload via
           the jax transfer server when both ends have one (DCN
           device-to-device), else host-staged byte frames
        3. host-staged frames — the always-correct fallback.

        Multi-host slices always take host-staged frames: followers
        replay inject steps with the payload riding the step stream.
        The sender's header layout is validated by the engine against its
        own geometry — tp/dp may differ freely (inject reshards via
        GSPMD)."""
        single_host = self.mh.world == 1
        if single_host:
            from ..disagg import broker

            src_engine = broker.lookup_engine(params["instance_id"])
            if src_engine is not None and src_engine is not self.engine:
                return broker.LocalEnginePullSource(
                    src_engine, params["request_id"])
        ns = params.get("namespace", self.namespace)
        comp = params.get("component", self.component)
        key = (ns, comp)
        client = self._pull_clients.get(key)
        if client is None:
            ep = (self.runtime.namespace(ns).component(comp)
                  .endpoint("kv_pull"))
            client = await ep.client().start()
            await client.wait_for_instances()
            self._pull_clients[key] = client
        from ..disagg.device_transfer import NegotiatedPullSource

        return NegotiatedPullSource(
            client, params,
            device=self.engine.mesh.devices.flat[0],
            allow_transfer=single_host,
        )

    async def _slo_loop(self) -> None:
        """Fold every frontend SLO summary into the engine's burn signal
        (worst window wins — the same reduction the planner's
        SloObserver applies)."""
        from ..obs.slo import SLO_SUBJECT_PREFIX

        subject = f"{SLO_SUBJECT_PREFIX}.{self.namespace}"
        try:
            async for subj, payload in self.runtime.event_plane.subscribe(
                subject, cancel=self._slo_cancel
            ):
                if subj != subject or self.engine is None:
                    continue
                try:
                    burns = payload.get("burn")
                    self.engine.set_slo_burn(
                        max((float(v) for v in burns.values()),
                            default=0.0)
                        if isinstance(burns, dict) else 0.0)
                except Exception:
                    # one malformed event (non-dict payload included)
                    # must not kill the feed task — a dead subscription
                    # silently disables SLA-aware admission for the
                    # worker's whole lifetime
                    logger.warning("malformed slo payload: %r",
                                   payload, exc_info=True)
        except asyncio.CancelledError:
            pass

    async def _load_loop(self) -> None:
        subject = f"{LOAD_SUBJECT_PREFIX}.{self.namespace}.{self.component}"
        fpm_subject = f"fpm.{self.namespace}.{self.component}"
        # local /metrics surface (system-status server): queue depth,
        # active sequences, KV pressure per worker
        m = self.runtime.metrics.scoped(component=self.component)
        tr = obs.tracer()
        if tr is not None:
            # per-span-kind duration histograms on this worker's
            # /metrics, next to the engine gauges
            tr.bind_metrics(m)
        # local FPM aggregation: the same derivations the planner's
        # FpmObserver runs fleet-wide, fed from this worker's own ring
        # BEFORE it ships — so a bare `/metrics` scrape sees the
        # headline engine numbers without a planner in the deployment
        # (and /debug/state reads compile stats + ITL p95 off the same
        # window)
        fw = self._fpm_window
        from ..router.tiered_index import compute_tier_costs

        ticks = 0
        tier_costs = None
        while True:
            await asyncio.sleep(0.5)
            ticks += 1
            if self.engine is None or self.served is None:
                continue
            # forward-pass metrics stream (ref fpm_publisher.rs): drain
            # the engine's per-program ring onto the event plane — the
            # planner's online perf regression input
            steps = []
            while self.engine.fpm and len(steps) < 512:
                steps.append(self.engine.fpm.popleft())
            for rec in steps:
                fw.add(self.served.instance_id, rec)
            # compile watchdog records -> per-family compile histogram,
            # then the shared gauge surface (planner/metrics.py
            # export_engine_gauges): headline FPM aggregates, per-phase
            # roofline MFU/MBU from XLA cost analysis over dispatch
            # gaps, KV occupancy per tier — ONE definition for both
            # workers, so mocker /metrics parity can't drift
            from ..obs.compile_watch import observe_compile_records
            from ..planner.metrics import export_engine_gauges

            observe_compile_records(m, steps)
            export_engine_gauges(
                m, fw, peak_tflops=self.config.peak_tflops,
                peak_hbm_gbps=self.config.peak_hbm_gbps,
                occupancy=self.engine.kv_occupancy(),
                kv_ledger=self.engine.kv_ledger)
            if steps:
                try:
                    await self.runtime.event_plane.publish(fpm_subject, {
                        "worker_id": self.served.instance_id,
                        "steps": steps,
                    })
                except Exception:
                    logger.warning("fpm publish failed", exc_info=True)
            # tier-2 sender refs whose receiver died mid-pull (mirrors the
            # engine's parked-KV TTL)
            self._chunk_refs.sweep(self.engine.parked_ttl_s)
            # per-tier onboard costs for the router's tiered selector:
            # measured prefill rate (roofline plane) over the cache's
            # per-block payload bytes.  Recomputed each tick — the
            # measured rate converges as the window fills; the selector
            # falls back to defaults until the first publish.
            flops_rate, _bytes_rate = fw._phase_rates("prefill")
            tok_rate = fw.prefill_tokens_per_s()
            if flops_rate > 0.0 and tok_rate > 0.0:
                tier_costs = compute_tier_costs(
                    prefill_flops_per_s=flops_rate,
                    flops_per_token=flops_rate / tok_rate,
                    bytes_per_block=self.engine.kv_block_bytes(),
                    block_tokens=self.config.block_size)
            # degraded-mode plane: fold circuit-breaker states into the
            # advertised costs (a non-closed tier is priced AT recompute
            # so the selector stops steering traffic toward its blocks)
            # and export the breaker + integrity-failure gauges
            if self.engine.kvbm is not None:
                from ..kvbm import breaker as kvbm_breaker
                from ..router.tiered_index import degraded_tier_costs

                states = self.engine.kvbm.tier_states()
                tier_costs = degraded_tier_costs(tier_costs, states)
                for tier, st in states.items():
                    m.set("dynamo_kvbm_tier_state",
                          float(kvbm_breaker.NUMERIC.get(st, 0)),
                          "KV tier circuit-breaker state "
                          "(0=closed, 1=half_open, 2=open)", tier=tier)
            for (tier, action), n in \
                    self.engine.kv_integrity_counters().items():
                m.set("dynamo_kv_integrity_failures_total", float(n),
                      "checksum quarantines and deadline/breaker I/O "
                      "failures across the KV cache fabric",
                      tier=tier, action=action)
            # lineage-driven G4 GC on a slow cadence (~30s): the shared
            # store is swept by every mounted worker; hot lineages get
            # their TTL renewed, dead ones reap early
            if ticks % 60 == 0:
                try:
                    await self.engine.sweep_kvbm_g4()
                except Exception:
                    logger.warning("g4 sweep failed", exc_info=True)
            await self.runtime.event_plane.publish(subject, {
                "worker_id": self.served.instance_id,
                "active_seqs": self.engine.num_active_seqs,
                "kv_usage": self.engine.kv_usage(),
                "kv_total_blocks": self.config.num_blocks,
                **({"kv_tier_costs": tier_costs} if tier_costs else {}),
                # effective KV dtype: the planner checks live workers
                # against the perf profile's dtype tag
                "kv_cache_dtype": self.engine.kv_dtype,
                "engine_metrics": dict(self.engine.metrics),
                # stable SLA-planner contract (planner/metrics.py
                # differentiates these; engine_metrics above is an
                # unversioned debug dump that happens to overlap)
                "requests_total": self.engine.metrics["requests"],
                "prompt_tokens_total": self.engine.metrics["prompt_tokens"],
                "itl_ema_s": self.engine.itl_ema_s,
            })
            m.set("dynamo_engine_active_seqs", self.engine.num_active_seqs)
            m.set("dynamo_engine_waiting_seqs", len(self.engine.waiting))
            m.set("dynamo_engine_kv_usage", self.engine.kv_usage())
            m.set("dynamo_engine_itl_ema_seconds", self.engine.itl_ema_s)

    async def drain(self, deadline_s: float = 5.0) -> None:
        """Graceful drain (SIGTERM path): withdraw this worker's routing
        identity from discovery, reject new work with the migratable
        "worker draining" marker, let in-flight requests finish until the
        deadline, then drain_abort() the rest so the frontend's
        token-replay migration moves them to surviving workers with no
        client-visible failure.  Only this worker's keys are deleted —
        co-resident workers on the same runtime keep serving.

        Followers of a multi-host slice have no routing identity and
        nothing to drain (the leader's drain stops the step stream)."""
        import time

        from .. import chaos

        if not self.mh.is_leader or self.engine is None:
            return
        # chaos: a worker that ignores drain (wedge) — the planner
        # connector's bounded wait escalates to stop, and migration
        # completes the in-flight streams on survivors
        await chaos.ahit("worker.drain", key=str(
            self.served.instance_id if self.served is not None else ""))
        self.engine.draining = True
        if self.served is not None:
            logger.warning("draining jax engine worker %d (deadline %.1fs)",
                           self.served.instance_id, deadline_s)
            await deregister_model(self.runtime, self.card,
                                   self.served.instance_id)
            await self.runtime.discovery.delete(self.served.instance.key())
        t0 = time.monotonic()
        while (self.engine.num_active_seqs
               and time.monotonic() - t0 < deadline_s):
            await asyncio.sleep(0.02)
        self.engine.drain_abort()

    async def close(self) -> None:
        if self._debug_source_name is not None:
            self.runtime.unregister_debug_source(self._debug_source_name)
            self._debug_source_name = None
        if getattr(self, "_kv_source_name", None) is not None:
            self.runtime.unregister_kv_source(self._kv_source_name)
            self._kv_source_name = None
        if getattr(self, "_broker_id", None) is not None:
            from ..disagg import broker

            broker.deregister_engine(self._broker_id)
        for client in getattr(self, "_pull_clients", {}).values():
            await client.close()
        if getattr(self, "_kvbm_index", None) is not None:
            await self._kvbm_index.close()
        if getattr(self, "_kvbm_pull_client", None) is not None:
            await self._kvbm_pull_client.close()
        if self._follower is not None:
            self._follower.stop()
        if self._follower_task is not None:
            self._follower_task.cancel()
        if getattr(self, "_announce_task", None) is not None:
            self._announce_task.cancel()
        if self._broadcaster is not None:
            await self._broadcaster.close()
        if self._load_task is not None:
            self._load_task.cancel()
        if getattr(self, "_slo_task", None) is not None:
            self._slo_cancel.set()
            self._slo_task.cancel()
        if self.engine is not None:
            await self.engine.close()
        if self.served is not None:
            await deregister_model(self.runtime, self.card,
                                   self.served.instance_id)
        for served in self._aux_served:
            await served.shutdown()
        if self.served is not None:
            await self.served.shutdown()
