"""Guided decoding: JSON-schema prefix validation + canonical completion.

Ref role: the reference's guided decoding / structural outputs
(preprocessor.rs structural_tag; engines' guided_json).  TPU-first
design note: full-vocab token masks per step would ship a 128k-bool
mask host->device every token (or compile a token-level grammar DFA on
device) — instead the engine samples a top-M candidate set ON DEVICE
and the host picks the best candidate whose text keeps the output a
valid PREFIX of a schema-conforming JSON document (engine/core.py
guided path).  When no candidate fits, the canonical completion closes
the document deterministically, so output validity is GUARANTEED, with
model-chosen content whenever the model cooperates.

Schema subset (the function-calling arguments shape): object with
properties (all required, canonical declaration order), string, integer,
number, boolean, null, enum of strings/numbers, arrays of a primitive
item type, and nested objects thereof.

The validator is a prefix acceptor: `ok(text)` answers "can `text` be
extended to a conforming document?"; `complete(text)` returns the
canonical suffix that closes it.  Both run a recursive descent that
tolerates truncation at any byte.
"""

from __future__ import annotations

import json
import math
from typing import Any, Dict, List, Optional, Tuple

_WS = " \t\n\r"


class _Trunc(Exception):
    """Input ended mid-production: valid prefix."""

    def __init__(self, completion: str):
        self.completion = completion


class _Bad(Exception):
    """Input cannot be extended to a conforming document."""


def _skip_ws(s: str, i: int) -> int:
    while i < len(s) and s[i] in _WS:
        i += 1
    return i


def _canonical(schema: Dict[str, Any]) -> str:
    """The canonical minimal document for a schema (used to close
    truncated output)."""
    t = schema.get("type")
    if "enum" in schema:
        return json.dumps(schema["enum"][0])
    if t == "object":
        props = schema.get("properties")
        if props is None:
            return "{}"  # generic object (json_object response format)
        parts = [f"{json.dumps(k)}: {_canonical(v)}"
                 for k, v in props.items()]
        return "{" + ", ".join(parts) + "}"
    if t == "array":
        return "[]"
    if t == "string":
        return '""'
    if t in ("integer", "number"):
        return "0"
    if t == "boolean":
        return "false"
    if t == "null":
        return "null"
    return "null"


class JsonSchemaGuide:
    """Prefix acceptor + canonical completer for one schema."""

    def __init__(self, schema: Dict[str, Any]):
        self.schema = schema or {}

    # -- public API -------------------------------------------------------
    def ok(self, text: str) -> bool:
        """True iff `text` is a prefix of some conforming document
        (trailing whitespace after a complete document is allowed;
        trailing garbage is not)."""
        try:
            end = self._value(self.schema, text, _skip_ws(text, 0))
        except _Trunc:
            return True
        except _Bad:
            return False
        return _skip_ws(text, end) == len(text)

    def done(self, text: str) -> bool:
        """True iff `text` already IS a complete conforming document."""
        try:
            end = self._value(self.schema, text, _skip_ws(text, 0))
        except (_Trunc, _Bad):
            return False
        return _skip_ws(text, end) == len(text)

    def complete(self, text: str) -> str:
        """Canonical suffix closing a valid prefix (empty when done).
        Raises ValueError on an invalid prefix."""
        try:
            end = self._value(self.schema, text, _skip_ws(text, 0))
        except _Trunc as t:
            return t.completion
        except _Bad:
            raise ValueError(f"not a valid prefix: {text!r}")
        if _skip_ws(text, end) != len(text):
            raise ValueError(f"trailing garbage: {text!r}")
        return ""

    # -- recursive descent ------------------------------------------------
    # each _X(schema, s, i) returns the index AFTER the parsed value, or
    # raises _Trunc(canonical completion from the truncation point) /
    # _Bad.

    def _value(self, schema: Dict[str, Any], s: str, i: int) -> int:
        i = _skip_ws(s, i)
        if i >= len(s):
            raise _Trunc(_canonical(schema))
        if "enum" in schema:
            return self._enum(schema, s, i)
        t = schema.get("type")
        if t == "object":
            return self._object(schema, s, i)
        if t == "array":
            return self._array(schema, s, i)
        if t == "string":
            return self._string(s, i)
        if t == "integer":
            return self._number(s, i, integer=True)
        if t == "number":
            return self._number(s, i, integer=False)
        if t == "boolean":
            return self._literal(s, i, ("true", "false"))
        if t == "null":
            return self._literal(s, i, ("null",))
        # untyped: accept any JSON value (fall back to a tolerant parse)
        return self._any(s, i)

    def _literal(self, s: str, i: int, options: Tuple[str, ...]) -> int:
        for lit in options:
            if s.startswith(lit, i):
                return i + len(lit)
            # truncated prefix of the literal?
            rest = s[i:]
            if lit.startswith(rest) and rest:
                raise _Trunc(lit[len(rest):])
        raise _Bad

    def _enum(self, schema: Dict[str, Any], s: str, i: int) -> int:
        lits = [json.dumps(v) for v in schema["enum"]]
        best_trunc: Optional[str] = None
        for lit in lits:
            if s.startswith(lit, i):
                return i + len(lit)
            rest = s[i:]
            if lit.startswith(rest):
                # keep the FIRST enum member as the canonical close
                if best_trunc is None:
                    best_trunc = lit[len(rest):]
        if best_trunc is not None:
            raise _Trunc(best_trunc)
        raise _Bad

    def _string(self, s: str, i: int) -> int:
        if s[i] != '"':
            raise _Bad
        i += 1
        while i < len(s):
            c = s[i]
            if c == '"':
                return i + 1
            if c == "\\":
                if i + 1 >= len(s):
                    raise _Trunc('\\"'[1:] + '"')  # finish escape + close
                nxt = s[i + 1]
                if nxt in '"\\/bfnrt':
                    i += 2
                elif nxt == "u":
                    hexpart = s[i + 2:i + 6]
                    if len(hexpart) < 4:
                        if all(ch in "0123456789abcdefABCDEF"
                               for ch in hexpart):
                            raise _Trunc("0" * (4 - len(hexpart)) + '"')
                        raise _Bad
                    if not all(ch in "0123456789abcdefABCDEF"
                               for ch in hexpart):
                        raise _Bad
                    i += 6
                else:
                    raise _Bad
            elif ord(c) < 0x20:
                raise _Bad  # control chars must be escaped
            else:
                i += 1
        raise _Trunc('"')

    _DIGITS = "0123456789"

    def _number(self, s: str, i: int, integer: bool) -> int:
        j = i
        if j < len(s) and s[j] == "-":
            j += 1
            if j >= len(s):
                raise _Trunc("0")
        if j >= len(s) or s[j] not in self._DIGITS:
            raise _Bad
        while j < len(s) and s[j] in self._DIGITS:
            j += 1
        if j >= len(s):
            return j  # complete number (more digits could follow: still
            #           a valid END here — caller treats EOS as done)
        if not integer and s[j] == ".":
            j += 1
            if j >= len(s):
                raise _Trunc("0")
            if s[j] not in self._DIGITS:
                raise _Bad
            while j < len(s) and s[j] in self._DIGITS:
                j += 1
        if not integer and j < len(s) and s[j] in "eE":
            j += 1
            if j < len(s) and s[j] in "+-":
                j += 1
            if j >= len(s):
                raise _Trunc("0")
            if s[j] not in self._DIGITS:
                raise _Bad
            while j < len(s) and s[j] in self._DIGITS:
                j += 1
        return j

    def _object(self, schema: Dict[str, Any], s: str, i: int) -> int:
        props = schema.get("properties")
        if props is None:
            # {"type": "object"} with no declared properties: any object
            # with arbitrary keys/values (json_object response format)
            if s[i] != "{":
                raise _Bad
            return self._any(s, i)
        keys = list(props)
        if s[i] != "{":
            raise _Bad

        def closer(from_key: int, prefix: str) -> str:
            parts = [f"{json.dumps(k)}: {_canonical(props[k])}"
                     for k in keys[from_key:]]
            return prefix + ", ".join(parts) + "}" if parts \
                else prefix.rstrip(", ") + "}"

        i += 1
        if not keys:
            i = _skip_ws(s, i)
            if i >= len(s):
                raise _Trunc("}")
            if s[i] != "}":
                raise _Bad
            return i + 1
        for n, key in enumerate(keys):
            i = _skip_ws(s, i)
            klit = json.dumps(key)
            if i >= len(s):
                raise _Trunc(closer(n, ""))
            if not s.startswith(klit, i):
                rest = s[i:]
                if klit.startswith(rest):
                    raise _Trunc(klit[len(rest):] + ": "
                                 + _canonical(props[key])
                                 + closer(n + 1, ", "))
                raise _Bad
            i += len(klit)
            i = _skip_ws(s, i)
            if i >= len(s):
                raise _Trunc(": " + _canonical(props[key])
                             + closer(n + 1, ", "))
            if s[i] != ":":
                raise _Bad
            i += 1
            try:
                i = self._value(props[key], s, i)
            except _Trunc as t:
                raise _Trunc(t.completion + closer(n + 1, ", "))
            i = _skip_ws(s, i)
            sep = "," if n + 1 < len(keys) else "}"
            if i >= len(s):
                raise _Trunc(closer(n + 1, ", ") if sep == ","
                             else "}")
            if s[i] != sep:
                raise _Bad
            i += 1
        return i

    def _array(self, schema: Dict[str, Any], s: str, i: int) -> int:
        item = schema.get("items", {})
        if s[i] != "[":
            raise _Bad
        i += 1
        i = _skip_ws(s, i)
        if i >= len(s):
            raise _Trunc("]")
        if s[i] == "]":
            return i + 1
        while True:
            try:
                i = self._value(item, s, i)
            except _Trunc as t:
                raise _Trunc(t.completion + "]")
            i = _skip_ws(s, i)
            if i >= len(s):
                raise _Trunc("]")
            if s[i] == "]":
                return i + 1
            if s[i] != ",":
                raise _Bad
            i += 1
            i = _skip_ws(s, i)
            if i >= len(s):
                raise _Trunc(_canonical(item) + "]")

    def _any(self, s: str, i: int) -> int:
        """Untyped value: structural JSON check without a schema."""
        c = s[i]
        if c == "{":
            # generic object: string keys, any values
            i += 1
            i = _skip_ws(s, i)
            if i >= len(s):
                raise _Trunc("}")
            if s[i] == "}":
                return i + 1
            while True:
                try:
                    i = self._string(s, i)
                except _Trunc:
                    raise _Trunc('": null}')
                i = _skip_ws(s, i)
                if i >= len(s):
                    raise _Trunc(": null}")
                if s[i] != ":":
                    raise _Bad
                try:
                    i = self._any(s, _skip_ws(s, i + 1))
                except _Trunc as t:
                    raise _Trunc(t.completion + "}")
                except IndexError:
                    raise _Trunc("null}")
                i = _skip_ws(s, i)
                if i >= len(s):
                    raise _Trunc("}")
                if s[i] == "}":
                    return i + 1
                if s[i] != ",":
                    raise _Bad
                i = _skip_ws(s, i + 1)
                if i >= len(s):
                    raise _Trunc('"k": null}')
        if c == "[":
            return self._array({"items": {}}, s, i)
        if c == '"':
            return self._string(s, i)
        if c in "-0123456789":
            return self._number(s, i, integer=False)
        return self._literal(s, i, ("true", "false", "null"))
