from .json_prefix import JsonSchemaGuide

__all__ = ["JsonSchemaGuide"]
