"""Chaos plane: deterministic, seeded fault injection for the runtime.

The fault-tolerance mechanisms in this repo (frontend token-replay
migration, canary lease withdrawal, KV-pull local-prefill fallback,
preemption) each work in isolation — this module exists to prove they
COMPOSE.  It is the reproduction's analogue of the reference's
`tests/fault_tolerance/` harness, built as a first-class subsystem so
the same scenarios run in tier-1 (mocker / CPU JAX engine) and against
a live fleet.

Design:

  * **Named seams.**  Production code declares injection points by name
    (`SEAMS` below documents the registry).  A seam call is a single
    module-global ``None`` check when chaos is disabled — zero overhead
    on every hot path, no test hooks leaking into production flow.

  * **Deterministic from a seed.**  A :class:`ChaosPlane` is constructed
    with a seed; every probabilistic decision draws from a per-rule
    ``random.Random`` derived from (seed, seam, action), and
    count-based rules (``after=N, times=M``) are pure counters.  Two
    runs with the same seed and the same call order inject identically
    — which is what lets the chaos suite assert token-identical output
    against a fault-free run.

  * **Typed faults.**  Injected failures raise :class:`ChaosError`
    whose message carries the real failure marker the fault simulates
    (``"connection lost"``, ``"worker draining"``, …), so the existing
    migratable-error classification (frontend/pipeline.py) sees exactly
    what a genuine fault would produce.

Usage (tests):

    plane = ChaosPlane(seed=7)
    plane.rule("request_plane.frame", "truncate", after=3, times=1)
    with plane:                       # install / uninstall
        ... drive requests ...
    assert plane.injections           # what actually fired

Seam registry (name — wired at — supported actions):

  request_plane.dispatch   Client.generate, before the stream opens
                           (fail, delay)
  request_plane.frame      RequestPlaneServer._run_handler, per response
                           frame (drop, delay, truncate ≙ connection
                           lost mid-stream, fail)
  discovery.op             discovery backend put/delete/get_prefix
                           (fail = transient outage, delay)
  discovery.lease          lease keepalive/heartbeat (fail = miss the
                           refresh → lease expiry)
  disagg.pull.chunk        engine _stream_pull, per chunk op — covers
                           broker, transfer-server and host-staged
                           tiers (fail = pull failure partway through
                           the sequence, delay = slow peer)
  kvbm.remote_pull         RemoteKvbmPuller.fetch_run, per peer pull
                           (fail, delay, corrupt = flip a byte in the
                           frame payload before decode — the wire
                           checksum must catch it and mark the source
                           suspect)
  kvbm.object_io           ObjectStorePool get/put (kvbm/object_store.py,
                           on the G4 I/O thread) and SimObjectStore
                           lookups (mocker/kv_cache_sim.py), per op.
                           corrupt = payload bytes differ from the
                           committed crc32 → quarantine; stall = hung
                           shared mount → the caller's deadline +
                           tier breaker; fail = I/O error
  engine.step              JaxEngine._sched_step / MockEngine._step,
                           per scheduler step (fail = crash on step N,
                           wedge = stop stepping)
  engine.kv_account        BlockAllocator free/allocate, per violation
                           class (drop = seed the named accounting
                           fault: key carries leak / double_free /
                           orphan / refcount_drift — the kv-ledger
                           auditor must catch each, obs/kv_ledger.py)
  planner.scale            Planner tick EXECUTE, before the connector
                           call (fail = actuation failure the loop must
                           survive, delay = slow connector)
  connector.spawn          SubprocessConnector / CallbackConnector, per
                           replica spawn (fail = spawn failure — what
                           the backoff/circuit-breaker governor must
                           absorb instead of respawning every tick)
  worker.drain             JaxEngineWorker.drain / MockerWorker.drain
                           entry (wedge = a worker that IGNORES drain,
                           forcing the connector's bounded-wait →
                           stop escalation; fail = drain raising)
  grouter.classify         GlobalRouterService pool classification,
                           per request (fail = classifier fault — the
                           global router must degrade to round-robin
                           over pools, never drop the request; delay)
  router_sync.snapshot     RouterReplicaSync snapshot-on-subscribe
                           answer, per joining peer (fail = snapshot
                           build fault — the recv loop must drop the
                           frame and stay alive, the joiner's retry
                           re-requests it; delay)
"""

from __future__ import annotations

import asyncio
import logging
import random
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

# actions a rule may carry; "drop"/"truncate" are interpreted by the
# call site (only the frame seam understands them), and so are
# "corrupt" (the site tampers the bytes it just read, so the integrity
# checksum — not the injector — is what catches the fault) and "stall"
# (the site decides between really sleeping on its I/O thread and
# charging its deadline, so an event-loop site never blocks the loop);
# the rest are executed by hit()/ahit() themselves
ACTIONS = ("fail", "delay", "wedge", "drop", "truncate", "corrupt",
           "stall")

# THE canonical seam registry: every hit()/ahit() call site names one of
# these, ChaosPlane.rule() rejects anything else, and the DYN006 lint
# (lint/rules.py) checks the literals at the call sites statically.  A
# typo'd seam in a scenario used to be a rule that silently never fired
# — the scenario "passed" by injecting nothing; now it is a loud
# ValueError at rule() time and a lint finding at the seam site.  Keep
# this set, the docstring registry above, and the wired call sites in
# lockstep when adding a seam.
SEAMS = frozenset({
    "request_plane.dispatch",
    "request_plane.frame",
    "discovery.op",
    "discovery.lease",
    "disagg.pull.chunk",
    "kvbm.remote_pull",
    "kvbm.object_io",
    "engine.step",
    "engine.kv_account",
    "planner.scale",
    "connector.spawn",
    "worker.drain",
    "grouter.classify",
    "router_sync.snapshot",
})

# how long a "wedge" blocks when no delay_s is given: effectively
# forever at test/canary timescales, finite so a wedged thread can
# still unwind on interpreter shutdown
WEDGE_DEFAULT_S = 3600.0


class ChaosError(RuntimeError):
    """An injected fault.  A RuntimeError subclass whose message carries
    the marker of the real failure mode being simulated, so downstream
    handling (is_migratable classification, the migration operator's
    except clauses, pull fallbacks) sees exactly what a genuine fault
    would produce."""


@dataclass
class Rule:
    seam: str
    action: str
    p: float = 1.0          # injection probability per eligible hit
    after: int = 0          # skip the first `after` eligible hits
    times: Optional[int] = None  # max injections (None = unlimited)
    delay_s: float = 0.0    # for delay (and optionally wedge)
    error: str = ""         # ChaosError message for fail/truncate
    match: str = ""         # substring the hit key must contain
    # internal state
    hits: int = 0           # eligible hits seen (post-match)
    fired: int = 0          # injections performed
    rng: random.Random = field(default_factory=random.Random, repr=False)

    def message(self) -> str:
        if self.error:
            return self.error
        if self.action == "truncate":
            # a truncated stream is what a worker death looks like from
            # the client: classify like the real thing
            return f"connection lost (chaos: {self.seam} truncated)"
        return f"chaos injected fault at seam {self.seam!r}"


@dataclass(frozen=True)
class Injection:
    """One fired injection, for post-run assertions."""

    seam: str
    key: Optional[str]
    action: str
    n: int  # 1-based injection ordinal for its rule


class ChaosPlane:
    """A seeded set of injection rules.  Install process-globally with
    ``with plane:`` (or install()/uninstall()); seams are no-ops while
    no plane is installed."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rules: List[Rule] = []
        self.injections: List[Injection] = []
        # seams fire from both the event loop and the engine's scheduler
        # thread; the decision path must be consistent under that
        self._lock = threading.Lock()

    def rule(self, seam: str, action: str, *, p: float = 1.0,
             after: int = 0, times: Optional[int] = None,
             delay_s: float = 0.0, error: str = "",
             match: str = "") -> "ChaosPlane":
        if action not in ACTIONS:
            raise ValueError(f"unknown chaos action {action!r}")
        if seam not in SEAMS:
            raise ValueError(
                f"unknown chaos seam {seam!r}: a rule on an unregistered "
                f"seam would silently never fire; known seams: "
                f"{sorted(SEAMS)}")
        r = Rule(seam=seam, action=action, p=p, after=after, times=times,
                 delay_s=delay_s, error=error, match=match)
        # deterministic per-rule stream: seed ⊕ rule identity.  The
        # insertion index is part of the identity so two otherwise
        # identical rules draw independent streams — which also means a
        # scenario reproduces only if rules are added in the same order
        # (fine: scenarios are code, and replays rerun the same code)
        ident = f"{seam}|{action}|{match}|{len(self.rules)}"
        r.rng.seed(self.seed ^ zlib.crc32(ident.encode()))
        self.rules.append(r)
        return self

    # -- decision ---------------------------------------------------------
    def decide(self, seam: str, key: Optional[str] = None) -> Optional[Rule]:
        """The rule that fires for this hit, or None.  Counts the hit on
        every matching rule (so `after=N` means "the N+1th hit")."""
        with self._lock:
            for r in self.rules:
                if r.seam != seam:
                    continue
                if r.match and (key is None or r.match not in key):
                    continue
                r.hits += 1
                if r.hits <= r.after:
                    continue
                if r.times is not None and r.fired >= r.times:
                    continue
                if r.p < 1.0 and r.rng.random() >= r.p:
                    continue
                r.fired += 1
                inj = Injection(seam=seam, key=key, action=r.action,
                                n=r.fired)
                self.injections.append(inj)
                logger.warning("chaos: %s action=%s key=%s (#%d)",
                               seam, r.action, key, r.fired)
                return r
        return None

    def fired(self, seam: Optional[str] = None) -> int:
        return sum(1 for i in self.injections
                   if seam is None or i.seam == seam)

    # -- install ----------------------------------------------------------
    def install(self) -> "ChaosPlane":
        global _PLANE
        _PLANE = self
        return self

    def uninstall(self) -> None:
        global _PLANE
        if _PLANE is self:
            _PLANE = None

    def __enter__(self) -> "ChaosPlane":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


_PLANE: Optional[ChaosPlane] = None


def _flight_dump(seam: str) -> None:
    """An injection fired: snapshot the timeline tracer's ring (obs/)
    so the post-mortem has the spans that led up to the fault.  No-op
    when tracing is off; never lets observability break an injection."""
    try:
        from .. import obs

        obs.flight_dump(f"chaos.{seam}")
    except Exception:  # pragma: no cover
        logger.warning("chaos flight dump failed", exc_info=True)


def active() -> Optional[ChaosPlane]:
    return _PLANE


def hit(seam: str, key: Optional[str] = None) -> Optional[str]:
    """Synchronous seam (scheduler-thread sites).  Raises ChaosError on
    "fail"/"truncate"; blocks the calling thread on "delay"/"wedge";
    returns the action name for caller-interpreted actions, else None.
    No-op (one global check) when chaos is disabled."""
    if _PLANE is None:
        return None
    r = _PLANE.decide(seam, key)
    if r is None:
        return None
    _flight_dump(seam)
    if r.action in ("fail", "truncate"):
        raise ChaosError(r.message())
    if r.action == "delay":
        time.sleep(r.delay_s)
    elif r.action == "wedge":
        time.sleep(r.delay_s or WEDGE_DEFAULT_S)
    return r.action


async def ahit(seam: str, key: Optional[str] = None) -> Optional[str]:
    """Async seam (event-loop sites).  Same contract as hit(), with
    cooperative sleeps."""
    if _PLANE is None:
        return None
    r = _PLANE.decide(seam, key)
    if r is None:
        return None
    _flight_dump(seam)
    if r.action in ("fail", "truncate"):
        raise ChaosError(r.message())
    if r.action == "delay":
        await asyncio.sleep(r.delay_s)
    elif r.action == "wedge":
        await asyncio.sleep(r.delay_s or WEDGE_DEFAULT_S)
    return r.action


__all__ = [
    "ACTIONS",
    "SEAMS",
    "ChaosError",
    "ChaosPlane",
    "Injection",
    "Rule",
    "active",
    "ahit",
    "hit",
]
