"""KV-block transfer: the TPU-native replacement for NIXL.

Reference model (docs/design-docs/kvbm-design.md:171-230, disagg-serving.md:
17-21): prefill and decode exchange *serialized layout metadata* plus the
block payload; the decode side owns the pull.  On GPU the payload moves
VRAM→VRAM over UCX/NVLink/IB.  Here the transfer rides the request plane as
a host-staged stream (device→host→TCP→host→device) with an explicit layout
header — correct on any topology.  On multi-slice TPU deployments the same
protocol carries only metadata and the payload path is swapped for ICI/DCN
device-to-device transfer (jax transfer server / collective_permute); the
host-staged path remains the DCN fallback.

Resharding falls out of the design: payloads are *logical* blocks
[layers, n_blocks, block_size, kv_heads, head_dim] gathered to host from
whatever tp-sharding the prefill engine used, and re-sharded on inject by
the decode engine's GSPMD layout — prefill TP ≠ decode TP needs no special
case (the reference calls this out as a headline feature).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Tuple

import numpy as np

try:
    import ml_dtypes  # jax dependency; provides numpy bfloat16
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = None

_DTYPES = {"float32": np.float32, "float16": np.float16}


def _np_dtype(name: str):
    if name == "bfloat16":
        if _BF16 is None:
            raise ValueError("bfloat16 payload needs ml_dtypes")
        return _BF16
    return np.dtype(_DTYPES[name])


@dataclass
class KvBlockPayload:
    """One chunk of KV blocks with its layout header."""

    k: np.ndarray  # [layers, n_blocks, block_size, kv_heads, head_dim]
    v: np.ndarray

    @property
    def n_blocks(self) -> int:
        return self.k.shape[1]


def serialize_kv(k: np.ndarray, v: np.ndarray) -> Dict[str, Any]:
    """Payload → wire dict (msgpack-safe: bytes + plain lists)."""
    assert k.shape == v.shape
    return {
        "shape": list(k.shape),
        "dtype": k.dtype.name,
        "k": k.tobytes(),
        "v": v.tobytes(),
    }


def deserialize_kv(wire: Dict[str, Any]) -> KvBlockPayload:
    shape = tuple(wire["shape"])
    dt = _np_dtype(wire["dtype"])
    k = np.frombuffer(wire["k"], dtype=dt).reshape(shape)
    v = np.frombuffer(wire["v"], dtype=dt).reshape(shape)
    return KvBlockPayload(k=k, v=v)


def make_transfer_params(
    *,
    instance_id: int,
    request_id: str,
    prompt_len: int,
    first_token: int,
    block_size: int,
    num_layers: int,
    engine: str = "jax",
) -> Dict[str, Any]:
    """kv_transfer_params attached to the prefill response (the analogue of
    vLLM's NIXL block-id metadata / TRT-LLM's opaque_state,
    disagg-serving.md:53-61)."""
    return {
        "engine": engine,
        "instance_id": instance_id,
        "request_id": request_id,
        "prompt_len": prompt_len,
        "first_token": first_token,
        "block_size": block_size,
        "num_layers": num_layers,
    }
