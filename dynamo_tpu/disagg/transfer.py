"""KV-block transfer: the TPU-native replacement for NIXL.

Reference model (docs/design-docs/kvbm-design.md:171-230, disagg-serving.md:
17-21): prefill and decode exchange *serialized layout metadata* plus the
block payload; the decode side owns the pull.  On GPU the payload moves
VRAM→VRAM over UCX/NVLink/IB.  Here the transfer rides the request plane as
a host-staged stream (device→host→TCP→host→device) with an explicit layout
header — correct on any topology.  On multi-slice TPU deployments the same
protocol carries only metadata and the payload path is swapped for ICI/DCN
device-to-device transfer (jax transfer server / collective_permute); the
host-staged path remains the DCN fallback.

Wire protocol (one kv_pull stream):
  1. header frame — prompt_len + KvLayout (logical geometry + the sender's
     mesh shape).  The receiver validates *logical* compatibility
     (layers/heads/head_dim/block_size/dtype must match) and ignores the
     sender's parallelism: payloads are logical blocks
     [layers, n_blocks, block_size, kv_heads, head_dim] gathered to host
     from whatever tp-sharding the prefill engine used, and re-sharded on
     inject by the decode engine's own GSPMD layout.  prefill TP ≠ decode
     TP therefore needs no special case (the reference calls this out as a
     headline feature) — and is covered by tests/test_disagg.py.
  2. N chunk frames — (layer, block-range) slabs, each bounded by
     max_chunk_bytes so a long prompt's KV never approaches the request
     plane's frame cap, and the receiver can overlap deserialization with
     the network.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

try:
    import ml_dtypes  # jax dependency; provides numpy bfloat16
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = None

_DTYPES = {"float32": np.float32, "float16": np.float16}

# Default slab bound.  Well under the request plane's 256MB frame cap even
# after msgpack framing, large enough to amortize per-frame overhead.
DEFAULT_CHUNK_BYTES = 16 * 1024 * 1024


def _np_dtype(name: str):
    if name == "bfloat16":
        if _BF16 is None:
            raise ValueError("bfloat16 payload needs ml_dtypes")
        return _BF16
    return np.dtype(_DTYPES[name])


@dataclass
class KvLayout:
    """Logical geometry of a KV payload + the sender's parallel layout.

    The logical fields are contract: a mismatch is a model mismatch and the
    pull must fail.  The mesh fields are advisory (telemetry / future
    device-to-device path negotiation) — resharding is the receiver's
    GSPMD's job, not the protocol's."""

    num_layers: int
    num_blocks: int
    block_size: int
    kv_heads: int
    head_dim: int
    dtype: str
    tp: int = 1
    dp: int = 1
    # MLA engines cache an asymmetric pair (latent R vs rope-key dr,
    # models/deepseek.py) — 0 means "v matches k" (the GQA case)
    head_dim_v: int = 0

    @property
    def hd_v(self) -> int:
        return self.head_dim_v or self.head_dim

    def to_dict(self) -> Dict[str, Any]:
        return {
            "num_layers": self.num_layers, "num_blocks": self.num_blocks,
            "block_size": self.block_size, "kv_heads": self.kv_heads,
            "head_dim": self.head_dim, "dtype": self.dtype,
            "tp": self.tp, "dp": self.dp, "head_dim_v": self.head_dim_v,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "KvLayout":
        return cls(**{k: d[k] for k in (
            "num_layers", "num_blocks", "block_size", "kv_heads",
            "head_dim", "dtype")}, tp=d.get("tp", 1), dp=d.get("dp", 1),
            head_dim_v=d.get("head_dim_v", 0))

    @classmethod
    def of(cls, k: np.ndarray, tp: int = 1, dp: int = 1,
           v: Optional[np.ndarray] = None) -> "KvLayout":
        L, nb, bs, nkv, hd = k.shape
        hd_v = v.shape[4] if v is not None and v.shape[4] != hd else 0
        return cls(num_layers=L, num_blocks=nb, block_size=bs, kv_heads=nkv,
                   head_dim=hd, dtype=k.dtype.name, tp=tp, dp=dp,
                   head_dim_v=hd_v)

    def check_compatible(self, other: "KvLayout") -> None:
        """Logical-geometry contract check (tp/dp intentionally excluded)."""
        for f in ("num_layers", "block_size", "kv_heads", "head_dim",
                  "dtype"):
            a, b = getattr(self, f), getattr(other, f)
            if a != b:
                raise ValueError(
                    f"incompatible KV layout: {f} is {a} on the sender but "
                    f"{b} on the receiver"
                )
        if self.hd_v != other.hd_v:
            raise ValueError(
                f"incompatible KV layout: head_dim_v is {self.hd_v} on the "
                f"sender but {other.hd_v} on the receiver"
            )


@dataclass
class KvBlockPayload:
    """A fully reassembled KV payload."""

    k: np.ndarray  # [layers, n_blocks, block_size, kv_heads, head_dim]
    v: np.ndarray

    @property
    def n_blocks(self) -> int:
        return self.k.shape[1]


def make_header(prompt_len: int, layout: KvLayout) -> Dict[str, Any]:
    return {"prompt_len": prompt_len, "layout": layout.to_dict()}


def iter_chunks(
    k: np.ndarray, v: np.ndarray, max_bytes: int = DEFAULT_CHUNK_BYTES
) -> Iterator[Dict[str, Any]]:
    """Split [L, nb, bs, nkv, hd] K/V into wire frames of bounded size.

    Slabs never span layers (keeps indexing trivial); within a layer the
    block axis is split so that k-bytes + v-bytes <= max_bytes (a single
    block larger than max_bytes still goes out whole — the bound is a
    target, the frame cap is the hard limit).  k and v may differ in their
    last (head_dim) axis — the MLA latent/rope-key pair."""
    assert k.shape[:4] == v.shape[:4] and k.dtype == v.dtype
    L, nb = k.shape[0], k.shape[1]
    pair_bytes = (int(k[0, :1].nbytes) + int(v[0, :1].nbytes)) if nb else 0
    per = max(1, max_bytes // max(1, pair_bytes))
    for layer in range(L):
        for b0 in range(0, nb, per):
            b1 = min(nb, b0 + per)
            yield {
                "layer": layer,
                "block_start": b0,
                "block_count": b1 - b0,
                "k": np.ascontiguousarray(k[layer, b0:b1]).tobytes(),
                "v": np.ascontiguousarray(v[layer, b0:b1]).tobytes(),
            }


class ChunkAssembler:
    """Receiver side: header + chunk frames → KvBlockPayload.

    Allocates the destination once from the header layout and writes each
    slab in place — no per-chunk concatenation garbage."""

    def __init__(self, header: Dict[str, Any],
                 expect: Optional[KvLayout] = None,
                 max_blocks: Optional[int] = None):
        self.prompt_len = int(header["prompt_len"])
        self.layout = KvLayout.from_dict(header["layout"])
        if expect is not None:
            self.layout.check_compatible(expect)
        if max_blocks is not None and self.layout.num_blocks > max_blocks:
            # the allocation below is sized entirely by the sender's header;
            # without this cap a corrupt header OOMs the receiver before a
            # single payload byte arrives
            raise ValueError(
                f"KV transfer of {self.layout.num_blocks} blocks exceeds "
                f"the receiver's limit of {max_blocks}"
            )
        lo = self.layout
        dt = _np_dtype(lo.dtype)
        self.k = np.zeros((lo.num_layers, lo.num_blocks, lo.block_size,
                           lo.kv_heads, lo.head_dim), dt)
        self.v = np.zeros((lo.num_layers, lo.num_blocks, lo.block_size,
                           lo.kv_heads, lo.hd_v), dt)
        self._filled = np.zeros((lo.num_layers, lo.num_blocks), bool)

    def add(self, frame: Dict[str, Any]) -> None:
        lo = self.layout
        layer = int(frame["layer"])
        b0 = int(frame["block_start"])
        n = int(frame["block_count"])
        if not (0 <= layer < lo.num_layers and 0 <= b0 and
                b0 + n <= lo.num_blocks):
            raise ValueError(f"chunk out of bounds: layer={layer} "
                             f"blocks=[{b0},{b0 + n})")
        dt = _np_dtype(lo.dtype)
        self.k[layer, b0:b0 + n] = np.frombuffer(
            frame["k"], dtype=dt).reshape(
                (n, lo.block_size, lo.kv_heads, lo.head_dim))
        self.v[layer, b0:b0 + n] = np.frombuffer(
            frame["v"], dtype=dt).reshape(
                (n, lo.block_size, lo.kv_heads, lo.hd_v))
        self._filled[layer, b0:b0 + n] = True

    def finish(self) -> KvBlockPayload:
        if not self._filled.all():
            missing = int((~self._filled).sum())
            raise ValueError(
                f"incomplete KV transfer: {missing} (layer, block) slabs "
                "never arrived"
            )
        return KvBlockPayload(k=self.k, v=self.v)


def make_transfer_params(
    *,
    instance_id: int,
    request_id: str,
    prompt_len: int,
    first_token: int,
    block_size: int,
    num_layers: int,
    engine: str = "jax",
) -> Dict[str, Any]:
    """kv_transfer_params attached to the prefill response (the analogue of
    vLLM's NIXL block-id metadata / TRT-LLM's opaque_state,
    disagg-serving.md:53-61)."""
    return {
        "engine": engine,
        "instance_id": instance_id,
        "request_id": request_id,
        "prompt_len": prompt_len,
        "first_token": first_token,
        "block_size": block_size,
        "num_layers": num_layers,
    }
