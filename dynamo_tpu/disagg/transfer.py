"""KV-block transfer: the TPU-native replacement for NIXL.

Reference model (docs/design-docs/kvbm-design.md:171-230, disagg-serving.md:
17-21): prefill and decode exchange *serialized layout metadata* plus the
block payload; the decode side owns the pull.  On GPU the payload moves
VRAM->VRAM over UCX/NVLink/IB.  Here the pull is RECEIVER-PACED and tiered
by deployment shape (the receiver picks the best available path):

  tier 1 — same process (engines sharing one JAX runtime, e.g. split
           sub-meshes of one slice): block chunks stay DEVICE-RESIDENT;
           the receiver `jax.device_put`s the sender's gathered chunk onto
           its own mesh sharding, so the bytes move over ICI without a
           host round-trip (disagg/broker.py).
  tier 2 — separate processes with the JAX transfer server available
           (jax.experimental.transfer, DCN cross-slice transfer): the
           request plane carries per-chunk METADATA (a uuid); the payload
           moves device-to-device through the transfer server
           (disagg/device_transfer.py).
  tier 3 — host-staged fallback, correct on any topology: chunks gather
           to host and ride the request plane as msgpack byte frames
           (RequestPlanePullSource below).

All tiers speak the same receiver-paced op protocol against the sender's
`kv_pull` endpoint:

  {"op": "open",  "request_id"}                  -> header frame
      header = {prompt_len, layout: KvLayout}    (+ "transfer_addr" when
      the sender runs a transfer server — tier-2 capability advertisement)
  {"op": "chunk", "request_id", "start", "count"[, "via": "transfer"]}
      -> one chunk frame: {"block_start", "block_count", "k", "v"} bytes
      (tier 3) or {"uuid": int} (tier 2 — pull the payload from the
      transfer server under that uuid)
  {"op": "close", "request_id"}                  -> {} (release parked KV)

Receiver pacing is what makes the pull STREAMING: each chunk is one
scheduler op on each engine, so decode bursts interleave with both the
sender's gathers and the receiver's injects, and neither side ever holds
more than one chunk of payload in host memory (the round-3 review called
out the whole-prompt triple materialization this replaces).

The logical layout contract is unchanged: payloads are logical blocks
[layers, n_blocks, block_size, kv_heads, head_dim] in the universal
transfer layout, gathered from whatever tp-sharding the prefill engine
used and re-sharded on inject by the decode engine's own GSPMD layout —
prefill TP != decode TP needs no special case (the reference calls this
out as a headline feature).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .. import obs

try:
    import ml_dtypes  # jax dependency; provides numpy bfloat16
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = None

_DTYPES = {"float32": np.float32, "float16": np.float16,
           "int8": np.int8}

# Default chunk bound.  Well under the request plane's 256MB frame cap even
# after msgpack framing, large enough to amortize per-frame overhead.
DEFAULT_CHUNK_BYTES = 16 * 1024 * 1024


def _np_dtype(name: str):
    if name == "bfloat16":
        if _BF16 is None:
            raise ValueError("bfloat16 payload needs ml_dtypes")
        return _BF16
    return np.dtype(_DTYPES[name])


@dataclass
class KvLayout:
    """Logical geometry of a KV payload + the sender's parallel layout.

    The logical fields are contract: a mismatch is a model mismatch and the
    pull must fail.  The mesh fields are advisory (telemetry / transfer
    path negotiation) — resharding is the receiver's GSPMD's job, not the
    protocol's."""

    num_layers: int
    num_blocks: int
    block_size: int
    kv_heads: int
    head_dim: int
    dtype: str
    tp: int = 1
    dp: int = 1
    # MLA engines cache an asymmetric pair (latent R vs rope-key dr,
    # models/deepseek.py) — 0 means "v matches k" (the GQA case)
    head_dim_v: int = 0
    # int8-quantized payload (quant/kv.py): chunks carry fp32 scale
    # planes [L, n, bs, nkv] alongside k/v — the quantized representation
    # rides the wire verbatim (half the payload bytes, scales bit-exact)
    scales: bool = False

    @property
    def hd_v(self) -> int:
        return self.head_dim_v or self.head_dim

    def to_dict(self) -> Dict[str, Any]:
        return {
            "num_layers": self.num_layers, "num_blocks": self.num_blocks,
            "block_size": self.block_size, "kv_heads": self.kv_heads,
            "head_dim": self.head_dim, "dtype": self.dtype,
            "tp": self.tp, "dp": self.dp, "head_dim_v": self.head_dim_v,
            "scales": self.scales,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "KvLayout":
        return cls(**{k: d[k] for k in (
            "num_layers", "num_blocks", "block_size", "kv_heads",
            "head_dim", "dtype")}, tp=d.get("tp", 1), dp=d.get("dp", 1),
            head_dim_v=d.get("head_dim_v", 0),
            scales=bool(d.get("scales", False)))

    @classmethod
    def of(cls, k, tp: int = 1, dp: int = 1, v=None,
           scales: bool = False) -> "KvLayout":
        """From a universal-layout K (and optionally V) array."""
        L, nb, bs, nkv, hd = k.shape
        hd_v = v.shape[4] if v is not None and v.shape[4] != hd else 0
        return cls(num_layers=L, num_blocks=nb, block_size=bs, kv_heads=nkv,
                   head_dim=hd, dtype=np.dtype(k.dtype).name, tp=tp, dp=dp,
                   head_dim_v=hd_v, scales=scales)

    def check_compatible(self, other: "KvLayout") -> None:
        """Logical-geometry contract check (tp/dp intentionally excluded).
        `dtype`/`scales` are part of the contract: an int8 payload cannot
        scatter into a bf16 cache (or vice versa) without silent
        corruption — mixed-dtype disagg pairs must fail the pull (the
        decode side then falls back to local prefill)."""
        for f in ("num_layers", "block_size", "kv_heads", "head_dim",
                  "dtype", "scales"):
            a, b = getattr(self, f), getattr(other, f)
            if a != b:
                raise ValueError(
                    f"incompatible KV layout: {f} is {a} on the sender but "
                    f"{b} on the receiver"
                )
        if self.hd_v != other.hd_v:
            raise ValueError(
                f"incompatible KV layout: head_dim_v is {self.hd_v} on the "
                f"sender but {other.hd_v} on the receiver"
            )

    # -- chunk sizing -----------------------------------------------------
    def block_bytes(self) -> int:
        """Payload bytes of ONE block across all layers (k + v, plus the
        fp32 scale planes for a quantized payload)."""
        dt = _np_dtype(self.dtype)
        per_tok = self.kv_heads * (self.head_dim + self.hd_v)
        data = self.num_layers * self.block_size * per_tok * dt.itemsize
        if self.scales:
            data += self.num_layers * self.block_size * self.kv_heads * 2 * 4
        return data

    def blocks_per_chunk(self, max_bytes: int = DEFAULT_CHUNK_BYTES) -> int:
        """Whole blocks per chunk under the byte bound (always >= 1: the
        bound is a target; the request plane's frame cap is the hard
        limit)."""
        return max(1, max_bytes // max(1, self.block_bytes()))


def make_header(prompt_len: int, layout: KvLayout,
                transfer_addr: Optional[str] = None) -> Dict[str, Any]:
    h: Dict[str, Any] = {"prompt_len": prompt_len,
                         "layout": layout.to_dict()}
    if transfer_addr:
        h["transfer_addr"] = transfer_addr
    return h


def encode_chunk_frame(b0: int, kb: np.ndarray, vb: np.ndarray,
                       ksb: np.ndarray = None,
                       vsb: np.ndarray = None) -> Dict[str, Any]:
    """Host-staged chunk -> wire frame.  kb/vb are universal-layout
    [L, n, bs, nkv, hd] for the block range [b0, b0+n); a quantized
    payload adds the fp32 scale planes ksb/vsb [L, n, bs, nkv]."""
    frame = {
        "block_start": int(b0),
        "block_count": int(kb.shape[1]),
        "k": np.ascontiguousarray(kb).tobytes(),
        "v": np.ascontiguousarray(vb).tobytes(),
    }
    if ksb is not None:
        frame["ks"] = np.ascontiguousarray(ksb).tobytes()
        frame["vs"] = np.ascontiguousarray(vsb).tobytes()
    frame["crc"] = _frame_crc(frame)
    return frame


def _frame_crc(frame: Dict[str, Any]) -> int:
    """crc32 over the frame's payload byte members in canonical order,
    seeded with (block_start, block_count) so a frame spliced onto the
    wrong block range fails verification too."""
    import zlib

    crc = zlib.crc32(
        f"{int(frame['block_start'])}:{int(frame['block_count'])}"
        .encode())
    for name in ("k", "v", "ks", "vs"):
        if name in frame:
            crc = zlib.crc32(frame[name], crc)
    return crc & 0xFFFFFFFF


def decode_chunk_frame(
    frame: Dict[str, Any], layout: KvLayout
) -> Tuple[Any, ...]:
    """Wire frame -> (b0, n, kb, vb[, ksb, vsb]) with bounds checked
    against the header layout (a corrupt frame must not write outside the
    payload).  The scale planes come back only when the layout declares
    them — and a declaring layout REQUIRES them (a frame without scales
    for an int8 payload is corrupt)."""
    b0 = int(frame["block_start"])
    n = int(frame["block_count"])
    if not (0 <= b0 and n >= 1 and b0 + n <= layout.num_blocks):
        raise ValueError(f"chunk out of bounds: blocks=[{b0},{b0 + n}) of "
                         f"{layout.num_blocks}")
    if "crc" in frame and _frame_crc(frame) != int(frame["crc"]):
        # same failure family as every other malformed frame — the
        # caller's existing local-prefill fallback handles it (a frame
        # without a crc is an unupgraded sender and passes)
        raise ValueError(
            f"chunk frame for blocks [{b0},{b0 + n}) failed its crc32 "
            "footer")
    dt = _np_dtype(layout.dtype)
    lo = layout
    kb = np.frombuffer(frame["k"], dtype=dt).reshape(
        (lo.num_layers, n, lo.block_size, lo.kv_heads, lo.head_dim))
    vb = np.frombuffer(frame["v"], dtype=dt).reshape(
        (lo.num_layers, n, lo.block_size, lo.kv_heads, lo.hd_v))
    if not lo.scales:
        return b0, n, kb, vb
    if "ks" not in frame or "vs" not in frame:
        raise ValueError("quantized chunk frame is missing scale planes")
    sshape = (lo.num_layers, n, lo.block_size, lo.kv_heads)
    ksb = np.frombuffer(frame["ks"], dtype=np.float32).reshape(sshape)
    vsb = np.frombuffer(frame["vs"], dtype=np.float32).reshape(sshape)
    return b0, n, kb, vb, ksb, vsb


class PullSource:
    """Receiver-side pull driver interface (the engine paces it).

    open()  -> header dict ({"prompt_len", "layout", ...})
    chunk(b0, n) -> (kb, vb) — plus (ksb, vsb) scale planes for an int8
        payload — for blocks [b0, b0+n): numpy arrays (tier 3) or device
        arrays (tiers 1-2; the engine device_puts them onto its own
        sharding before injecting)
    close() -> release the sender's parked KV.  Idempotent; called on
        success AND failure."""

    async def open(self) -> Dict[str, Any]:
        raise NotImplementedError

    async def chunk(self, b0: int, n: int) -> Tuple[Any, ...]:
        raise NotImplementedError

    async def close(self) -> None:
        raise NotImplementedError


class RequestPlanePullSource(PullSource):
    """Tier 3: host-staged chunks over the request plane (the universal
    fallback).  One RPC per op; the sender gathers each chunk as its own
    scheduler op, so its decode interleaves with the extraction."""

    def __init__(self, client, params: Dict[str, Any]):
        self.client = client
        self.params = params
        self.layout: Optional[KvLayout] = None

    async def _call(self, body: Dict[str, Any]) -> Dict[str, Any]:
        out = None
        async for item in self.client.generate(
            body, instance_id=self.params["instance_id"]
        ):
            out = item
        if out is None:
            raise RuntimeError("empty kv_pull response")
        return out

    async def open(self) -> Dict[str, Any]:
        with obs.span("disagg_open",
                      request_id=self.params["request_id"]):
            header = await self._call(
                {"op": "open", "request_id": self.params["request_id"]})
        self.layout = KvLayout.from_dict(header["layout"])
        return header

    async def chunk(self, b0: int, n: int):
        with obs.span("disagg_chunk",
                      request_id=self.params["request_id"],
                      start=int(b0), count=int(n)):
            frame = await self._call({
                "op": "chunk", "request_id": self.params["request_id"],
                "start": int(b0), "count": int(n),
            })
        out = decode_chunk_frame(frame, self.layout)
        fb0, fn, arrs = out[0], out[1], out[2:]
        if fb0 != b0 or fn != n:
            raise ValueError(f"sender returned blocks [{fb0},{fb0 + fn}) "
                             f"for a request of [{b0},{b0 + n})")
        return arrs

    async def close(self) -> None:
        try:
            await self._call({"op": "close",
                              "request_id": self.params["request_id"]})
        except Exception:
            pass  # sender-side TTL reaps unreleased parks


def make_transfer_params(
    *,
    instance_id: int,
    request_id: str,
    prompt_len: int,
    first_token: int,
    block_size: int,
    num_layers: int,
    engine: str = "jax",
) -> Dict[str, Any]:
    """kv_transfer_params attached to the prefill response (the analogue of
    vLLM's NIXL block-id metadata / TRT-LLM's opaque_state,
    disagg-serving.md:53-61)."""
    return {
        "engine": engine,
        "instance_id": instance_id,
        "request_id": request_id,
        "prompt_len": prompt_len,
        "first_token": first_token,
        "block_size": block_size,
        "num_layers": num_layers,
    }
