"""Tier-2 device-to-device KV transfer via the JAX transfer server.

jax.experimental.transfer ("DCN cross slice transfer") moves device
arrays between separate JAX processes: the sender parks arrays under a
uuid (`TransferServer.await_pull`), the receiver connects to the
sender's advertised address and pulls them into ITS OWN devices/sharding
(`TransferConnection.pull`).  This is the closest TPU analogue of the
reference's NIXL RDMA pull (docs/design-docs/kvbm-design.md:171-230):
payload bytes never transit the request plane — only per-chunk METADATA
(the uuid) does.

Availability is probed once per process: the API needs PJRT support
(CreateBuffersForAsyncHostToDevice); where it is missing (e.g. some
plugin backends) every helper degrades to "unavailable" and callers fall
back to the host-staged tier.  Capability is advertised in the kv_pull
header (`transfer_addr`), so mixed fleets negotiate per-pull.
"""

from __future__ import annotations

import itertools
import logging
import threading
from typing import Any, Dict, Optional, Tuple

from .transfer import RequestPlanePullSource

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_server = None
_server_failed = False
_uuid_counter = itertools.count(1)


def get_transfer_server():
    """The process-wide transfer server, started lazily; None when the
    backend does not support it OR when not explicitly enabled.

    OPT-IN via DYN_KV_TRANSFER_SERVER=1: the in-process loopback probe
    below cannot prove the backend's CROSS-process bulk transport works,
    and on at least one PJRT plugin a real cross-process pull aborts the
    SENDER process (fatal in the aux socket transport) — a dead prefill
    worker is far worse than a host-staged copy.  Deployments on
    backends with known-good DCN transfer enable it explicitly."""
    global _server, _server_failed
    import os

    if os.environ.get("DYN_KV_TRANSFER_SERVER", "0").lower() not in (
            "1", "true", "yes", "on"):
        return None
    with _lock:
        if _server is not None or _server_failed:
            return _server
        try:
            import jax
            from jax.experimental import transfer

            client = jax.devices()[0].client
            srv = transfer.start_transfer_server(client)
            # probe a real round-trip: some backends construct the server
            # but fail on pull (UNIMPLEMENTED PJRT hooks)
            import numpy as np

            x = jax.device_put(np.zeros(8, np.float32))
            uid = next(_uuid_counter)
            srv.await_pull(uid, [x])
            conn = srv.connect(srv.address())
            out = conn.pull(uid, [jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=x.sharding)])
            np.asarray(out[0])
            _server = srv
            logger.info("jax transfer server at %s", srv.address())
        except Exception as e:  # pragma: no cover - backend-dependent
            logger.info("jax transfer server unavailable (%s); "
                        "device-to-device pulls fall back to host staging",
                        e)
            _server_failed = True
        return _server


def next_uuid() -> int:
    return next(_uuid_counter)


class SenderChunkRegistry:
    """Sender-side refs for chunks parked in the transfer server.

    await_pull gives no completion signal, so the arrays must stay
    referenced until the receiver has pulled them.  The registry keeps AT
    MOST ONE outstanding chunk per request (the receiver is paced: it
    pulls chunk i before asking for i+1, so registering i+1 proves i is
    consumed) and drops everything for a request on close or TTL sweep
    (a receiver that dies mid-pull must not pin device memory forever —
    the worker sweeps from its load loop)."""

    def __init__(self):
        import time

        self._now = time.monotonic
        self._parked: Dict[str, Tuple[int, Any, float]] = {}

    def park(self, request_id: str, uuid: int, arrays) -> None:
        self._parked[request_id] = (uuid, arrays, self._now())

    def release(self, request_id: str) -> None:
        self._parked.pop(request_id, None)

    def sweep(self, max_age_s: float = 120.0) -> int:
        """Drop refs whose receiver never finished; mirrors the engine's
        parked-KV TTL."""
        cutoff = self._now() - max_age_s
        stale = [r for r, (_, _, t) in self._parked.items() if t < cutoff]
        for r in stale:
            del self._parked[r]
        return len(stale)

    def __len__(self) -> int:
        return len(self._parked)


class NegotiatedPullSource(RequestPlanePullSource):
    """Receiver pull source that negotiates tier 2 per pull.

    Opens over the request plane like the host-staged tier (the base
    class); if the sender's header advertises a transfer server AND this
    process has one too, chunk payloads switch to device-to-device pulls
    (the chunk RPC carries only a uuid); otherwise chunks arrive as host
    byte frames — so mixed fleets (e.g. a backend whose PJRT lacks
    transfer support talking to one that has it) always interoperate."""

    def __init__(self, client, params: Dict[str, Any],
                 device: Any = None, allow_transfer: bool = True):
        """device: the jax device pulled chunks land on (the receiving
        engine's first mesh device).  The wire format is canonically
        SINGLE-shard — the transfer server requires identical shard
        structure on both ends (probed empirically), and prefill TP never
        needs to match decode TP here, so each side reshards locally over
        ICI (sender: gather to one device; receiver: inject device_puts
        onto its own sharding).  A matched-topology multi-stream fast
        path is a future optimization."""
        super().__init__(client, params)
        self.device = device
        self.allow_transfer = allow_transfer and device is not None
        self._conn = None

    @property
    def device_resident(self) -> bool:
        """True once tier 2 is negotiated: chunks land as device arrays,
        so the receiver can size chunks for the device path."""
        return self._conn is not None

    async def open(self) -> Dict[str, Any]:
        header = await super().open()
        addr = header.get("transfer_addr")
        if addr and self.allow_transfer:
            srv = get_transfer_server()
            if srv is not None:
                try:
                    self._conn = srv.connect(addr)
                    logger.info("kv pull %s: device-to-device via "
                                "transfer server %s",
                                self.params["request_id"], addr)
                except Exception:
                    logger.warning("transfer server connect to %s failed; "
                                   "host-staged fallback", addr,
                                   exc_info=True)
                    self._conn = None
        return header

    async def chunk(self, b0: int, n: int):
        if self._conn is None:
            return await self._host_chunk(b0, n)
        try:
            return await self._device_chunk(b0, n)
        except Exception:
            # a failed device pull (connection torn down mid-stream, PJRT
            # quirk) degrades the REST of this pull to host frames
            logger.warning("device-to-device chunk [%d,%d) failed; "
                           "host-staged fallback", b0, b0 + n,
                           exc_info=True)
            self._conn = None
            return await self._host_chunk(b0, n)

    async def _host_chunk(self, b0: int, n: int):
        return await RequestPlanePullSource.chunk(self, b0, n)

    async def _device_chunk(self, b0: int, n: int):
        import asyncio

        import jax

        from .transfer import _np_dtype

        reply = await self._call({
            "op": "chunk", "request_id": self.params["request_id"],
            "start": int(b0), "count": int(n), "via": "transfer",
        })
        if "uuid" not in reply:
            raise RuntimeError("sender refused transfer-server chunk")
        uuid = int(reply["uuid"])
        lo = self.layout
        dt = _np_dtype(lo.dtype)
        sh = jax.sharding.SingleDeviceSharding(self.device)
        sds = [
            jax.ShapeDtypeStruct(
                (lo.num_layers, n, lo.block_size, lo.kv_heads,
                 lo.head_dim), dt, sharding=sh),
            jax.ShapeDtypeStruct(
                (lo.num_layers, n, lo.block_size, lo.kv_heads, lo.hd_v),
                dt, sharding=sh),
        ]
        if lo.scales:
            # int8 payload: the sender parked fp32 scale planes too
            sshape = (lo.num_layers, n, lo.block_size, lo.kv_heads)
            import numpy as np

            sds += [jax.ShapeDtypeStruct(sshape, np.float32, sharding=sh)
                    for _ in range(2)]
        # conn.pull blocks on the wire; keep the event loop free
        out = await asyncio.to_thread(self._conn.pull, uuid, sds)
        return tuple(out)
