from .prefill_router import ConditionalDisaggConfig, PrefillOrchestrator
from .transfer import (
    KvLayout,
    PullSource,
    RequestPlanePullSource,
    decode_chunk_frame,
    encode_chunk_frame,
    make_header,
    make_transfer_params,
)

__all__ = [
    "ConditionalDisaggConfig",
    "KvLayout",
    "PrefillOrchestrator",
    "PullSource",
    "RequestPlanePullSource",
    "decode_chunk_frame",
    "encode_chunk_frame",
    "make_header",
    "make_transfer_params",
]
