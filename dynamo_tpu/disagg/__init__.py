from .prefill_router import ConditionalDisaggConfig, PrefillOrchestrator
from .transfer import KvBlockPayload, deserialize_kv, serialize_kv

__all__ = [
    "ConditionalDisaggConfig",
    "KvBlockPayload",
    "PrefillOrchestrator",
    "deserialize_kv",
    "serialize_kv",
]
