from .prefill_router import ConditionalDisaggConfig, PrefillOrchestrator
from .transfer import ChunkAssembler, KvBlockPayload, KvLayout, iter_chunks

__all__ = [
    "ChunkAssembler",
    "ConditionalDisaggConfig",
    "KvBlockPayload",
    "KvLayout",
    "PrefillOrchestrator",
    "iter_chunks",
]
