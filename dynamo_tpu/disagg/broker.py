"""In-process engine broker: tier-1 device-to-device KV pulls.

When the prefill and decode engines live in ONE JAX process (split
sub-meshes of a slice, or two engines time-sharing a chip), the transfer
needs no transport at all: the receiver `jax.device_put`s the sender's
gathered chunk onto its own mesh sharding and XLA moves the bytes
device-to-device (ICI on real hardware) — the host never touches the
payload.  This is the TPU analogue of NIXL's NVLink path
(docs/design-docs/disagg-serving.md:17-21) for co-located engines.

The broker is a process-global registry: workers register their engine
under their instance_id at startup; a decode worker's pull first checks
the registry and only falls back to the network tiers on a miss.

Multi-host caveat: followers replay inject steps with the payload riding
the step stream as host bytes (parallel/multihost.py), so device-resident
chunks would force a host gather anyway — workers therefore only take
this tier when the slice is single-host (worker.py gates on world == 1).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

_ENGINES: Dict[int, Any] = {}


def register_engine(instance_id: int, engine) -> None:
    _ENGINES[int(instance_id)] = engine


def deregister_engine(instance_id: int) -> None:
    _ENGINES.pop(int(instance_id), None)


def lookup_engine(instance_id: int):
    return _ENGINES.get(int(instance_id))


class LocalEnginePullSource:
    """Tier 1: chunks stay device-resident end to end.

    chunk() returns the sender's gathered device arrays; the receiving
    engine device_puts them onto its own sharding (the actual ICI move)
    inside its inject op.  Each gather is one scheduler op on the SENDER,
    so its decode keeps stepping during the extraction."""

    # chunks are device arrays: the receiver may use device-sized chunks
    # (no host frame bound) and pipeline gathers against injects
    device_resident = True

    def __init__(self, src_engine, request_id: str):
        self.src = src_engine
        self.request_id = request_id

    async def open(self) -> Dict[str, Any]:
        from .transfer import KvLayout, make_header

        n_blocks, prompt_len = await self.src.parked_info(self.request_id)
        lo = self.src.kv_wire_layout(n_blocks)
        return make_header(prompt_len, lo)

    async def chunk(self, b0: int, n: int) -> Tuple[Any, ...]:
        # (kb, vb) — plus (ksb, vsb) scale planes when the sender's cache
        # is int8-quantized (the payload moves quantized, never dequanted)
        return await self.src.extract_parked_chunk(
            self.request_id, b0, n, to_host=False)

    async def close(self) -> None:
        try:
            await self.src.release_parked(self.request_id)
        except Exception:
            pass
