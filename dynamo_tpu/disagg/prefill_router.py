"""Frontend-side disaggregation: prefill orchestration + conditional bypass.

Ref: lib/llm/src/kv_router/prefill_router/mod.rs:137 (PrefillRouter) and
lib/kv-router/src/conditional_disagg.rs:11-18.  The orchestrator sits between
the preprocessor and the decode router: it sends the request to a prefill
worker (annotated `disagg_prefill`), receives `kv_transfer_params`, and
attaches them to the decode request.  The conditional-disagg policy bypasses
the remote hop when the *effective* prefill (tokens not already cached on
the decode fleet) is too small to be worth a transfer.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, replace
from typing import Optional

from ..protocols import LLMEngineOutput, PreprocessedRequest
from ..protocols.llm import DISAGG_ANNOTATION
from ..runtime import Client

logger = logging.getLogger(__name__)


@dataclass
class ConditionalDisaggConfig:
    """Thresholds from the reference (conditional_disagg.rs): remote prefill
    only if effective ISL >= min_effective_isl AND effective/total >= ratio."""

    min_effective_isl: int = 2048
    min_effective_ratio: float = 0.7
    always_remote: bool = False  # force remote (benchmarks/tests)


class PrefillOrchestrator:
    def __init__(self, prefill_client: Client,
                 config: Optional[ConditionalDisaggConfig] = None,
                 prefill_route=None,
                 decode_overlap_fn=None):
        """prefill_route: optional KvRouter over the prefill fleet.
        decode_overlap_fn(request) -> cached blocks on the likely decode
        target (for effective-ISL computation)."""
        self.client = prefill_client
        self.config = config or ConditionalDisaggConfig()
        self.prefill_route = prefill_route
        self.decode_overlap_fn = decode_overlap_fn

    def should_disagg(self, request: PreprocessedRequest,
                      overlap_tokens: int) -> bool:
        if self.config.always_remote:
            return True
        isl = len(request.token_ids)
        effective = max(0, isl - overlap_tokens)
        if effective < self.config.min_effective_isl:
            return False
        if isl > 0 and effective / isl < self.config.min_effective_ratio:
            return False
        return True

    async def maybe_prefill(
        self, request: PreprocessedRequest, token=None
    ) -> PreprocessedRequest:
        """Run the remote-prefill hop; returns the request to hand to the
        decode router (with disaggregated_params on success)."""
        overlap_tokens = 0
        if self.decode_overlap_fn is not None:
            overlap_tokens = await self.decode_overlap_fn(request)
        if not self.should_disagg(request, overlap_tokens):
            return request

        prefill_req = replace(
            request,
            annotations=list(request.annotations) + [DISAGG_ANNOTATION],
        )
        instance_id = None
        if self.prefill_route is not None:
            instance_id = await self.prefill_route(prefill_req, avoid=None)
        try:
            params = None
            forensic = None
            async for item in self.client.generate(
                prefill_req.to_dict(), instance_id=instance_id, token=token
            ):
                out = LLMEngineOutput.from_dict(item)
                if out.kv_transfer_params is not None:
                    params = out.kv_transfer_params
                if out.metrics and "forensic" in out.metrics:
                    # the prefill worker's stamp (realized prefix reuse,
                    # queue position — obs/forensics.py): ride it on the
                    # transfer params so the frontend's prefill_done hop
                    # carries the hop's own facts (the decode worker's
                    # stream only ever stamps the decode side)
                    forensic = out.metrics["forensic"]
            if params is not None and forensic is not None:
                params = {**params, "prefill_forensic": forensic}
            if params is None:
                logger.warning(
                    "prefill worker returned no kv_transfer_params for %s; "
                    "falling back to local prefill", request.request_id)
                return request
            return replace(request, disaggregated_params=params)
        except Exception:
            # remote prefill is an optimization; decode-local prefill is the
            # always-correct fallback (ref: admission bypass)
            logger.warning("remote prefill failed for %s; local fallback",
                           request.request_id, exc_info=True)
            return request
        finally:
            if self.prefill_route is not None and hasattr(
                self.prefill_route, "complete"
            ):
                self.prefill_route.complete(prefill_req.request_id)

    async def close(self) -> None:
        if self.prefill_route is not None and hasattr(self.prefill_route, "close"):
            await self.prefill_route.close()
        await self.client.close()
