"""Zero-weight n-gram (prompt-lookup) proposer.

Drafts come from the sequence's OWN history: the longest n-gram suffix of
(prompt + generated output) is matched against every earlier position,
and the tokens that followed the most recent previous occurrence become
the proposal.  No weights, no device programs, no extra HBM — the
proposer runs on the scheduler thread in microseconds, which is why it
is the tier-1 test proposer and the default production choice for
repetitive workloads (extraction, code completion, templated JSON, and
any greedy stream that has entered a cycle).

The acceptance dynamics are self-regulating at the engine level: when
history matches predict the target model well the engine's per-sequence
acceptance EMA keeps the draft length up; on non-repetitive text matches
either don't exist (propose() returns [] and the step costs nothing) or
get rejected, and the EMA collapses the sequence back to plain decode.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


class NgramProposer:
    """Prompt-lookup proposer (vLLM's ngram speculator, Saxena 2023).

    max_ngram/min_ngram bound the suffix lengths tried, longest first —
    a longer match is a stronger signal, so it wins over a more recent
    shorter one."""

    name = "ngram"

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"[{min_ngram}, {max_ngram}]")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose(self, tokens: Sequence[int], k: int, *, ctx: int = 0,
                draft_pos: int = 0, block_table=None) -> List[int]:
        """Up to k draft tokens continuing `tokens`, or [] when no
        suffix n-gram recurs in the history.  ctx/draft_pos/block_table
        are the draft-model proposer's bookkeeping; ignored here."""
        a = np.asarray(tokens, dtype=np.int64)
        L = len(a)
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            # candidate starts i in [0, L-n-1]: every window a[i:i+n]
            # starts before the suffix's own start (the self-match at
            # i = L-n is out of range by construction, so overlapping
            # recurrences right up against the suffix — the onset of
            # token-level repetition — are legitimate candidates) and
            # leaves >= 1 token after it
            if L < n + 2:
                continue
            suffix = a[-n:]
            ok = np.ones(L - n, dtype=bool)
            for j in range(n):
                ok &= a[j:j + L - n] == suffix[j]
            hits = np.nonzero(ok)[0]
            if len(hits) == 0:
                continue
            # most recent occurrence still followed by k tokens; when
            # every recurrence sits closer to the end than that, fall
            # back to the earliest one (longest available continuation)
            full = hits[hits + n + k <= L]
            i = int(full[-1]) if len(full) else int(hits[0])
            drafts = a[i + n:i + n + k]
            if len(drafts):
                return [int(t) for t in drafts]
        return []
