"""Verification packing planner: speculating slots -> ONE packed program.

Each speculating slot contributes the row [last_token, d1 .. dk] at
absolute positions [ctx, ctx+k]; rows concatenate into a single
padding-free token stream with segment ids — the same shape family as
packed chunked prefill (engine/prefill.py plan_packed_prefill), so the
verify program reuses ops/packed_prefill.py's segment-id causal
attention and per-segment paged KV scatter wholesale.  The stream
length buckets pow2 (lo=min_bucket), the segment-row count pow2, and
the table width pow2 up to max_blocks_per_seq, bounding the compiled
shape zoo exactly like prefill packing does.

`temps_t` carries each token's sequence temperature so the verify
program can temperature-scale BEFORE its on-device top-CAP reduction —
the host-side acceptance test (engine/sampler.py spec_accept_tokens)
then sees the exact candidate window the decode sampler would draw
from.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

# one bucket-rounding policy for BOTH packed planners: a divergence here
# would silently fork the verify-plan shape zoo from the prefill one
from ..engine.prefill import _pow2


@dataclass
class SpecPlan:
    """One packed verify dispatch: rows[i] = (slot, drafts) occupies
    packed indices [offsets[i], offsets[i] + len(drafts) + 1)."""

    rows: List[Tuple]             # (engine _Slot, [draft token ids])
    offsets: List[int]            # packed start index per row
    arrays: Dict[str, np.ndarray]
    tokens: int                   # real (non-padding) tokens in the stream
    bucket: int                   # padded stream length


def plan_spec_verify(
    rows: List[Tuple],
    *,
    block_size: int,
    max_blocks_per_seq: int,
    min_bucket: int = 8,
) -> SpecPlan:
    """Build the jit inputs for one spec_verify dispatch.

    rows: [(slot, drafts)] with len(drafts) >= 1 per row; the caller has
    already grown each slot's block table to cover positions
    [ctx, ctx + len(drafts)]."""
    n = len(rows)
    total = sum(len(d) + 1 for _, d in rows)
    bucket = _pow2(total, lo=min_bucket)
    S = _pow2(n)
    mbp = min(
        _pow2(max(-(-(s.ctx_len + len(d) + 1) // block_size)
                  for s, d in rows)),
        max_blocks_per_seq,
    )

    toks = np.zeros(bucket, np.int32)
    positions = np.zeros(bucket, np.int32)
    seg_ids = np.zeros(bucket, np.int32)
    valid = np.zeros(bucket, bool)
    temps_t = np.zeros(bucket, np.float32)
    tables = np.zeros((S, mbp), np.int32)

    offsets: List[int] = []
    off = 0
    for i, (slot, drafts) in enumerate(rows):
        row = [slot.last_token] + list(drafts)
        m = len(row)
        toks[off:off + m] = row
        positions[off:off + m] = slot.ctx_len + np.arange(m, dtype=np.int32)
        seg_ids[off:off + m] = i
        valid[off:off + m] = True
        temps_t[off:off + m] = slot.request.sampling.temperature
        tables[i] = slot.block_table[:mbp]
        offsets.append(off)
        off += m

    return SpecPlan(
        rows=list(rows), offsets=offsets,
        arrays={
            "toks": toks, "positions": positions, "seg_ids": seg_ids,
            "tables": tables, "valid": valid, "temps_t": temps_t,
        },
        tokens=total, bucket=bucket,
    )
