"""Speculative decoding subsystem: proposers + packed verification.

Decode is memory-bandwidth-bound (BENCH_r05: the raw loop at 0.76 of the
HBM roofline), so the only way left to raise tokens/s/chip is to emit
MORE THAN ONE accepted token per weight/KV pass.  Speculative decoding
(Leviathan et al. 2023; Chen et al. 2023) does that: a cheap proposer
drafts k continuation tokens, the target model scores all of them in one
pass, and rejection sampling accepts the longest prefix that preserves
the target distribution exactly (greedy mode = exact argmax-prefix
match, so served output is token-identical to plain decode).

Pieces:

  * ngram.py  — NgramProposer: zero-weight prompt-lookup.  The tail of
    the generated sequence is matched against its own history
    (prompt + output); on a hit the tokens that followed the previous
    occurrence become the draft.  Free to run, surprisingly effective on
    repetitive serving workloads (extraction, code, templated JSON), and
    CPU-only — the tier-1 test proposer.
  * draft.py  — DraftModelProposer: a second, smaller model on the SAME
    mesh, with its own KV cache ADDRESSED BY THE TARGET'S block tables
    (same block_size/num_blocks geometry, separate arrays) — no second
    allocator, no second scheduler.  Greedy k-step drafts via the
    family's fused decode_multi program.
  * verify.py — the packing planner: speculating slots' rows
    [last_token, d1..dk] concatenate into ONE padding-free stream with
    segment ids, verified by the engine's `spec_verify` program
    (models/*.spec_verify_packed over ops/packed_prefill.py segment-id
    causal attention).  Rejection sampling itself lives in
    engine/sampler.py (spec_accept_tokens) next to the distribution it
    must preserve.

The engine side (engine/core.py _spec_step) owns adaptivity — a
per-sequence acceptance-rate EMA shrinks the draft length down to 0
(plain decode) and probes periodically to re-engage — and KV rollback:
blocks grown for rejected draft positions return to the allocator
(block_allocator.trim_blocks), so accounting matches plain decode.
"""

from .draft import DraftModelProposer
from .ngram import NgramProposer
from .verify import SpecPlan, plan_spec_verify


def make_proposer(config, mesh, compile_watch=None):
    """Build the proposer an EngineConfig asks for (engine/core.py).

    `config.spec_decode`: "ngram" (zero-weight prompt lookup) or "draft"
    (second model on the same mesh; resolved from spec_draft_config >
    spec_draft_model_path > spec_draft_model preset, vocab-checked
    against the target)."""
    if config.spec_decode == "ngram":
        return NgramProposer(max_ngram=config.spec_ngram_max,
                             min_ngram=config.spec_ngram_min)
    if config.spec_decode == "draft":
        from ..models import PRESETS, get_family  # noqa: F401

        if config.spec_draft_config is not None:
            draft_cfg = config.spec_draft_config
        elif config.spec_draft_model_path:
            from ..engine.loader_cache import cached_hf_config

            draft_cfg = cached_hf_config(config.spec_draft_model_path)
        elif config.spec_draft_model:
            if config.spec_draft_model not in PRESETS:
                raise ValueError(
                    f"unknown draft preset {config.spec_draft_model!r}; "
                    f"have {sorted(PRESETS)}")
            draft_cfg = PRESETS[config.spec_draft_model]
        else:
            raise ValueError(
                "spec_decode='draft' needs spec_draft_config, "
                "spec_draft_model_path, or spec_draft_model")
        target_cfg = config.resolve_model()
        if draft_cfg.vocab_size != target_cfg.vocab_size:
            raise ValueError(
                f"draft vocab {draft_cfg.vocab_size} != target vocab "
                f"{target_cfg.vocab_size}: draft tokens must be valid "
                "target tokens")
        return DraftModelProposer(
            draft_cfg, mesh,
            num_blocks=config.num_blocks, block_size=config.block_size,
            prefill_buckets=config.prefill_buckets,
            model_path=config.spec_draft_model_path,
            max_k=config.spec_k, seed=config.seed,
            # the draft cache matches the target's quantization policy:
            # its writes (catch-up prefill + propose bursts) are KV write
            # sites like any other, and its HBM footprint halves too
            kv_cache_dtype=config.kv_cache_dtype,
            # the engine threads its compile watchdog through so draft
            # compiles are observed on the same FPM/metric plane
            compile_watch=compile_watch,
        )
    raise ValueError(
        f"spec_decode must be 'off' | 'ngram' | 'draft', "
        f"got {config.spec_decode!r}")


__all__ = [
    "DraftModelProposer",
    "NgramProposer",
    "SpecPlan",
    "make_proposer",
    "plan_spec_verify",
]
