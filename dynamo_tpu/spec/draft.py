"""Draft-model proposer: a second, smaller model on the target's mesh.

The draft holds its own params and its own KV cache arrays, but the
cache is ADDRESSED BY THE TARGET'S BLOCK TABLES: same block_size, same
num_blocks, same garbage block 0.  That makes the whole proposer
allocator-free — wherever the engine's allocator put a sequence's
target KV, the draft KV for the same positions lives at the same block
ids in the draft arrays.  Shared prefix blocks are safe by the same
hash argument as the target cache (one hash = one token run = one KV
content), and a block id recycled to a new sequence is overwritten by
that sequence's catch-up prefill before it is ever read.

Per speculation round for one slot:

  1. catch-up: prefill the draft over tokens[draft_pos:ctx] (bucketed
     B=1 chunks).  draft_pos is engine bookkeeping on the slot — after a
     verify it equals the new ctx, so steady-state catch-up is EMPTY
     (the accepted drafts' KV was already written by step 2, and the
     rejected tail is overwritten by the next round's step 2).
  2. propose: ONE fused decode_multi program runs k greedy draft steps
     from last_token at position ctx, chaining sampled ids on device —
     k tokens for one dispatch, exactly the program shape the target
     engine uses for its own fused decode.

v1 scope: greedy drafts (the proposal is a point mass, which is what
engine/sampler.py spec_accept_tokens assumes), single-host slices only
(draft programs do not ride the multihost step stream; engine/core.py
rejects the combination at init).
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models import get_family


class DraftModelProposer:
    name = "draft"

    def __init__(self, model_cfg, mesh, *, num_blocks: int,
                 block_size: int, prefill_buckets, model_path: str = "",
                 max_k: int = 4, seed: int = 0,
                 kv_cache_dtype: str = "bf16", compile_watch=None):
        from ..parallel.mesh import shard_params

        self.cfg = model_cfg
        self.family = get_family(model_cfg)
        self.mesh = mesh
        self.block_size = block_size
        self.buckets = tuple(prefill_buckets)
        self.max_k = max_k
        # int8 draft cache (quant/kv.py): same fallback rule as the
        # engine — a family without the quantized path stays bf16
        quantized = (kv_cache_dtype == "int8"
                     and hasattr(self.family, "kv_cache_scale_shapes"))
        with mesh:
            if model_path:
                from ..models.loader import load_params

                self.params = load_params(model_path, model_cfg, mesh=mesh)
            else:
                self.params = shard_params(
                    self.family.init_params(model_cfg,
                                            jax.random.PRNGKey(seed)),
                    mesh)
            k_shape, v_shape = self.family.kv_cache_shapes(
                model_cfg, num_blocks, block_size)
            k_spec, v_spec = self.family.kv_cache_specs()
            from jax.sharding import NamedSharding

            dtype = jnp.int8 if quantized else model_cfg.dtype
            kv = [
                # dynlint: disable=DYN001 one-shot sharded-zeros allocation at init, never dispatched while serving
                jax.jit(partial(jnp.zeros, k_shape, dtype),
                        out_shardings=NamedSharding(mesh, k_spec))(),
                # dynlint: disable=DYN001 one-shot sharded-zeros allocation at init, never dispatched while serving
                jax.jit(partial(jnp.zeros, v_shape, dtype),
                        out_shardings=NamedSharding(mesh, v_spec))(),
            ]
            if quantized:
                scale_shapes = self.family.kv_cache_scale_shapes(
                    model_cfg, num_blocks, block_size)
                scale_specs = self.family.kv_cache_scale_specs()
                kv += [
                    # dynlint: disable=DYN001 one-shot sharded-zeros allocation at init, never dispatched while serving
                    jax.jit(partial(jnp.zeros, shape, jnp.float32),
                            out_shardings=NamedSharding(mesh, spec))()
                    for shape, spec in zip(scale_shapes, scale_specs)
                ]
            self.kv = tuple(kv)
        # the draft's prefill/propose programs dispatch during serving
        # exactly like the target's: under the engine's compile watchdog
        # (obs/compile_watch.py) a draft recompile mid-serving is
        # observed too.  A standalone proposer (tests, benches) wraps
        # with a local watch so the call syntax never branches.
        if compile_watch is None:
            from ..obs.compile_watch import CompileWatch

            compile_watch = CompileWatch()
        self._watch = compile_watch
        self._jit_prefill = compile_watch.wrap(jax.jit(
            partial(self._prefill_impl, self.family, self.cfg),
            donate_argnums=(1,)), "draft_prefill", lambda a: a[2].shape[-1])
        self._jit_propose = {}  # k -> jitted k-step greedy draft program

    @staticmethod
    def _prefill_impl(family, cfg, params, kv, toks, positions, table,
                      ctx_len, true_len):
        _, kv = family.prefill(params, cfg, kv, toks, positions, table,
                               ctx_len, true_len)
        return kv

    @staticmethod
    def _propose_impl(family, cfg, mesh, k, params, kv, token, position,
                      table, ctx_len):
        toks, kv = family.decode_multi(
            params, cfg, kv, token[None], position[None], table[None],
            ctx_len[None], k, None, valid=jnp.ones((1,), bool), mesh=mesh,
        )
        return toks[:, 0], kv

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def propose(self, tokens: Sequence[int], k: int, *, ctx: int,
                draft_pos: int, block_table) -> List[int]:
        """k greedy draft tokens continuing tokens[:ctx+1] (last_token is
        tokens[ctx]).  Catch-up prefill covers [draft_pos, ctx); the
        caller advances draft_pos to the new ctx after verification."""
        table = jnp.asarray(block_table)
        pos = draft_pos
        while pos < ctx:
            chunk = min(ctx - pos, self.buckets[-1])
            bucket = self._bucket_for(chunk)
            toks = np.zeros(bucket, np.int32)
            toks[:chunk] = tokens[pos:pos + chunk]
            positions = pos + np.arange(bucket, dtype=np.int32)
            self.kv = self._jit_prefill(
                self.params, self.kv, jnp.asarray(toks),
                jnp.asarray(positions), table, jnp.int32(pos),
                jnp.int32(chunk))
            pos += chunk
        k = min(k, self.max_k)
        jit = self._jit_propose.get(k)
        if jit is None:
            jit = self._jit_propose[k] = self._watch.wrap(jax.jit(
                partial(self._propose_impl, self.family, self.cfg,
                        self.mesh, k),
                donate_argnums=(1,)), "draft_propose",
                lambda a, _k=k: _k)
        burst, self.kv = jit(
            self.params, self.kv, jnp.int32(tokens[ctx]), jnp.int32(ctx),
            table, jnp.int32(ctx))
        return [int(t) for t in np.asarray(burst)]
