"""`python -m dynamo_tpu.profiler` — self-benchmark an engine and write
the perf-profile JSON the SLA planner plans against.

Ref: the reference's profiler component bootstraps the planner perf model
from pre-deployment sweeps (planner-design.md "Capacity Estimation").
Run `--engine jax` on the TPU host to profile real hardware; `--engine
mock` profiles the simulator (CI / planner tests).

    python -m dynamo_tpu.profiler --engine jax --model tiny \
        --out profile.json --isls 128,512 --concurrencies 1,2,4,8
"""

import argparse
import asyncio

from ..runtime.logging import setup_logging


def build_args() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("dynamo_tpu.profiler")
    p.add_argument("--engine", default="mock", choices=["mock", "jax"])
    p.add_argument("--out", default="profile.json")
    p.add_argument("--isls", default="128,512,2048",
                   help="comma-separated prompt lengths")
    p.add_argument("--concurrencies", default="1,2,4,8,16")
    p.add_argument("--osl", type=int, default=32)
    p.add_argument("--rounds", type=int, default=2)
    # jax engine shape (mirrors dynamo_tpu.engine flags)
    p.add_argument("--model", default="tiny")
    p.add_argument("--model-path", default="")
    p.add_argument("--block-size", type=int, default=128)
    p.add_argument("--num-blocks", type=int, default=512)
    p.add_argument("--max-blocks-per-seq", type=int, default=64)
    p.add_argument("--max-num-seqs", type=int, default=16)
    p.add_argument("--tp", type=int, default=1)
    return p


async def main() -> None:
    setup_logging()
    args = build_args().parse_args()
    isls = [int(x) for x in args.isls.split(",") if x]
    concs = [int(x) for x in args.concurrencies.split(",") if x]

    if args.engine == "mock":
        from ..mocker import MockEngine, MockEngineArgs

        engine = MockEngine(MockEngineArgs(speedup_ratio=1.0))
        name = "mock"
    else:
        from ..engine.config import EngineConfig
        from ..engine.core import JaxEngine

        config = EngineConfig(
            model=args.model, model_path=args.model_path,
            block_size=args.block_size, num_blocks=args.num_blocks,
            max_blocks_per_seq=args.max_blocks_per_seq,
            max_num_seqs=args.max_num_seqs, tp=args.tp,
        )
        engine = JaxEngine(config)
        name = args.model_path or args.model

    from . import profile_engine

    try:
        prof = await profile_engine(
            engine, model_name=name, isls=isls, osl=args.osl,
            concurrencies=concs, rounds=args.rounds,
        )
    finally:
        await engine.close()
    prof.save(args.out)
    print(f"wrote {len(prof.points)} grid points to {args.out}", flush=True)
    for pt in prof.points:
        print(f"  isl={pt.isl:5d} c={pt.concurrency:3d} "
              f"ttft_p95={pt.ttft_p95_s * 1e3:8.1f}ms "
              f"itl_p95={pt.itl_p95_s * 1e3:7.2f}ms "
              f"rps={pt.req_per_s:7.2f}", flush=True)


if __name__ == "__main__":
    asyncio.run(main())
