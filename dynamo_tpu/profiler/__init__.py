from .profile import PerfPoint, PerfProfile, profile_engine

__all__ = ["PerfPoint", "PerfProfile", "profile_engine"]
