"""Mini-profiler: closed-loop sweep that measures an engine's latency
surface, producing the perf profile the SLA planner plans against.

Ref: components/src/dynamo/profiler (the reference's ~20k-LoC profiling
stack) and planner-design.md "Capacity Estimation": the planner perf model
is bootstrapped from self-benchmark data — (concurrency, ISL) grid points
with observed TTFT / ITL / throughput, interpolated at plan time.

This is the TPU-native analogue: the sweep drives any object with the
engine `generate(PreprocessedRequest) -> AsyncIterator[LLMEngineOutput]`
contract — the JAX engine on real hardware, the mocker on CPU (its
polynomial timing model makes SLA-planner behavior testable without a
chip).  For each grid point it runs a closed loop of `concurrency`
identical requests and records first-token and inter-token latencies.
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Sequence

from ..protocols import PreprocessedRequest, SamplingOptions, StopConditions
from ..runtime.metrics import percentile


def _pctl(xs: List[float], q: float) -> float:
    return percentile(xs, q * 100.0)


@dataclass
class PerfPoint:
    """One grid point: `concurrency` closed-loop requests of `isl`
    prompt tokens / `osl` output tokens each."""

    isl: int
    osl: int
    concurrency: int
    ttft_p50_s: float = 0.0
    ttft_p95_s: float = 0.0
    itl_mean_s: float = 0.0
    itl_p95_s: float = 0.0
    req_per_s: float = 0.0
    output_tok_per_s: float = 0.0

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "PerfPoint":
        return cls(**d)


@dataclass
class PerfProfile:
    """A sweep's worth of PerfPoints plus identifying metadata.

    Serialized as JSON so a profile taken on TPU hardware can bootstrap a
    planner running anywhere (the reference ships profiles as NPZ/JSON in
    `profile_results_dir`; JSON alone covers our needs)."""

    model_name: str = ""
    points: List[PerfPoint] = field(default_factory=list)
    meta: Dict[str, object] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps({
            "model_name": self.model_name,
            "meta": self.meta,
            "points": [p.to_dict() for p in self.points],
        }, indent=1)

    @classmethod
    def from_json(cls, s: str) -> "PerfProfile":
        d = json.loads(s)
        return cls(model_name=d.get("model_name", ""),
                   meta=d.get("meta", {}),
                   points=[PerfPoint.from_dict(p)
                           for p in d.get("points", [])])

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "PerfProfile":
        with open(path) as f:
            return cls.from_json(f.read())


async def _measure_point(engine, isl: int, osl: int, concurrency: int,
                         *, rounds: int, token_base: int) -> PerfPoint:
    """Closed loop: each of `concurrency` workers issues `rounds`
    sequential requests; latencies are pooled across workers."""
    ttfts: List[float] = []
    itls: List[float] = []
    n_done = 0
    t_start = time.monotonic()

    async def one_worker(w: int) -> None:
        nonlocal n_done
        for r in range(rounds):
            # unique prompts: defeat the prefix cache so prefill cost is
            # real (a profile with 100% cache hits underestimates TTFT)
            base = token_base + (w * rounds + r) * (isl + 1)
            req = PreprocessedRequest(
                token_ids=[3 + (base + i) % 30000 for i in range(isl)],
                request_id=f"prof-{w}-{r}-{base}",
                stop=StopConditions(max_tokens=osl, ignore_eos=True),
                sampling=SamplingOptions(temperature=0.0),
            )
            t0 = time.monotonic()
            t_prev: Optional[float] = None
            async for out in engine.generate(req):
                now = time.monotonic()
                if out.token_ids:
                    if t_prev is None:
                        ttfts.append(now - t0)
                    else:
                        itls.append(now - t_prev)
                    t_prev = now
            n_done += 1

    await asyncio.gather(*(one_worker(w) for w in range(concurrency)))
    elapsed = max(time.monotonic() - t_start, 1e-9)
    return PerfPoint(
        isl=isl, osl=osl, concurrency=concurrency,
        ttft_p50_s=_pctl(ttfts, 0.50), ttft_p95_s=_pctl(ttfts, 0.95),
        itl_mean_s=(sum(itls) / len(itls)) if itls else 0.0,
        itl_p95_s=_pctl(itls, 0.95),
        req_per_s=n_done / elapsed,
        output_tok_per_s=n_done * osl / elapsed,
    )


async def profile_engine(
    engine,
    *,
    model_name: str = "",
    isls: Sequence[int] = (128, 512, 2048),
    osl: int = 32,
    concurrencies: Sequence[int] = (1, 2, 4, 8, 16),
    rounds: int = 2,
    warmup: bool = True,
    kv_cache_dtype: Optional[str] = None,
) -> PerfProfile:
    """Sweep the (isl, concurrency) grid.  `engine` is anything with the
    generate() contract; callers own its lifecycle.

    The profile is tagged with the engine's KV storage dtype (explicit
    `kv_cache_dtype` beats auto-detection off the engine) so the SLA
    planner can refuse to silently apply a bf16-measured ITL surface to
    an int8 fleet (planner/perf_model.py check_kv_dtype)."""
    if kv_cache_dtype is None:
        # JaxEngine exposes the EFFECTIVE dtype; the mocker carries it
        # on its args
        kv_cache_dtype = getattr(engine, "kv_dtype", None) or getattr(
            getattr(engine, "args", None), "kv_cache_dtype", "")
    prof = PerfProfile(model_name=model_name,
                       meta={"osl": osl, "rounds": rounds,
                             "kv_cache_dtype": kv_cache_dtype})
    token_base = 0
    if warmup:
        # first call pays compilation / pool-initialisation; don't let it
        # pollute the smallest grid point
        await _measure_point(engine, int(isls[0]), 4, 1,
                             rounds=1, token_base=token_base)
        token_base += 10_000_000
    for isl in isls:
        for c in concurrencies:
            pt = await _measure_point(engine, int(isl), osl, int(c),
                                      rounds=rounds, token_base=token_base)
            token_base += 10_000_000
            prof.points.append(pt)
    return prof
