"""Graph spec model + Deployment rendering.

Ref: deploy/operator/api/v1beta1/dynamographdeployment_types.go:181 — the
reference CRD's services map (component name -> replicas/image/resources/
envs) rendered by its controller into component Deployments.  Same
information here as a plain JSON document in a ConfigMap, rendered into
the manifest shapes deploy/*.yaml documents by hand.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

GRAPH_LABEL = "dynamo.dev/graph"          # marks spec ConfigMaps
GRAPH_NAME_LABEL = "dynamo.dev/graph-name"
COMPONENT_LABEL = "dynamo.dev/component"
HASH_ANN = "dynamo.dev/spec-hash"
REPLICAS_ANN = "dynamo.dev/spec-replicas"

# component kind -> (module, default args); the worker kinds add
# role/model flags in render
_KIND_MODULE = {
    "frontend": "dynamo_tpu.frontend",
    "worker": "dynamo_tpu.engine",
    "mocker": "dynamo_tpu.mocker",
    "planner": "dynamo_tpu.planner",
    "router": "dynamo_tpu.router",
    "multimodal": "dynamo_tpu.multimodal",
}


@dataclass
class ComponentSpec:
    name: str
    kind: str                      # frontend | worker | mocker | planner...
    replicas: int = 1
    role: str = ""                 # worker kinds: decode | prefill | both
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    tpu: int = 0                   # google.com/tpu resource limit
    port: Optional[int] = None


@dataclass
class GraphSpec:
    name: str
    image: str
    components: Dict[str, ComponentSpec]
    model_name: str = ""
    model_path: str = ""
    cluster_id: str = "default"
    service_account: str = "dynamo-tpu"
    namespace: str = ""

    @classmethod
    def parse(cls, doc: Dict[str, Any]) -> "GraphSpec":
        """Validate + normalize a spec document (the ConfigMap's
        data["spec"] JSON)."""
        if not isinstance(doc, dict):
            raise ValueError("graph spec must be a JSON object")
        name = doc.get("name")
        image = doc.get("image")
        comps = doc.get("components")
        if not name or not isinstance(name, str):
            raise ValueError("graph spec needs a string 'name'")
        if not image or not isinstance(image, str):
            raise ValueError(f"graph {name!r}: spec needs 'image'")
        if not isinstance(comps, dict) or not comps:
            raise ValueError(f"graph {name!r}: spec needs 'components'")
        model = doc.get("model") or {}
        out: Dict[str, ComponentSpec] = {}
        for cname, c in comps.items():
            if not isinstance(c, dict):
                raise ValueError(
                    f"graph {name!r}: component {cname!r} must be an "
                    "object")
            kind = c.get("kind", cname)
            if kind not in _KIND_MODULE:
                raise ValueError(
                    f"graph {name!r}: component {cname!r} has unknown kind "
                    f"{kind!r} (expected one of {sorted(_KIND_MODULE)})")
            out[cname] = ComponentSpec(
                name=cname, kind=kind,
                replicas=int(c.get("replicas", 1)),
                role=c.get("role", ""),
                args=[str(a) for a in c.get("args", [])],
                env={str(k): str(v) for k, v in (c.get("env") or {}).items()},
                tpu=int(c.get("tpu", 0)),
                port=c.get("port"),
            )
        return cls(
            name=name, image=image, components=out,
            model_name=model.get("name", ""),
            model_path=model.get("path", ""),
            cluster_id=doc.get("cluster_id", "default"),
            service_account=doc.get("service_account", "dynamo-tpu"),
            namespace=doc.get("namespace", ""),
        )


def _command(spec: GraphSpec, c: ComponentSpec) -> List[str]:
    cmd = ["python", "-m", _KIND_MODULE[c.kind]]
    if c.kind == "worker":
        if spec.model_path:
            cmd += ["--model-path", spec.model_path]
        if c.role:
            cmd += ["--role", c.role]
    if c.kind == "frontend" and c.port:
        cmd += ["--port", str(c.port)]
    return cmd + c.args


def deployment_name(spec: GraphSpec, cname: str) -> str:
    return f"{spec.name}-{cname}"


def render_deployments(spec: GraphSpec) -> Dict[str, Dict[str, Any]]:
    """spec -> {deployment name: apps/v1 Deployment manifest}.

    The manifest carries HASH_ANN (hash of everything the spec controls
    EXCEPT replicas) and REPLICAS_ANN (the spec's replica count) so the
    reconciler can tell spec drift from planner-driven scaling."""
    out: Dict[str, Dict[str, Any]] = {}
    for cname, c in spec.components.items():
        dname = deployment_name(spec, cname)
        labels = {
            "app": dname,
            GRAPH_NAME_LABEL: spec.name,
            COMPONENT_LABEL: cname,
        }
        env = {
            "DYN_DISCOVERY_BACKEND": "kubernetes",
            "DYN_CLUSTER_ID": spec.cluster_id,
            **({"JAX_PLATFORMS": "cpu"} if c.tpu == 0 else {}),
            **c.env,
        }
        container: Dict[str, Any] = {
            "name": c.kind,
            "image": spec.image,
            "command": _command(spec, c),
            "env": [{"name": k, "value": v} for k, v in sorted(env.items())],
        }
        if c.port:
            container["ports"] = [{"containerPort": int(c.port)}]
        if c.tpu > 0:
            container["resources"] = {
                "limits": {"google.com/tpu": str(c.tpu)}}
        template = {
            "metadata": {"labels": dict(labels)},
            "spec": {
                "serviceAccountName": spec.service_account,
                "containers": [container],
            },
        }
        spec_hash = hashlib.sha256(json.dumps(
            {"template": template, "image": spec.image},
            sort_keys=True).encode()).hexdigest()[:16]
        out[dname] = {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {
                "name": dname,
                "labels": dict(labels),
                "annotations": {
                    HASH_ANN: spec_hash,
                    REPLICAS_ANN: str(c.replicas),
                },
            },
            "spec": {
                "replicas": c.replicas,
                "selector": {"matchLabels": {"app": dname}},
                # surge-style rolling update: new pods come up before old
                # ones drain, so a worker fleet never drops to zero on an
                # image/args change (ref: the operator's rolling updates)
                "strategy": {
                    "type": "RollingUpdate",
                    "rollingUpdate": {"maxUnavailable": 0, "maxSurge": 1},
                },
                "template": template,
            },
        }
    return out
