"""`python -m dynamo_tpu.operator` — run the graph reconcile loop.

Ref: the reference operator's manager entrypoint
(deploy/operator/cmd/main.go); here a single asyncio process suffices.
Credentials resolve exactly like every other component (in-cluster
service account, or DYN_K8S_* for dev).
"""

import argparse
import asyncio
import logging

from .reconciler import GraphOperator


def main() -> None:
    ap = argparse.ArgumentParser(description="dynamo_tpu graph operator")
    ap.add_argument("--api-url", default="", help="K8s API (default: "
                    "in-cluster / DYN_K8S_API)")
    ap.add_argument("--namespace", default="")
    ap.add_argument("--interval", type=float, default=10.0,
                    help="reconcile resync period, seconds")
    ap.add_argument("--once", action="store_true",
                    help="single reconcile pass (CI / dry-run)")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    async def run():
        op = GraphOperator(api_url=args.api_url, namespace=args.namespace,
                           interval_s=args.interval)
        try:
            if args.once:
                await op.reconcile_once()
            else:
                await op.run()
        finally:
            await op.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()
