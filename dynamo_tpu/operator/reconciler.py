"""The reconcile loop: spec ConfigMaps -> converged Deployment set.

Ref: deploy/operator/internal/controller/dynamographdeployment_controller.go
— level-triggered reconciliation: every pass reads the desired state
(spec ConfigMaps), reads the actual state (Deployments labeled with the
graph name), and applies the difference.  Same aiohttp-on-the-JSON-API
discipline as runtime/kube.py and planner/connectors.py (no client
library); tested against tests/fake_kube.py.

Drift rules:
  * missing Deployment           -> create
  * HASH_ANN differs             -> merge-patch template/labels (rolling
                                    update via the Deployment machinery)
  * REPLICAS_ANN differs         -> the SPEC's replica count changed:
                                    patch replicas too (spec wins)
  * REPLICAS_ANN equal           -> leave replicas alone — the planner's
                                    KubernetesConnector owns scale drift
  * stray graph-labeled objects  -> delete (component removed from spec)
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Dict, List, Optional, Tuple

from .spec import (
    GRAPH_LABEL,
    GRAPH_NAME_LABEL,
    HASH_ANN,
    REPLICAS_ANN,
    GraphSpec,
    render_deployments,
)

logger = logging.getLogger(__name__)


class GraphOperator:
    def __init__(self, api_url: str = "", namespace: str = "",
                 token: str = "", interval_s: float = 10.0):
        from ..runtime.kube import resolve_k8s_credentials

        self.api, self.namespace, self.token, self._ssl = \
            resolve_k8s_credentials(api_url, namespace, token)
        self.interval_s = interval_s
        self._session = None
        self._closed = asyncio.Event()
        # reconcile-pass counters (observability + test hooks)
        self.stats = {"created": 0, "patched": 0, "scaled": 0,
                      "deleted": 0, "errors": 0, "passes": 0}

    # -- transport --------------------------------------------------------

    def _http(self):
        import aiohttp

        if self._session is None or self._session.closed:
            headers = {}
            if self.token:
                headers["Authorization"] = f"Bearer {self.token}"
            self._session = aiohttp.ClientSession(
                headers=headers,
                timeout=aiohttp.ClientTimeout(total=30),
                connector=(aiohttp.TCPConnector(ssl=self._ssl)
                           if self._ssl is not None else None))
        return self._session

    def _cm_url(self) -> str:
        return f"{self.api}/api/v1/namespaces/{self.namespace}/configmaps"

    def _dep_url(self, name: str = "") -> str:
        base = (f"{self.api}/apis/apps/v1/namespaces/{self.namespace}"
                "/deployments")
        return f"{base}/{name}" if name else base

    # -- desired state ----------------------------------------------------

    async def load_specs(self) -> Tuple[List[GraphSpec], Optional[set]]:
        """All graph specs: ConfigMaps labeled GRAPH_LABEL=1, spec JSON in
        data["spec"].  A malformed spec is logged and skipped — one bad
        graph must not stall reconciliation of the others.

        Returns (specs, quarantine): quarantine is the set of graph NAMES
        whose spec failed to parse (their live Deployments must NOT be
        reaped as strays — a config typo must never take down a running
        fleet), or None when a spec was so broken its graph name is
        unknowable (the caller then skips stray deletion entirely)."""
        params = {"labelSelector": f"{GRAPH_LABEL}=1"}
        async with self._http().get(self._cm_url(), params=params) as resp:
            resp.raise_for_status()
            out = await resp.json()
        specs: List[GraphSpec] = []
        quarantine: Optional[set] = set()
        for obj in out.get("items", []):
            name = (obj.get("metadata") or {}).get("name", "?")
            doc = None
            try:
                doc = json.loads((obj.get("data") or {}).get("spec", ""))
                specs.append(GraphSpec.parse(doc))
            except Exception:
                # ANY malformed spec (bad JSON, wrong shapes, surprise
                # types) must quarantine that graph, never wedge the
                # reconcile loop for the others
                self.stats["errors"] += 1
                logger.warning("graph ConfigMap %s has invalid spec; "
                               "skipping", name, exc_info=True)
                gname = doc.get("name") if isinstance(doc, dict) else None
                if quarantine is not None and isinstance(gname, str) \
                        and gname:
                    quarantine.add(gname)
                else:
                    quarantine = None  # name unknowable: freeze deletes
        return specs, quarantine

    # -- actual state -----------------------------------------------------

    async def _list_owned(self) -> Dict[str, Dict[str, Any]]:
        """Deployments this operator manages (any graph), by name."""
        params = {"labelSelector": GRAPH_NAME_LABEL}
        async with self._http().get(self._dep_url(), params=params) as resp:
            resp.raise_for_status()
            out = await resp.json()
        return {(o.get("metadata") or {}).get("name"): o
                for o in out.get("items", [])}

    # -- reconcile --------------------------------------------------------

    @staticmethod
    def _drift(existing: Dict[str, Any],
               desired: Dict[str, Any]) -> Tuple[bool, Optional[int]]:
        """(template drifted?, replicas to set or None)."""
        e_ann = (existing.get("metadata") or {}).get("annotations") or {}
        d_ann = desired["metadata"]["annotations"]
        drifted = e_ann.get(HASH_ANN) != d_ann[HASH_ANN]
        replicas = None
        if e_ann.get(REPLICAS_ANN) != d_ann[REPLICAS_ANN]:
            replicas = int(desired["spec"]["replicas"])
        return drifted, replicas

    async def reconcile_once(self) -> None:
        specs, quarantine = await self.load_specs()
        desired: Dict[str, Dict[str, Any]] = {}
        for spec in specs:
            desired.update(render_deployments(spec))
        existing = await self._list_owned()

        for name, manifest in desired.items():
            try:
                if name not in existing:
                    async with self._http().post(
                            self._dep_url(), json=manifest) as resp:
                        if resp.status == 409:
                            # raced another operator replica; next pass
                            # converges via the patch path
                            continue
                        resp.raise_for_status()
                    self.stats["created"] += 1
                    logger.info("operator created %s", name)
                    continue
                drifted, replicas = self._drift(existing[name], manifest)
                if not drifted and replicas is None:
                    continue
                patch: Dict[str, Any] = {
                    "metadata": {
                        "labels": manifest["metadata"]["labels"],
                        "annotations": manifest["metadata"]["annotations"],
                    },
                    "spec": {},
                }
                if drifted:
                    patch["spec"]["template"] = \
                        manifest["spec"]["template"]
                    patch["spec"]["strategy"] = \
                        manifest["spec"]["strategy"]
                if replicas is not None:
                    patch["spec"]["replicas"] = replicas
                    self.stats["scaled"] += 1
                async with self._http().patch(
                    self._dep_url(name), json=patch,
                    headers={"Content-Type":
                             "application/merge-patch+json"},
                ) as resp:
                    resp.raise_for_status()
                self.stats["patched"] += 1
                logger.info("operator patched %s (template=%s replicas=%s)",
                            name, drifted, replicas)
            except asyncio.CancelledError:
                raise
            except Exception:
                self.stats["errors"] += 1
                logger.warning("reconcile of %s failed", name,
                               exc_info=True)

        for name in set(existing) - set(desired):
            if quarantine is None:
                break  # an unparseable spec froze stray deletion
            owner = ((existing[name].get("metadata") or {})
                     .get("labels") or {}).get(GRAPH_NAME_LABEL)
            if owner in quarantine:
                continue  # its spec is broken, not gone: keep it running
            try:
                async with self._http().delete(
                        self._dep_url(name)) as resp:
                    if resp.status != 404:
                        resp.raise_for_status()
                self.stats["deleted"] += 1
                logger.info("operator deleted stray %s", name)
            except asyncio.CancelledError:
                raise
            except Exception:
                self.stats["errors"] += 1
                logger.warning("delete of %s failed", name, exc_info=True)
        self.stats["passes"] += 1

    async def run(self) -> None:
        """Level-triggered loop: reconcile, sleep, repeat.  Every pass
        re-reads both sides, so missed watch events cannot wedge it (the
        reference controller's resync period plays the same role)."""
        while not self._closed.is_set():
            try:
                await self.reconcile_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                self.stats["errors"] += 1
                logger.warning("reconcile pass failed", exc_info=True)
            try:
                await asyncio.wait_for(self._closed.wait(),
                                       timeout=self.interval_s)
            except asyncio.TimeoutError:
                pass

    async def close(self) -> None:
        self._closed.set()
        if self._session is not None and not self._session.closed:
            await self._session.close()
