"""Deployment operator: declarative graph spec -> reconciled Deployments.

Ref: deploy/operator/internal/controller/dynamographdeployment_controller.go
and api/v1beta1/dynamographdeployment_types.go:181 — the reference ships a
Go kubebuilder operator whose DynamoGraphDeployment CRD describes a whole
serving graph (frontend + workers + planner) and whose controller
reconciles it into component Deployments with rolling updates.

This is the CRD-free redesign: the graph spec lives in a ConfigMap
(`dynamo.dev/graph: "1"`-labeled), so any cluster works without CRD
install rights, and a Python reconcile loop (`python -m
dynamo_tpu.operator`) renders the spec into plain apps/v1 Deployments —
the same objects deploy/*.yaml hand-write — and keeps them converged:
create on add, merge-patch on drift (image/replicas/args/env roll pods
via the Deployment's own rolling-update machinery), delete on removal.
Scale-subresource writes from the planner's KubernetesConnector are
preserved on spec-unrelated reconciles (replicas drift is only corrected
when the spec's own replica count changed).
"""

from .spec import GraphSpec, render_deployments
from .reconciler import GraphOperator

__all__ = ["GraphSpec", "render_deployments", "GraphOperator"]
