"""Packed multi-sequence prefill over the paged KV cache.

The padding killer for the prefill phase (round-5 verdict: prefill MFU
0.098 while decode sits at 0.76 of its roofline).  The batched prefill
path pads EVERY co-scheduled row to the largest chunk's bucket, so a
(100, 500, 37, 1800)-token admission wave computes 4x2048 padded tokens
for 2437 real ones — and the B=1 path serializes one jit dispatch per
sequence per bucket on top.  Here multiple prompts (and prompt TAILS
after prefix-cache hits) concatenate into ONE padding-free token stream
with segment ids:

    tokens    [T]      packed stream (chunks back to back, tail padded)
    seg_ids   [T]      which segment row each token belongs to
    positions [T]      each token's ABSOLUTE position in its sequence
    tables    [S, mb]  per-segment block tables (mb sliced+bucketed to
                       the blocks this dispatch actually touches)
    valid     [T]      False for the padded tail (writes -> garbage)

KV writes scatter each token into its own segment's paged block first;
attention then reads everything — cached prefix AND this chunk — back
through the block table, masked causal-within-segment by absolute
position (token t sees its segment's cache positions [0, positions[t]]).
Because the chunk's K/V are in the cache before attention runs, chunk
boundaries need no special casing: later chunks of the same prompt (even
co-packed in one dispatch at consecutive positions) attend to earlier
ones exactly like a prefix-cache hit.

The attention is flash-style: an online-softmax (running max / sum)
lax.scan over block-column chunks of the gathered context, so the score
matrix never materializes beyond [T, nh, chunk].  One pass runs per
segment row (S is small — max_prefill_seqs); each pass computes scores
for the whole packed stream and masks foreign tokens out, an S-fold
attention-FLOP overhead traded for zero padding on the projection/MLP
FLOPs that dominate prefill at serving context lengths.  `impl` selects
the implementation: "xla"/"auto" is this reference path;
"pallas"/"pallas_interpret" is the hand-tiled kernel
(ops/pallas_packed_prefill.py) whose per-token-block segment-aware
iteration SKIPS (token-block, context-chunk) tiles that belong to
other segments instead of computing-then-masking — no S-fold overhead,
and the context streams HBM->VMEM by physical block id instead of
through an XLA gather.  Both accept int8 caches (the kernel
dequantizes in VMEM, the reference on the gather).

Shape/layout conventions match ops/paged_attention.py: cache
[L, nkv, nb, hd, bs] head-major transposed blocks, physical block 0 is
garbage, all shapes static.

Second consumer: speculative decoding's multi-token verification
(spec/, models/*.spec_verify_packed) runs each speculating sequence's
[last_token, d1..dk] row through this exact path — the draft positions'
KV scatters in place and every row scores against its own paged context
causally, which is precisely the k-token verify step.  Rows there are
short (k+1 tokens), so the S-fold attention overhead is negligible
against the weight pass the verify amortizes.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .paged_attention import (
    NEG_INF,
    _gather_ctx,
    _gqa_out,
    _gqa_scores,
    _store_kv,
)

# the packed-prefill dispatch's impl vocabulary — the single source of
# truth the engine's --packed-attn-impl validation and CLI choices
# reference (a new impl added here is accepted end-to-end)
PACKED_IMPLS = ("auto", "xla", "pallas", "pallas_interpret")


def write_packed_kv(
    k_cache: jax.Array,       # [L, nkv, nblocks, hd, bs]
    v_cache: jax.Array,
    layer: int,
    k: jax.Array,             # [T, nkv, hd] packed-stream keys
    v: jax.Array,
    block_tables: jax.Array,  # [S, mb] int32
    seg_ids: jax.Array,       # [T] int32 segment row per token
    positions: jax.Array,     # [T] int32 absolute position per token
    valid: jax.Array,         # [T] bool (False = padded tail)
    k_scale: jax.Array = None,  # [L, nkv, nblocks, bs] fp32 (int8 cache)
    v_scale: jax.Array = None,
) -> Tuple[jax.Array, ...]:
    """Scatter a packed chunk's K/V into each token's own sequence blocks
    (one flat scatter; sequences own disjoint blocks, padding tokens land
    in the garbage block).  With scales, tokens quantize per (token,
    head) on the way in (paged_attention._store_kv)."""
    bs = k_cache.shape[4]
    blocks = block_tables[seg_ids, positions // bs]  # [T]
    offsets = positions % bs
    blocks = jnp.where(valid, blocks, 0)
    return _store_kv(k_cache, v_cache, layer, k, v, blocks, offsets,
                     k_scale, v_scale)


def _segment_flash(q, k_cache, v_cache, layer, table, token_mask,
                   positions, chunk_cols, k_scale=None, v_scale=None):
    """One segment row's flash pass: online-softmax scan over chunks of
    `chunk_cols` block columns of the segment's paged context.  Returns
    fp32 attention output [T, nh, hd] for every packed token (foreign
    tokens produce junk the caller masks out)."""
    T, nh, hd = q.shape
    bs = k_cache.shape[4]
    mb = table.shape[0]
    n_chunks = -(-mb // chunk_cols)
    pad = n_chunks * chunk_cols - mb
    if pad:
        table = jnp.pad(table, (0, pad))  # padded columns hit garbage
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))

    def body(carry, jc):
        m, l, acc = carry
        cols = jax.lax.dynamic_slice(table, (jc * chunk_cols,),
                                     (chunk_cols,))
        k_c = _gather_ctx(k_cache, layer, cols, k_scale)  # [nkv, C, hd]
        v_c = _gather_ctx(v_cache, layer, cols, v_scale)
        C = chunk_cols * bs
        s = _gqa_scores(q, k_c) * scale          # [T, nh, C] fp32
        span = jc * C + jnp.arange(C)
        mask = token_mask[:, None, None] \
            & (span[None, None, :] <= positions[:, None, None])
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + p.sum(-1)
        acc = acc * alpha[..., None] + _gqa_out(p, v_c)
        return (m_new, l_new, acc), None

    init = (
        jnp.full((T, nh), NEG_INF, jnp.float32),
        jnp.zeros((T, nh), jnp.float32),
        jnp.zeros((T, nh, hd), jnp.float32),
    )
    (m, l, acc), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    return acc / jnp.maximum(l, 1e-20)[..., None]


def _packed_pallas_tp(q, k_cache, v_cache, layer, block_tables, seg_ids,
                      positions, valid, *, mesh, interpret, chunk_cols,
                      k_scale=None, v_scale=None):
    """Packed-prefill kernel under tensor parallelism
    (paged_attention.kernel_tp_call — the shard_map scaffolding shared
    with the decode kernel: local kv-head slices, replicated stream
    metadata, scale planes sharded with the cache)."""
    from jax.sharding import PartitionSpec as P

    from .paged_attention import kernel_tp_call
    from .pallas_packed_prefill import packed_prefill_attention_pallas

    quantized = k_scale is not None

    def local(q, kc, vc, tables, seg, pos, val, *scales):
        ks, vs = scales if quantized else (None, None)
        return packed_prefill_attention_pallas(
            q, kc, vc, layer, tables, seg, pos, val,
            chunk_cols=chunk_cols, interpret=interpret,
            k_scale=ks, v_scale=vs,
        )

    return kernel_tp_call(
        mesh, local,
        [q, k_cache, v_cache, block_tables, seg_ids, positions, valid],
        [P(None, "tp", None), P(None, "tp", None, None, None),
         P(None, "tp", None, None, None), P(None, None), P(None),
         P(None), P(None)],
        k_scale=k_scale, v_scale=v_scale,
    )


def packed_prefill_attention(
    q: jax.Array,             # [T, nh, hd] packed-stream queries (rope'd)
    k_cache: jax.Array,
    v_cache: jax.Array,
    layer: int,
    block_tables: jax.Array,  # [S, mb]
    seg_ids: jax.Array,       # [T]
    positions: jax.Array,     # [T]
    valid: jax.Array,         # [T]
    impl: str = "auto",
    chunk_cols: int = 8,      # block columns per flash step
    k_scale: jax.Array = None,  # int8 cache: dequant scales (quant/kv.py)
    v_scale: jax.Array = None,
    mesh=None,                # required for the Pallas path under tp>1
) -> jax.Array:
    """Causal-within-segment attention for a packed prefill chunk.

    Every token attends to its OWN segment's paged cache over absolute
    positions [0, positions[t]] — cached prefix plus the chunk itself,
    whose K/V write_packed_kv already scattered in (so on an int8 cache
    the chunk's own K/V round-trip the quantizer before attention reads
    them — bit-consistent with how every later chunk will see them).

    impl: "auto"/"xla" (this XLA reference — one masked flash pass per
    segment row, S-fold attention FLOPs); "pallas"/"pallas_interpret"
    (ops/pallas_packed_prefill.py — per-token-block tile-skip
    iteration, ~1x attention FLOPs, context DMA'd HBM->VMEM by
    physical block id).  Int8 caches work on every impl.  `mesh` is
    required for the Pallas path when the cache is tensor-parallel
    (kv_heads over a "tp" axis): the kernel then runs under shard_map
    per shard, like the decode kernel.
    """
    if impl in ("pallas", "pallas_interpret"):
        interpret = impl == "pallas_interpret"
        tp = int(mesh.shape.get("tp", 1)) if mesh is not None else 1
        if tp > 1:
            return _packed_pallas_tp(
                q, k_cache, v_cache, layer, block_tables, seg_ids,
                positions, valid, mesh=mesh, interpret=interpret,
                chunk_cols=chunk_cols, k_scale=k_scale, v_scale=v_scale,
            )
        from .pallas_packed_prefill import packed_prefill_attention_pallas

        return packed_prefill_attention_pallas(
            q, k_cache, v_cache, layer, block_tables, seg_ids,
            positions, valid, chunk_cols=chunk_cols, interpret=interpret,
            k_scale=k_scale, v_scale=v_scale,
        )
    if impl not in ("auto", "xla"):
        raise ValueError(
            f"unknown packed-prefill impl {impl!r}; expected "
            + " | ".join(PACKED_IMPLS)
        )
    S = block_tables.shape[0]
    out = jnp.zeros(q.shape, jnp.float32)
    for s in range(S):  # static unroll: S = co-scheduled segment rows
        seg_mask = (seg_ids == s) & valid
        o_s = _segment_flash(q, k_cache, v_cache, layer, block_tables[s],
                             seg_mask, positions, chunk_cols,
                             k_scale, v_scale)
        out = jnp.where(seg_mask[:, None, None], o_s, out)
    return out.astype(q.dtype)
