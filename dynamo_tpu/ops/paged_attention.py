"""Paged KV-cache attention ops.

The TPU replacement for the reference's only first-party GPU kernels
(lib/kvbm-kernels/cuda/tensor_kernels.cu — block gather/scatter) plus the
paged attention the reference delegates to vLLM/TRT-LLM.

Cache layout (per tensor): [n_layers, n_kv_heads, num_blocks, head_dim,
block_size] — HEAD-MAJOR with TRANSPOSED blocks.  Head-major: one
(head, block) slab is contiguous, so the Pallas decode kernel DMAs blocks
by physical id as whole planes, and the tp sharding over kv_heads
(parallel/mesh.py:kv_cache_spec) splits the cache into contiguous
per-shard slabs.  Transposed ([hd, bs] instead of [bs, hd]): block_size is
the TPU lane dimension, so with block_size a multiple of 128 the DMA slabs
are lane-aligned for ANY head_dim (64-dim models included) and the
kernel's two matmuls hit the MXU without in-kernel transposes.

Conventions:
  * physical block 0 is the GARBAGE block: inactive slots' writes land there
    and are never read; allocators hand out ids >= 1.
  * all shapes are static; sequence validity is carried by ctx_len/true_len
    scalars and enforced with masks, so XLA compiles one program per bucket.

These are the jnp reference implementations — numerically exact, fully
fused-able by XLA.  ops/pallas_paged_attention.py is the hand-tiled
Pallas decode kernel; the two are interchangeable and cross-checked in
tests/test_paged_attention.py.  `paged_attention_decode` dispatches
between them: "auto" selects the jnp/XLA gather path (measured FASTER
than the Pallas kernel on this platform — see the impl="auto" rationale
in paged_attention_decode; the kernel stays available via
impl="pallas"), and "jnp_bf16" keeps matmul operands in the cache dtype
with fp32 accumulation (the serving fast path; "jnp" upcasts to fp32
for exact test numerics).

Int8 KV quantization (quant/kv.py, engine `kv_cache_dtype="int8"`):
every write function takes optional `k_scale`/`v_scale` sibling arrays
[L, nkv, num_blocks, block_size] fp32 — when passed, the incoming K/V
quantize per (token, head) on the way into the cache and the scale
scatters with the same index math, and the function returns a 4-tuple.
EVERY read impl supports int8:

  * "jnp" / "jnp_bf16" / "auto" — the int8 block gather is what
    streams from HBM; dequantization happens on the gathered context
    (`_gather_ctx`), upcast to fp32 ("jnp") or bf16 ("jnp_bf16", keeping
    the MXU operands 16-bit with fp32 accumulation).
  * "pallas" / "pallas_interpret" — in-kernel dequant: the kernel DMAs
    int8 blocks plus their [nkv, bs] fp32 scale rows into VMEM and
    fuses the scale multiply into the chunk consume (query-dtype MXU
    operands, fp32 softmax/accumulate) — int8's halved HBM traffic
    happens inside the fast path (pallas_paged_attention.py docstring
    has the VMEM layout).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..quant.kv import quantize_tokens

NEG_INF = -1e30

# the decode dispatch's impl vocabulary — the single source of truth the
# engine's --attn-impl validation and CLI choices reference (a new impl
# added here is automatically accepted end-to-end)
DECODE_IMPLS = ("auto", "pallas", "pallas_interpret", "jnp", "jnp_bf16")


# ---------------------------------------------------------------------------
# cache writes (block scatter)
# ---------------------------------------------------------------------------


def _store_kv(k_cache, v_cache, layer, k, v, blocks, offsets,
              k_scale, v_scale):
    """Shared scatter tail for every write site: data at
    [layer, :, blocks, :, offsets] (advanced dims front — the target
    reads [T, nkv, hd], exactly the token-major layout k/v arrive in),
    and for an int8 cache the per-(token, head) fp32 scales at
    [layer, :, blocks, offsets] (target [T, nkv]) with the SAME
    blocks/offsets, so data and scale can never disagree on placement.
    Returns the cache tuple in the caller's arity."""
    if k_scale is not None:
        k, ks = quantize_tokens(k)
        v, vs = quantize_tokens(v)
        k_scale = k_scale.at[layer, :, blocks, offsets].set(ks, mode="drop")
        v_scale = v_scale.at[layer, :, blocks, offsets].set(vs, mode="drop")
    k_cache = k_cache.at[layer, :, blocks, :, offsets].set(
        k.astype(k_cache.dtype), mode="drop"
    )
    v_cache = v_cache.at[layer, :, blocks, :, offsets].set(
        v.astype(v_cache.dtype), mode="drop"
    )
    if k_scale is not None:
        return k_cache, v_cache, k_scale, v_scale
    return k_cache, v_cache


def write_prompt_kv(
    k_cache: jax.Array,  # [L, nkv, nblocks, hd, bs]
    v_cache: jax.Array,
    layer: int,
    k: jax.Array,        # [T, nkv, hd] new tokens' keys
    v: jax.Array,
    block_table: jax.Array,  # [max_blocks] int32
    ctx_len: jax.Array,      # scalar: tokens already in cache
    true_len: jax.Array,     # scalar: valid entries of k/v
    k_scale: jax.Array = None,  # [L, nkv, nblocks, bs] fp32 (int8 cache)
    v_scale: jax.Array = None,
) -> Tuple[jax.Array, ...]:
    T = k.shape[0]
    bs = k_cache.shape[4]
    pos = ctx_len + jnp.arange(T, dtype=jnp.int32)  # absolute positions
    blocks = block_table[pos // bs]                 # [T]
    offsets = pos % bs
    valid = jnp.arange(T) < true_len
    # invalid rows scatter to the garbage block
    blocks = jnp.where(valid, blocks, 0)
    return _store_kv(k_cache, v_cache, layer, k, v, blocks, offsets,
                     k_scale, v_scale)


def write_prompt_kv_batched(
    k_cache: jax.Array,       # [L, nkv, nblocks, hd, bs]
    v_cache: jax.Array,
    layer: int,
    k: jax.Array,             # [Bp, T, nkv, hd] chunk keys per sequence
    v: jax.Array,
    block_tables: jax.Array,  # [Bp, max_blocks] int32
    ctx_lens: jax.Array,      # [Bp] tokens already in cache per sequence
    true_lens: jax.Array,     # [Bp] valid entries of each row of k/v
    k_scale: jax.Array = None,  # [L, nkv, nblocks, bs] fp32 (int8 cache)
    v_scale: jax.Array = None,
) -> Tuple[jax.Array, ...]:
    """Multi-sequence chunk scatter: Bp sequences' prefill chunks written in
    one flat scatter (sequences own disjoint blocks, so rows never collide;
    invalid/padding rows land in the garbage block)."""
    Bp, T = k.shape[:2]
    bs = k_cache.shape[4]
    pos = ctx_lens[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    blocks = jnp.take_along_axis(block_tables, pos // bs, axis=1)  # [Bp, T]
    offsets = pos % bs
    valid = jnp.arange(T)[None, :] < true_lens[:, None]
    blocks = jnp.where(valid, blocks, 0)
    bf = blocks.reshape(-1)
    of = offsets.reshape(-1)
    kf = k.reshape(Bp * T, *k.shape[2:])
    vf = v.reshape(Bp * T, *v.shape[2:])
    return _store_kv(k_cache, v_cache, layer, kf, vf, bf, of,
                     k_scale, v_scale)


def write_token_kv(
    k_cache: jax.Array,
    v_cache: jax.Array,
    layer: int,
    k: jax.Array,            # [B, nkv, hd]
    v: jax.Array,
    block_tables: jax.Array,  # [B, max_blocks]
    ctx_lens: jax.Array,      # [B] position to write (== current length)
    k_scale: jax.Array = None,  # [L, nkv, nblocks, bs] fp32 (int8 cache)
    v_scale: jax.Array = None,
) -> Tuple[jax.Array, ...]:
    bs = k_cache.shape[4]
    B = k.shape[0]
    blocks = block_tables[jnp.arange(B), ctx_lens // bs]  # [B]
    offsets = ctx_lens % bs
    return _store_kv(k_cache, v_cache, layer, k, v, blocks, offsets,
                     k_scale, v_scale)


# ---------------------------------------------------------------------------
# attention reads
# ---------------------------------------------------------------------------


def _gather_ctx(cache: jax.Array, layer: int, block_table: jax.Array,
                scale: jax.Array = None, dtype=None) -> jax.Array:
    """[L,nkv,nb,hd,bs] + [max_blocks] -> [nkv, max_blocks*bs, hd].

    `scale` [L, nkv, nb, bs] dequantizes an int8 cache on the gathered
    context (quant/kv.py): the int8 gather is what streams from HBM;
    the upcast target is `dtype` (bf16 for the jnp_bf16 fast path) or
    fp32 when unset."""
    g = cache[layer][:, block_table]  # [nkv, max_blocks, hd, bs]
    nkv, mb, hd, bs = g.shape
    g = g.swapaxes(2, 3).reshape(nkv, mb * bs, hd)
    if scale is not None:
        s = scale[layer][:, block_table].reshape(nkv, mb * bs)
        g = g.astype(jnp.float32) * s[..., None]
        if dtype is not None:
            g = g.astype(dtype)
    return g


def _gqa_scores(q: jax.Array, k: jax.Array,
                native_dtype: bool = False) -> jax.Array:
    """q [.., nh, hd] x k [nkv, S, hd] -> scores [.., nh, S] with GQA.

    native_dtype=True feeds the MXU the storage dtype (bf16) with fp32
    accumulation instead of upcasting operands — the decode fast path."""
    nh = q.shape[-2]
    nkv = k.shape[0]
    group = nh // nkv
    qg = q.reshape(*q.shape[:-2], nkv, group, q.shape[-1])
    if native_dtype:
        return jnp.einsum(
            "...kgh,ksh->...kgs", qg, k,
            preferred_element_type=jnp.float32,
        ).reshape(*q.shape[:-2], nh, k.shape[1])
    s = jnp.einsum("...kgh,ksh->...kgs", qg.astype(jnp.float32),
                   k.astype(jnp.float32))
    return s.reshape(*q.shape[:-2], nh, k.shape[1])


def _gqa_out(p: jax.Array, v: jax.Array,
             native_dtype: bool = False) -> jax.Array:
    """p [.., nh, S] x v [nkv, S, hd] -> out [.., nh, hd]."""
    nh = p.shape[-2]
    nkv = v.shape[0]
    group = nh // nkv
    pg = p.reshape(*p.shape[:-2], nkv, group, p.shape[-1])
    if native_dtype:
        o = jnp.einsum("...kgs,ksh->...kgh", pg.astype(v.dtype), v,
                       preferred_element_type=jnp.float32)
    else:
        o = jnp.einsum("...kgs,ksh->...kgh", pg, v.astype(jnp.float32))
    return o.reshape(*p.shape[:-2], nh, v.shape[-1])


def paged_prefill_attention(
    q: jax.Array,        # [T, nh, hd] (rope applied)
    k: jax.Array,        # [T, nkv, hd] this chunk's keys
    v: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    layer: int,
    block_table: jax.Array,
    ctx_len: jax.Array,   # cached tokens this chunk attends to
    true_len: jax.Array,  # valid tokens in the chunk
    k_scale: jax.Array = None,  # int8 cache: dequant scales (quant/kv.py)
    v_scale: jax.Array = None,
) -> jax.Array:
    """Chunk tokens attend to (cached context) ++ (chunk, causally).

    One code path serves plain prefill (ctx_len=0), prefix-cache hits and
    chunked prefill (ctx_len>0) — the unified form that lets the engine reuse
    blocks the router already counted as overlap.  The chunk's own K/V
    attend at full precision (they arrive fresh from the projection);
    only the cached context dequantizes on an int8 cache.
    """
    T, nh, hd = q.shape
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))

    k_ctx = _gather_ctx(k_cache, layer, block_table, k_scale)  # [nkv,S,hd]
    v_ctx = _gather_ctx(v_cache, layer, block_table, v_scale)
    S = k_ctx.shape[1]
    k_hm = k.swapaxes(0, 1)  # head-major [nkv, T, hd]
    v_hm = v.swapaxes(0, 1)

    s_ctx = _gqa_scores(q, k_ctx) * scale            # [T, nh, S]
    ctx_mask = (jnp.arange(S) < ctx_len)[None, None, :]
    s_ctx = jnp.where(ctx_mask, s_ctx, NEG_INF)

    s_self = _gqa_scores(q, k_hm) * scale            # [T, nh, T]
    i = jnp.arange(T)[:, None, None]
    j = jnp.arange(T)[None, None, :]
    causal = (j <= i) & (j < true_len)
    s_self = jnp.where(causal, s_self, NEG_INF)

    s = jnp.concatenate([s_ctx, s_self], axis=-1)    # [T, nh, S+T]
    p = jax.nn.softmax(s, axis=-1)
    out = _gqa_out(p[..., :S], v_ctx) + _gqa_out(p[..., S:], v_hm)
    return out.astype(q.dtype)


def paged_attention_decode_jnp(
    q: jax.Array,            # [B, nh, hd]
    k_cache: jax.Array,
    v_cache: jax.Array,
    layer: int,
    block_tables: jax.Array,  # [B, max_blocks]
    kv_lens: jax.Array,       # [B] valid tokens (incl. the one just written)
    native_dtype: bool = False,
    k_scale: jax.Array = None,  # int8 cache: dequant scales (quant/kv.py)
    v_scale: jax.Array = None,
) -> jax.Array:
    """XLA path: the block gather feeds the einsums directly (fused by
    XLA — no explicit DMA kernel).  native_dtype=True keeps matmul
    operands in the cache dtype (bf16) with fp32 accumulation; False
    upcasts to fp32 (exact reference numerics for tests).  An int8 cache
    dequantizes on the gather — to bf16 under native_dtype (operands
    stay 16-bit for the MXU), else to fp32."""
    B, nh, hd = q.shape
    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    deq_dtype = jnp.bfloat16 if native_dtype else None

    def one(qb, table, kvlen):
        kb = _gather_ctx(k_cache, layer, table, k_scale, deq_dtype)
        vb = _gather_ctx(v_cache, layer, table, v_scale, deq_dtype)
        s = _gqa_scores(qb, kb, native_dtype) * scale   # [nh, S]
        mask = (jnp.arange(kb.shape[1]) < kvlen)[None, :]
        s = jnp.where(mask, s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        return _gqa_out(p, vb, native_dtype)     # [nh, hd]

    out = jax.vmap(one)(q, block_tables, kv_lens)
    return out.astype(q.dtype)


def kernel_tp_call(mesh, local, args, specs, k_scale=None, v_scale=None):
    """shard_map scaffolding shared by the Pallas decode and
    packed-prefill kernels under tensor parallelism.

    The kernels are custom calls GSPMD cannot partition (left alone,
    XLA all-gathers the whole kv_heads-sharded cache per layer per
    step — the exact fallback this replaces).  Under shard_map each tp
    shard runs `local` on its LOCAL kv-head slice; GQA head grouping
    is kv-major and contiguous, so a kv head's entire query group
    lives on the same shard and the op needs zero cross-shard
    communication — the row-parallel wo matmul downstream performs the
    usual psum.  An int8 cache's scale planes shard with the cache
    (kv_heads over tp, parallel/mesh.py kv_scale_spec) so each shard
    dequantizes its own slab in-kernel; when scales are passed they
    are appended to `args` and `local` receives them as its trailing
    *scales.  Everything left unmentioned in a spec is replicated
    (tables/lengths/stream metadata — the engine's host-array
    inputs)."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.compat import shard_map

    args = list(args)
    specs = list(specs)
    if k_scale is not None:
        args += [k_scale, v_scale]
        specs += [P(None, "tp", None, None), P(None, "tp", None, None)]
    return shard_map(
        local, mesh=mesh,
        in_specs=tuple(specs),
        out_specs=P(None, "tp", None),
        # pallas_call's out_shape carries no varying-mesh-axes annotation,
        # so the vma checker cannot see through it
        check_vma=False,
    )(*args)


def _decode_pallas_tp(q, k_cache, v_cache, layer, block_tables, kv_lens,
                      *, mesh, interpret, k_scale=None, v_scale=None):
    """Pallas decode under tensor parallelism (kernel_tp_call)."""
    from jax.sharding import PartitionSpec as P

    from .pallas_paged_attention import paged_attention_decode_pallas

    quantized = k_scale is not None

    def local(q, kc, vc, tables, lens, *scales):
        ks, vs = scales if quantized else (None, None)
        return paged_attention_decode_pallas(
            q, kc, vc, layer, tables, lens, interpret=interpret,
            k_scale=ks, v_scale=vs,
        )

    return kernel_tp_call(
        mesh, local,
        [q, k_cache, v_cache, block_tables, kv_lens],
        [P(None, "tp", None), P(None, "tp", None, None, None),
         P(None, "tp", None, None, None), P(None, None), P(None)],
        k_scale=k_scale, v_scale=v_scale,
    )


def paged_attention_decode(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    layer: int,
    block_tables: jax.Array,
    kv_lens: jax.Array,
    impl: str = "auto",
    mesh=None,
    k_scale: jax.Array = None,
    v_scale: jax.Array = None,
) -> jax.Array:
    """Single-token batched paged attention (the decode hot loop).

    impl: "auto" (the jnp/XLA gather path — measured faster than the
    Pallas kernel on this platform, see below), "pallas",
    "pallas_interpret" (kernel under the interpreter — CPU testing),
    "jnp" (fp32-upcast operands: exact reference numerics for tests), or
    "jnp_bf16" (operands stay in the cache dtype, fp32 accumulation —
    the bandwidth-friendly serving variant of the jnp path).

    mesh: required for the Pallas path when the kv cache is tensor-parallel
    (kv_heads sharded over a "tp" axis) — the kernel then runs under
    shard_map per shard.  Without a mesh, "auto" under tp>1 would hit
    GSPMD's unpartitionable-custom-call all-gather, so callers serving
    multi-chip must pass their mesh (the engine does).

    k_scale/v_scale: an int8 cache's dequant scales (quant/kv.py).
    Every impl consumes them natively — the jnp paths dequantize on
    the gather, the Pallas kernel DMAs int8 blocks + scale rows and
    fuses the multiply in VMEM (module docstring's support matrix).
    """
    tp = int(mesh.shape.get("tp", 1)) if mesh is not None else 1
    if impl == "auto":
        # "auto" = the XLA gather path, bf16 AND int8.  Measured on v5e
        # (round 5, benchmarks/bench_decode_phases.py, llama-3b B=8
        # ctx=2048): the full decode step runs 14.2 ms with this path vs
        # 17.1 ms with the Pallas kernel — the kernel's explicit DMAs
        # cap at ~206 GB/s on this platform (per-engine ceiling,
        # measured in benchmarks/bench_dma_layouts.py) while XLA's fused
        # gather sustains ~340 GB/s.  The kernel stays available via
        # impl="pallas" for platforms where Pallas DMA streams at full
        # bandwidth; the int8 in-kernel dequant path is new this round
        # and unmeasured on TPU (benchmarks/bench_kv_quant.py carries
        # the int8-Pallas row), so "auto" keeps the measured choice
        # until a TPU bench round says otherwise.  Under tp the jnp ops
        # partition natively (kv_heads axis), so no shard_map is needed
        # either way.
        impl = "jnp"
    if impl in ("pallas", "pallas_interpret"):
        interpret = impl == "pallas_interpret"
        if tp > 1:
            return _decode_pallas_tp(
                q, k_cache, v_cache, layer, block_tables, kv_lens,
                mesh=mesh, interpret=interpret,
                k_scale=k_scale, v_scale=v_scale,
            )
        from .pallas_paged_attention import paged_attention_decode_pallas

        return paged_attention_decode_pallas(
            q, k_cache, v_cache, layer, block_tables, kv_lens,
            interpret=interpret, k_scale=k_scale, v_scale=v_scale,
        )
    if impl not in ("jnp", "jnp_bf16"):
        raise ValueError(
            f"unknown attention impl {impl!r}; expected "
            + " | ".join(DECODE_IMPLS)
        )
    return paged_attention_decode_jnp(
        q, k_cache, v_cache, layer, block_tables, kv_lens,
        native_dtype=(impl == "jnp_bf16"),
        k_scale=k_scale, v_scale=v_scale,
    )
