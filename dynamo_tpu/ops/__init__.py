from .packed_prefill import packed_prefill_attention, write_packed_kv
from .paged_attention import (
    paged_attention_decode,
    paged_prefill_attention,
    write_prompt_kv,
    write_token_kv,
)

__all__ = [
    "packed_prefill_attention",
    "paged_attention_decode",
    "paged_prefill_attention",
    "write_packed_kv",
    "write_prompt_kv",
    "write_token_kv",
]
