from .paged_attention import (
    paged_attention_decode,
    paged_prefill_attention,
    write_prompt_kv,
    write_token_kv,
)

__all__ = [
    "paged_attention_decode",
    "paged_prefill_attention",
    "write_prompt_kv",
    "write_token_kv",
]
