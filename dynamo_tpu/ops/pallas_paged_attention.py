"""Pallas TPU paged-attention decode kernel.

The hand-tiled fast path for the decode hot loop — the TPU counterpart of
the reference's only first-party GPU kernels (the block gather/copy family
in lib/kvbm-kernels/cuda/tensor_kernels.cu:151,192,494): where the CUDA
kernels permute paged blocks through a universal layout, on TPU the same
block-gather problem is fused INTO attention — each sequence's scattered
KV blocks are DMA'd from HBM into VMEM by physical block id and consumed
by an online-softmax accumulation without ever materializing a gathered
context tensor in HBM (which is what the jnp fallback in paged_attention.py
makes XLA do, and why that path measures ~80% of the decode step).

Layout: the cache stores TRANSPOSED blocks, [n_kv, num_blocks, head_dim,
block_size] per layer (paged_attention.py docstring).  block_size is the
lane dimension, so with block_size a multiple of 128:
  * every (head, block) DMA slab [hd, bs] is lane-aligned for ANY head_dim
    (Mosaic rejects sub-128 lane slices; head_dim=64 models would otherwise
    need padded storage);
  * scores q[g,hd] @ k[hd,bs] and the p@v contraction are MXU-shaped with
    no in-kernel reshapes or lane-splits (both unsupported on this Mosaic).

Structure: grid = (batch,); block tables + kv lengths ride scalar prefetch
(SMEM); per sequence, KV is consumed in chunks of `bpc` physical blocks,
double-buffered (chunk c+1's DMAs fly while chunk c is reduced into fp32
m/l/acc carries).  Padded table entries point at physical block 0 (the
garbage block) and are masked by position, so shapes stay static.

Numerics match paged_attention.paged_attention_decode_jnp exactly (fp32
softmax accumulation); tests/test_paged_attention.py cross-checks the two,
and interpret mode keeps the kernel runnable on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    tables_ref,   # [B, n_chunks * bpc] int32 physical block ids
    kv_lens_ref,  # [B] int32 valid positions (incl. current token)
    # inputs
    q_ref,        # [1, nkv, group, hd] VMEM (this sequence's query)
    k_hbm,        # [nkv, num_blocks, hd, bs] ANY (stays in HBM)
    v_hbm,
    # output
    o_ref,        # [1, nkv, group, hd] VMEM
    # scratch
    k_buf,        # [2, nkv, bpc, hd, bs] VMEM
    v_buf,
    sem,          # DMA semaphores [2 slots, 2 (k/v)]
    *,
    bpc: int,
    bs: int,
):
    b = pl.program_id(0)
    nkv = k_hbm.shape[0]
    hd = k_hbm.shape[2]
    S = bpc * bs  # positions per chunk
    kv_len = kv_lens_ref[b]
    n_chunks = pl.cdiv(kv_len, S)

    def chunk_copies(c, slot):
        """Per-(head, block) DMAs for chunk c into buffer `slot`: each copy
        is one full [hd, bs] plane — contiguous, lane-aligned for any hd."""
        copies = []
        for i in range(bpc):
            pid = tables_ref[b, c * bpc + i]
            for h in range(nkv):
                copies.append(pltpu.make_async_copy(
                    k_hbm.at[h, pid], k_buf.at[slot, h, i], sem.at[slot, 0],
                ))
                copies.append(pltpu.make_async_copy(
                    v_hbm.at[h, pid], v_buf.at[slot, h, i], sem.at[slot, 1],
                ))
        return copies

    def start_chunk(c, slot):
        for cp in chunk_copies(c, slot):
            cp.start()

    def wait_chunk(c, slot):
        for cp in chunk_copies(c, slot):
            cp.wait()

    start_chunk(0, 0)
    q = q_ref[0].astype(jnp.float32)  # [nkv, group, hd]
    g = q.shape[1]

    def body(c, carry):
        m, l, acc = carry
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < n_chunks)
        def _():
            start_chunk(c + 1, jax.lax.rem(c + 1, 2))

        wait_chunk(c, slot)
        # one online-softmax update per block plane: every matmul is a
        # single-contracting-dim batched 2D form Mosaic lowers directly
        for i in range(bpc):
            k = k_buf[slot, :, i].astype(jnp.float32)  # [nkv, hd, bs]
            v = v_buf[slot, :, i].astype(jnp.float32)
            # scores [nkv, g, bs]: q[g,hd] @ k[hd,bs] per kv head
            s = jax.lax.dot_general(
                q, k, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            pos = (c * bpc + i) * bs \
                + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
            s = jnp.where(pos < kv_len, s, NEG_INF)

            m_new = jnp.maximum(m, jnp.max(s, axis=2, keepdims=True))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new)
            l = l * alpha + jnp.sum(p, axis=2, keepdims=True)
            # out [nkv, g, hd]: p[g,bs] @ v[hd,bs]^T per kv head
            pv = jax.lax.dot_general(
                p, v, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            acc = acc * alpha + pv
            m = m_new
        return m, l, acc

    m0 = jnp.full((nkv, g, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((nkv, g, 1), jnp.float32)
    a0 = jnp.zeros((nkv, g, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_chunks, body, (m0, l0, a0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("layer", "blocks_per_chunk", "interpret"),
)
def paged_attention_decode_pallas(
    q: jax.Array,             # [B, nh, hd] (rope applied, NOT pre-scaled)
    k_cache: jax.Array,       # [L, nkv, num_blocks, hd, bs]
    v_cache: jax.Array,
    layer: int,
    block_tables: jax.Array,  # [B, max_blocks] int32
    kv_lens: jax.Array,       # [B] int32, valid positions incl. current
    *,
    blocks_per_chunk: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Drop-in fast path for paged_attention.paged_attention_decode."""
    B, nh, hd = q.shape
    kc, vc = k_cache[layer], v_cache[layer]
    nkv, _, _, bs = kc.shape
    group = nh // nkv
    max_blocks = block_tables.shape[1]

    bpc = blocks_per_chunk or max(1, min(max_blocks, -(-256 // bs)))
    n_chunks = -(-max_blocks // bpc)
    pad = n_chunks * bpc - max_blocks
    if pad:
        # padded entries hit the garbage block (0) and are masked by pos
        block_tables = jnp.pad(block_tables, ((0, 0), (0, pad)))

    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    qg = (q.astype(jnp.float32) * scale).astype(q.dtype)
    qg = qg.reshape(B, nkv, group, hd)

    out = pl.pallas_call(
        functools.partial(_decode_kernel, bpc=bpc, bs=bs),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, nkv, group, hd),
                             lambda b, *refs: (b, 0, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((1, nkv, group, hd),
                                   lambda b, *refs: (b, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, nkv, bpc, hd, bs), kc.dtype),
                pltpu.VMEM((2, nkv, bpc, hd, bs), vc.dtype),
                pltpu.SemaphoreType.DMA((2, 2)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, nkv, group, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * 2 * B * nh * hd * max_blocks * bs,
            bytes_accessed=2 * B * nkv * max_blocks * bs * hd
            * kc.dtype.itemsize,
            transcendentals=B * nh * max_blocks * bs,
        ),
        interpret=interpret,
    )(block_tables, kv_lens, qg, kc, vc)
    return out.reshape(B, nh, hd)
