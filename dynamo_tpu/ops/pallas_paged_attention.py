"""Pallas TPU paged-attention decode kernel.

The hand-tiled fast path for the decode hot loop — the TPU counterpart of
the reference's only first-party GPU kernels (the block gather/copy family
in lib/kvbm-kernels/cuda/tensor_kernels.cu:151,192,494): where the CUDA
kernels permute paged blocks through a universal layout, on TPU the same
block-gather problem is fused INTO attention — each sequence's scattered
KV blocks are DMA'd from HBM into VMEM by physical block id and consumed
by an online-softmax accumulation without ever materializing a gathered
context tensor in HBM (which is what the jnp fallback in
paged_attention.py makes XLA do — double traffic through HBM).

Layout: the cache stores TRANSPOSED blocks, [n_kv, num_blocks, head_dim,
block_size] per layer (paged_attention.py docstring).  block_size is the
lane dimension, so with block_size a multiple of 128:
  * every per-block DMA ([nkv, hd, bs] — one strided descriptor covering
    all heads) is lane-aligned for ANY head_dim;
  * scores q[g,hd] @ k[hd,S] and the p@v contraction are MXU-shaped with
    no in-kernel reshapes or lane-splits.

Structure (what round-4's 0.55-of-roofline bench paid for getting wrong,
each point measured in benchmarks/bench_decode_phases.py):
  * grid = (batch,), sequential; block tables + kv lengths ride scalar
    prefetch (SMEM).
  * KV is consumed in chunks of `bpc` physical blocks DMA'd into
    [nkv, hd, S=bpc*bs] VMEM buffers, double-buffered, and the prefetch
    chain CROSSES grid steps (the last chunk of sequence b prefetches
    chunk 0 of sequence b+1, bookkept in SMEM scratch that persists
    across grid iterations) — the DMA engines never drain between
    sequences.  The prior per-(head, block) copies were latency-bound at
    ~190 GB/s; whole-chunk strided descriptors with a cross-sequence
    chain stream continuously.
  * compute per chunk is TWO batched bf16 dot_generals with fp32
    accumulation ([nkv, g, hd] @ [nkv, hd, S] and the p@v contraction)
    plus one online-softmax update on [nkv, g, S].  The prior kernel
    upcast K/V to fp32 and issued 2 matmuls PER BLOCK — fp32 MXU
    throughput plus 64 fill-bound passes made compute as slow as the
    entire bandwidth budget.

Int8 KV caches (quant/kv.py) are consumed natively: alongside each
[nkv, hd, bs] int8 block the kernel DMAs the block's [nkv, bs] fp32
scale row (the per-position scale planes that ride the cache as
sibling arrays) into [2, nkv, S] VMEM buffers on two extra semaphore
lanes, and the chunk consume fuses the dequantizing multiply —
int8 elements stream from HBM (half the bandwidth of bf16, +4 bytes
per position of scale), the MXU sees query-dtype operands (bf16 on
the serving path), softmax/accumulation stay fp32.  This is what lets
quantization's bandwidth win compound with the fast attention path
instead of routing around it (the pre-PR-12 jnp-gather fallback).

Padded table entries point at physical block 0 (the garbage block) and
are masked by position, so shapes stay static.  Numerics match
paged_attention.paged_attention_decode_jnp to bf16 matmul tolerance
(fp32 softmax and accumulation); tests/test_paged_attention.py and
tests/test_packed_pallas.py cross-check the two (int8 included), and
interpret mode keeps the kernel runnable on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def tpu_compiler_params(**kwargs):
    """pltpu compiler-params across jax versions: the class was named
    TPUCompilerParams before jax 0.5.x and CompilerParams after (found
    by the tier-1 interpreter cross-checks when the toolchain moved)."""
    cls = getattr(pltpu, "CompilerParams",
                  getattr(pltpu, "TPUCompilerParams", None))
    if cls is None:
        raise AttributeError(
            "jax.experimental.pallas.tpu exposes neither CompilerParams "
            "nor TPUCompilerParams; unsupported jax version"
        )
    return cls(**kwargs)


def make_chunk_dma(tables_ref, k_hbm, v_hbm, k_buf, v_buf, sem, *,
                   bpc, bs, ks_hbm=None, vs_hbm=None, ks_buf=None,
                   vs_buf=None):
    """The chunk DMA contract shared by the decode and packed-prefill
    kernels: (start, wait) closures moving `bpc` physical blocks into a
    double-buffered VMEM chunk — one strided descriptor per block per
    tensor ([nkv, hd, bs], all heads, landing at the block's offset in
    the chunk buffer), and for an int8 cache the block's [nkv, bs] fp32
    scale rows on two extra semaphore lanes (`sem` is [slots, 2] bf16 /
    [slots, 4] int8).  Both closures take (row, c, slot) where `row`
    indexes tables_ref's first axis (the sequence for decode, the
    segment for packed prefill).  One definition site keeps the two
    kernels' DMA contracts — descriptor shapes, semaphore pairing,
    scale lanes — from drifting."""
    quantized = ks_hbm is not None

    def _copies(row, c, slot):
        for i in range(bpc):
            pid = tables_ref[row, c * bpc + i]
            yield pltpu.make_async_copy(
                k_hbm.at[:, pid],
                k_buf.at[slot, :, :, pl.ds(i * bs, bs)],
                sem.at[slot, 0])
            yield pltpu.make_async_copy(
                v_hbm.at[:, pid],
                v_buf.at[slot, :, :, pl.ds(i * bs, bs)],
                sem.at[slot, 1])
            if quantized:
                yield pltpu.make_async_copy(
                    ks_hbm.at[:, pid],
                    ks_buf.at[slot, :, pl.ds(i * bs, bs)],
                    sem.at[slot, 2])
                yield pltpu.make_async_copy(
                    vs_hbm.at[:, pid],
                    vs_buf.at[slot, :, pl.ds(i * bs, bs)],
                    sem.at[slot, 3])

    def start(row, c, slot):
        for dma in _copies(row, c, slot):
            dma.start()

    def wait(row, c, slot):
        for dma in _copies(row, c, slot):
            dma.wait()

    return start, wait


def make_chunk_chain(start_chunk, wait_chunk):
    """Global never-drain slot phase over a make_chunk_dma pair — the
    scheme both kernels share: every chunk fetched anywhere in the
    launch occupies one position `base + c` in a single global phase
    sequence, its VMEM slot is `(base + c) % 2`, and each chunk's
    consume loop prefetches the NEXT phase's chunk (this row's next
    chunk, or chunk 0 of `next_row` — the next active row, possibly in
    a later grid step) into the opposite slot before waiting on its
    own.  Only the launch's globally first fetch (`base == 0`) is ever
    un-overlapped; the DMA engines never drain across sequence, tile,
    or segment boundaries.

    `prime(row, nch, base)` issues that first fetch; `step(row, c, nch,
    base, next_row)` runs inside the chunk loop and returns the slot
    holding chunk `c` (next_row < 0 = nothing left to prefetch).  The
    caller supplies `base` (chunks consumed by all earlier rows — the
    decode kernel recomputes it from kv_lens, the packed kernel rides a
    precomputed scalar-prefetch plane) and `next_row`; the double-buffer
    safety argument is program order: phase p+1's slot was last read by
    phase p-1's consume, which completes before p's loop iteration
    issues p+1."""

    def prime(row, nch, base):
        @pl.when((nch > 0) & (base == 0))
        def _():
            start_chunk(row, 0, 0)

    def step(row, c, nch, base, next_row):
        slot = jax.lax.rem(base + c, 2)
        nxt = jax.lax.rem(base + c + 1, 2)

        # prefetch BEFORE waiting: next chunk of this row, or chunk 0
        # of the next active row (the cross-boundary chain)
        @pl.when(c + 1 < nch)
        def _():
            start_chunk(row, c + 1, nxt)

        @pl.when((c + 1 == nch) & (next_row >= 0))
        def _():
            start_chunk(next_row, 0, nxt)

        wait_chunk(row, c, slot)
        return slot

    return prime, step


def _decode_kernel(
    # scalar prefetch
    tables_ref,   # [B, n_chunks * bpc] int32 physical block ids
    kv_lens_ref,  # [B] int32 valid positions (incl. current token)
    # inputs
    q_ref,        # [1, nkv, group, hd] VMEM (this sequence's query)
    k_hbm,        # [nkv, num_blocks, hd, bs] ANY (stays in HBM)
    v_hbm,
    # int8 caches add (ks_hbm, vs_hbm) [nkv, num_blocks, bs] fp32 ANY,
    # then: o_ref [1, nkv, group, hd] VMEM; scratch k_buf/v_buf
    # [2, nkv, hd, S] VMEM (+ks_buf/vs_buf [2, nkv, S] fp32), DMA
    # semaphores [2 slots, 2 (k/v) or 4 (+scales)]
    *rest,
    bpc: int,
    bs: int,
    quantized: bool = False,
    debug_mode: str = "",  # "" | "dma_only" | "compute_only" (profiling)
):
    if quantized:
        (ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf, vs_buf, sem) = rest
    else:
        (o_ref, k_buf, v_buf, sem) = rest
        ks_hbm = vs_hbm = ks_buf = vs_buf = None
    b = pl.program_id(0)
    B = pl.num_programs(0)
    nkv = k_hbm.shape[0]
    hd = k_hbm.shape[2]
    S = bpc * bs  # positions per chunk
    kv_len = kv_lens_ref[b]
    n_chunks = pl.cdiv(kv_len, S)

    # the chunk DMA contract (descriptor shapes, semaphore pairing, int8
    # scale lanes) is shared with the packed-prefill kernel
    start_chunk, wait_chunk = make_chunk_dma(
        tables_ref, k_hbm, v_hbm, k_buf, v_buf, sem, bpc=bpc, bs=bs,
        ks_hbm=ks_hbm, vs_hbm=vs_hbm, ks_buf=ks_buf, vs_buf=vs_buf)
    prime, chain_step = make_chunk_chain(start_chunk, wait_chunk)

    # slot phase = chunks consumed by earlier sequences (recomputed from
    # kv_lens — stateless, so the kernel needs nothing persisted across
    # grid steps); the wrapper clamps kv_lens >= 1, mirrored here so the
    # phase arithmetic cannot desync from the chunk loop
    base = jax.lax.fori_loop(
        0, b,
        lambda j, acc: acc + pl.cdiv(jnp.maximum(kv_lens_ref[j], 1), S),
        jnp.int32(0),
    )
    # the very first grid step primes the pipeline (every sequence has
    # >= 1 chunk, so base == 0 is exactly b == 0); afterwards chunk 0 of
    # sequence b was prefetched by sequence b-1's last chunk and the DMA
    # chain never drains between sequences
    prime(b, n_chunks, base)
    next_row = jnp.where(b + 1 < B, b + 1, -1)
    q = q_ref[0]     # [nkv, g, hd] bf16, pre-scaled
    g = q.shape[1]

    def body(c, carry):
        m, l, acc = carry
        if debug_mode == "compute_only":
            # profiling: every sequence reduces the primed buffer 0 (only
            # b==0/c==0 may wait — nothing ever signals the other grid
            # steps' semaphores, so waiting there would deadlock)
            slot = jnp.int32(0)

            @pl.when((c == 0) & (b == 0))
            def _():
                wait_chunk(0, 0, slot)
        else:
            slot = chain_step(b, c, n_chunks, base, next_row)
        if debug_mode == "dma_only":
            acc = acc + jnp.max(k_buf[slot].astype(jnp.float32)) \
                + jnp.max(v_buf[slot].astype(jnp.float32))
            return m, l, acc

        # scores [nkv, g, S]: ONE batched bf16 matmul for the whole chunk
        k = k_buf[slot]  # [nkv, hd, S]
        v = v_buf[slot]
        if quantized:
            # fused dequant on the chunk consume: int8 streamed from
            # HBM (half the traffic), per-position fp32 scale multiply
            # in VMEM, operands cast to the query dtype for the MXU
            # (bf16 on the serving path) with fp32 accumulation below
            k = (k.astype(jnp.float32)
                 * ks_buf[slot][:, None, :]).astype(q.dtype)
            v = (v.astype(jnp.float32)
                 * vs_buf[slot][:, None, :]).astype(q.dtype)
        s = jax.lax.dot_general(
            q, k, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        pos = c * S + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(pos < kv_len, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=2, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l = l * alpha + jnp.sum(p, axis=2, keepdims=True)
        # out [nkv, g, hd]: p is cast to the operand dtype for the MXU
        # (standard flash practice; fp32 running accumulation keeps the
        # precision).  `v` is the dequantized chunk on an int8 cache.
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v,
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc = acc * alpha + pv
        return m_new, l, acc

    m0 = jnp.full((nkv, g, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((nkv, g, 1), jnp.float32)
    a0 = jnp.zeros((nkv, g, hd), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_chunks, body, (m0, l0, a0))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


@functools.partial(
    # dynlint: disable=DYN001 kernel-level jit: engine dispatch reaches this inside already-watched programs; direct calls are bench/test-only
    jax.jit,
    static_argnames=("layer", "blocks_per_chunk", "interpret", "debug_mode"),
)
def paged_attention_decode_pallas(
    q: jax.Array,             # [B, nh, hd] (rope applied, NOT pre-scaled)
    k_cache: jax.Array,       # [L, nkv, num_blocks, hd, bs]
    v_cache: jax.Array,
    layer: int,
    block_tables: jax.Array,  # [B, max_blocks] int32
    kv_lens: jax.Array,       # [B] int32, valid positions incl. current
    *,
    blocks_per_chunk: int | None = None,
    interpret: bool = False,
    debug_mode: str = "",
    k_scale: jax.Array = None,  # [L, nkv, num_blocks, bs] fp32 (int8)
    v_scale: jax.Array = None,
) -> jax.Array:
    """Drop-in fast path for paged_attention.paged_attention_decode.

    With `k_scale`/`v_scale` (an int8 cache's per-position fp32 scale
    planes, quant/kv.py) the kernel DMAs int8 blocks plus their scale
    rows into VMEM and fuses the dequantizing multiply into the chunk
    consume — int8's halved HBM traffic lands inside the fast path."""
    B, nh, hd = q.shape
    kc, vc = k_cache[layer], v_cache[layer]
    nkv, _, _, bs = kc.shape
    group = nh // nkv
    max_blocks = block_tables.shape[1]
    quantized = k_scale is not None

    # chunk of up to 8 blocks (S = 1024 lanes at bs=128): big enough that
    # the two per-chunk matmuls amortize their pipeline fills and DMA
    # descriptors stay few, small enough for double-buffered VMEM
    bpc = blocks_per_chunk or max(1, min(max_blocks, -(-1024 // bs)))
    n_chunks = -(-max_blocks // bpc)
    pad = n_chunks * bpc - max_blocks
    if pad:
        # padded entries hit the garbage block (0) and are masked by pos
        block_tables = jnp.pad(block_tables, ((0, 0), (0, pad)))
    # the kernel's slot/semaphore chain assumes every sequence consumes
    # >= 1 chunk; the engine always passes ctx+1 >= 1, this is a guard
    kv_lens = jnp.maximum(kv_lens, 1)

    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    qg = (q.astype(jnp.float32) * scale).astype(q.dtype)
    qg = qg.reshape(B, nkv, group, hd)

    S = bpc * bs
    inputs = [qg, kc, vc]
    in_specs = [
        pl.BlockSpec((1, nkv, group, hd),
                     lambda b, *refs: (b, 0, 0, 0)),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    scratch = [
        pltpu.VMEM((2, nkv, hd, S), kc.dtype),
        pltpu.VMEM((2, nkv, hd, S), vc.dtype),
    ]
    if quantized:
        inputs += [k_scale[layer], v_scale[layer]]
        in_specs += [pl.BlockSpec(memory_space=pl.ANY),
                     pl.BlockSpec(memory_space=pl.ANY)]
        scratch += [pltpu.VMEM((2, nkv, S), jnp.float32),
                    pltpu.VMEM((2, nkv, S), jnp.float32)]
    scratch.append(pltpu.SemaphoreType.DMA((2, 4 if quantized else 2)))
    # bytes per context position per head: int8 streams 1-byte elements
    # plus one fp32 scale per (head, position)
    pos_bytes = hd * kc.dtype.itemsize + (4 if quantized else 0)
    out = pl.pallas_call(
        functools.partial(_decode_kernel, bpc=bpc, bs=bs,
                          quantized=quantized, debug_mode=debug_mode),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, nkv, group, hd),
                                   lambda b, *refs: (b, 0, 0, 0)),
            scratch_shapes=scratch,
        ),
        out_shape=jax.ShapeDtypeStruct((B, nkv, group, hd), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * 2 * B * nh * hd * max_blocks * bs,
            bytes_accessed=2 * B * nkv * max_blocks * bs * pos_bytes,
            transcendentals=B * nh * max_blocks * bs,
        ),
        interpret=interpret,
    )(block_tables, kv_lens, *inputs)
    return out.reshape(B, nh, hd)
