"""Paged Multi-head Latent Attention (MLA) ops — the DeepSeek-family
attention over a compressed latent KV cache.

Ref role: the reference serves DeepSeek-R1/V3 through vLLM/SGLang MLA
kernels (recipes/deepseek-r1/, docs/benchmarks/deepseek-v3-2-wideep-
routing.mdx); this is the TPU-native equivalent built on the same paged
layout as ops/paged_attention.py.

MLA caches, per token, a LATENT pair instead of per-head K/V:
    c    [R]   compressed KV latent (R = kv_lora_rank, e.g. 512)
    k_R  [dr]  decoupled RoPE key (dr = qk_rope_head_dim, e.g. 64)
an ~order-of-magnitude smaller cache than GQA for the same model — the
property that makes DeepSeek long-context serving cheap.  The caches
reuse the head-major transposed block layout with nkv=1:
    c_cache  [L, 1, nblocks, R,  bs]
    kr_cache [L, 1, nblocks, dr, bs]
so every existing block op (write/scatter/gather, KVBM offload, disagg
transfer) works unchanged on MLA engines.

Decode uses the WEIGHT-ABSORBED formulation: per head
    score_t = q_nope·(W_UK c_t) + q_rope·k_R_t
            = (q_nope W_UK^T)·c_t + q_rope·k_R_t
so the per-head key is never materialized — queries are absorbed into
latent space ([B, nh, R]) and attention runs directly against the cache;
the context vector (sum_t p_t c_t) is up-projected once by W_UV.  Prefill
materializes per-head K/V for the chunk+context (the standard non-absorbed
path: better MXU shapes for long chunks, and it runs once per prompt).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _gather_latent(cache: jax.Array, layer: int,
                   block_table: jax.Array) -> jax.Array:
    """[L,1,nb,R,bs] + [max_blocks] -> [S, R] (S = max_blocks*bs)."""
    g = cache[layer, 0][block_table]         # [mb, R, bs]
    mb, R, bs = g.shape
    return g.swapaxes(1, 2).reshape(mb * bs, R)


def mla_prefill_attention(
    q_nope: jax.Array,    # [T, nh, dn]  (no rope)
    q_rope: jax.Array,    # [T, nh, dr]  (rope applied)
    c: jax.Array,         # [T, R]   this chunk's latents (normed)
    kr: jax.Array,        # [T, dr]  this chunk's rope keys (rope applied)
    c_cache: jax.Array,
    kr_cache: jax.Array,
    layer: int,
    block_table: jax.Array,  # [max_blocks]
    ctx_len: jax.Array,      # cached tokens this chunk attends to
    true_len: jax.Array,     # valid tokens in the chunk
    w_uk: jax.Array,      # [nh, R, dn]
    w_uv: jax.Array,      # [nh, R, dv]
) -> jax.Array:
    """Chunk tokens attend to (cached context) ++ (chunk, causally).
    Returns [T, nh, dv].  Cached context is up-projected from latents —
    identical math to having cached full K/V, at R+dr bytes/token."""
    T, nh, dn = q_nope.shape
    dr = q_rope.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.float32(dn + dr))

    c_ctx = _gather_latent(c_cache, layer, block_table)    # [S, R]
    kr_ctx = _gather_latent(kr_cache, layer, block_table)  # [S, dr]
    S = c_ctx.shape[0]
    c_all = jnp.concatenate([c_ctx.astype(jnp.float32),
                             c.astype(jnp.float32)], axis=0)   # [S+T, R]
    kr_all = jnp.concatenate([kr_ctx.astype(jnp.float32),
                              kr.astype(jnp.float32)], axis=0)  # [S+T, dr]

    k_nope = jnp.einsum("sr,hrd->hsd", c_all,
                        w_uk.astype(jnp.float32))          # [nh, S+T, dn]
    v_all = jnp.einsum("sr,hrd->hsd", c_all,
                       w_uv.astype(jnp.float32))           # [nh, S+T, dv]

    s = jnp.einsum("thd,hsd->ths", q_nope.astype(jnp.float32), k_nope)
    s = s + jnp.einsum("thd,sd->ths", q_rope.astype(jnp.float32), kr_all)
    s = s * scale                                          # [T, nh, S+T]

    i = jnp.arange(T)[:, None, None]
    j = jnp.arange(S + T)[None, None, :]
    # context part: j < ctx_len; self part: causal within valid chunk
    mask = jnp.where(j < S, j < ctx_len,
                     ((j - S) <= i) & ((j - S) < true_len))
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("ths,hsd->thd", p, v_all)             # [T, nh, dv]
    return out.astype(q_nope.dtype)


def mla_decode_attention(
    q_abs: jax.Array,     # [B, nh, R]  absorbed queries (q_nope @ w_uk^T)
    q_rope: jax.Array,    # [B, nh, dr]
    c_cache: jax.Array,
    kr_cache: jax.Array,
    layer: int,
    block_tables: jax.Array,  # [B, max_blocks]
    kv_lens: jax.Array,       # [B] valid tokens (incl. the one just written)
    w_uv: jax.Array,      # [nh, R, dv]
    scale: jax.Array | float,
) -> jax.Array:
    """One decode step over the latent cache, weight-absorbed.
    Returns [B, nh, dv]."""

    def one(qa, qr, table, kvlen):
        c_ctx = _gather_latent(c_cache, layer, table)      # [S, R]
        kr_ctx = _gather_latent(kr_cache, layer, table)    # [S, dr]
        s = jnp.einsum("hr,sr->hs", qa.astype(jnp.float32),
                       c_ctx.astype(jnp.float32))
        s = s + jnp.einsum("hd,sd->hs", qr.astype(jnp.float32),
                           kr_ctx.astype(jnp.float32))
        s = s * scale
        mask = (jnp.arange(c_ctx.shape[0]) < kvlen)[None, :]
        s = jnp.where(mask, s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1)                     # [nh, S]
        ctx = jnp.einsum("hs,sr->hr", p, c_ctx.astype(jnp.float32))
        return jnp.einsum("hr,hrd->hd", ctx, w_uv.astype(jnp.float32))

    out = jax.vmap(one)(q_abs, q_rope, block_tables, kv_lens)
    return out.astype(q_abs.dtype)
