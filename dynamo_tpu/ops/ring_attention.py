"""Ring attention: sequence-parallel exact attention for long context.

The TPU-native answer to the reference's long-sequence context parallelism
(SURVEY.md §2.4 SP row).  The sequence axis is sharded over an "sp" mesh
axis; each device holds one Q shard and one KV shard.  The kernel runs
axis_size steps of flash-style online softmax, rotating the KV shard one
hop around the ring with `lax.ppermute` per step, so

  * memory per device is O(T / sp) — context length scales linearly with
    the ring size,
  * the rotation rides the ICI ring (neighbor exchange, the topology's
    native pattern), overlapped by XLA with the per-step attention matmuls,
  * the result is EXACT attention (online-softmax rescaling, no
    approximation), verified against the single-device reference in
    tests/test_ring_attention.py.

Design notes (vs a naive translation of GPU ring attention):
  - accumulators stay in float32 regardless of input dtype (bf16-safe);
  - causal masking is done with *global* positions derived from
    `axis_index`, so per-step masks are static-shape and jit-friendly;
  - fully-masked (future) chunks still rotate — the ppermute schedule is
    uniform across devices, which XLA requires — but their contribution is
    exp(-inf) = 0 under the masked online-softmax update, so correctness
    does not depend on skipping them.

GQA is supported: kv_heads may divide q_heads; KV shards carry only the
kv_heads, the kernel broadcasts over the head-group axis on the fly.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.compat import pvary, shard_map

_NEG_INF = -1e30  # finite -inf stand-in: keeps exp/max NaN-free


def _online_update(o, m, l, s, v):
    """One flash-attention accumulator update, grouped GQA layout.

    o [T, G, R, D] f32, m/l [T, G, R] f32, s [T, G, R, Tk] f32 scores
    (already masked), v [Tk, G, D] — G = kv heads, R = q heads per group."""
    m_new = jnp.maximum(m, s.max(axis=-1))
    # rows with no unmasked key yet: keep exponent base at 0 to avoid
    # exp(large) — their p and alpha both come out 0/1 harmlessly
    base = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(s - base[..., None])           # [T, G, R, Tk]
    p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
    alpha = jnp.exp(jnp.where(m <= _NEG_INF / 2, 0.0, m) - base)
    alpha = jnp.where(m <= _NEG_INF / 2, jnp.where(m_new <= _NEG_INF / 2,
                                                   1.0, 0.0), alpha)
    l_new = l * alpha + p.sum(axis=-1)
    pv = jnp.einsum("tgrs,sgd->tgrd", p, v.astype(jnp.float32))
    o_new = o * alpha[..., None] + pv
    return o_new, m_new, l_new


def _ring_shard(q, k, v, *, axis_name: str, causal: bool, sm_scale: float):
    """Per-device body under shard_map.  q [Tq, Hq, D]; k,v [Tk, Hkv, D]."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    tq, hq, d = q.shape
    tk, hkv = k.shape[0], k.shape[1]
    # grouped GQA layout end-to-end: [T, G=hkv, R=hq//hkv, ...]
    qg = q.reshape(tq, hkv, hq // hkv, d).astype(jnp.float32)
    q_pos = my_idx * tq + jnp.arange(tq)  # global query positions

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def attend(o, m, l, kr, vr, src):
        k_pos = src * tk + jnp.arange(tk)
        s = jnp.einsum("tgrd,sgd->tgrs", qg,
                       kr.astype(jnp.float32)) * sm_scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]      # [Tq, Tk]
            s = jnp.where(mask[:, None, None, :], s, _NEG_INF)
        return _online_update(o, m, l, s, vr)

    def step(i, carry):
        o, m, l, kr, vr = carry
        # rotate FIRST: the i=0 (resident-shard) contribution is computed
        # outside the loop, so no dead permute after the final step
        kr = lax.ppermute(kr, axis_name, perm)
        vr = lax.ppermute(vr, axis_name, perm)
        # after i forward hops the resident shard originated at ring
        # position (my_idx - i) mod axis_size
        src = (my_idx - i) % axis_size
        o, m, l = attend(o, m, l, kr, vr, src)
        return o, m, l, kr, vr

    # constants start device-invariant; the accumulators become
    # device-varying after one update, so align the carry types (jax>=0.9
    # varying-manual-axes tracking)
    o = pvary(jnp.zeros((tq, hkv, hq // hkv, d), jnp.float32), axis_name)
    m = pvary(jnp.full((tq, hkv, hq // hkv), _NEG_INF, jnp.float32),
              axis_name)
    l = pvary(jnp.zeros((tq, hkv, hq // hkv), jnp.float32), axis_name)
    o, m, l = attend(o, m, l, k, v, my_idx)
    o, m, l, _, _ = lax.fori_loop(1, axis_size, step, (o, m, l, k, v))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> 0 output
    return (o / l[..., None]).reshape(tq, hq, d).astype(q.dtype)


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
    axis_name: str = "sp", causal: bool = True,
    sm_scale: Optional[float] = None, head_axis: Optional[str] = None,
) -> jax.Array:
    """Exact attention with the sequence axis sharded over `axis_name`.

    q [B, T, Hq, D], k/v [B, T, Hkv, D]; T must divide evenly by the sp
    axis size.  When the head axis is tensor-sharded, pass its mesh axis as
    `head_axis` so each tp shard keeps only its own heads (the ring runs
    per head-shard; omitting it would all-gather heads and redo every
    head's FLOPs on every tp device).  Returns [B, T, Hq, D] sharded like
    the inputs."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    body = partial(_ring_shard, axis_name=axis_name, causal=causal,
                   sm_scale=sm_scale)
    spec = P(None, axis_name, head_axis, None)
    fn = shard_map(
        jax.vmap(body, in_axes=(0, 0, 0)),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return fn(q, k, v)


def attention_reference(q, k, v, causal: bool = True,
                        sm_scale: Optional[float] = None) -> jax.Array:
    """Single-device exact attention (the oracle for ring tests).

    Same shapes/semantics as ring_attention, computed globally."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    b, t, hq, d = q.shape
    hkv = k.shape[2]
    s = jnp.einsum(
        "btgrd,bsgd->btgrs",
        q.reshape(b, t, hkv, hq // hkv, d).astype(jnp.float32),
        k.astype(jnp.float32),
    ) * sm_scale
    if causal:
        mask = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]
        s = jnp.where(mask[None, :, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("btgrs,bsgd->btgrd", p, v.astype(jnp.float32))
    return o.reshape(b, t, hq, d).astype(q.dtype)
