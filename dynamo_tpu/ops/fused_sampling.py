"""Fused sampling/top-k epilogue: final projection -> token ids without
materializing [B, vocab] logits in HBM.

The decode hot loop's second documented stall (after attention): every
step runs the [B, d] x [d, vocab] final projection, writes [B, vocab]
fp32 logits to HBM, then reads them straight back for an argmax or a
top-CAP window — at Llama-3 vocab (128k) that round trip is ~1 MB per
slot per token of pure HBM traffic on an otherwise bandwidth-bound
phase.  This epilogue streams the projection in vocab TILES and reduces
each tile on the fly into exactly the statistics sampling needs:

  * a running argmax over the RAW logits (strict `>` update, so the
    first maximum wins — byte-identical to `jnp.argmax` over the full
    vector, which is the sampler's greedy and temp<=0 contract);
  * a running top-CAP candidate window over the TEMPERATURE-SCALED
    logits (merge order: running candidates concatenated BEFORE the
    tile's, so `lax.top_k`'s stable lower-index tie-break matches the
    full-vocab call);
  * a running logsumexp of the scaled logits (online max/sum rescale),
    the true-softmax normalizer the top-p nucleus is measured against.

From those three, `fused_sample_tokens` replays engine/sampler.py's
`sample_tokens` EXACTLY — same fold_in(PRNGKey(seed), step) key, same
top-k clamp, same first-candidate-always-kept nucleus mask, same masked
categorical — so greedy output is byte-identical and sampled output is
distribution-identical (the only divergence is the fp32 summation
order inside logsumexp, ~1 ulp on the nucleus boundary).

Implementation choice (the "measured choice" the EngineConfig knob
gates): fused-XLA (a fori_loop of dynamic-sliced tile matmuls inside
the already-jitted decode program) rather than a Pallas kernel — the
projection is a plain MXU matmul XLA already schedules at peak, the
reduction carry is tiny ([B, CAP]), and keeping it in XLA lets the
epilogue fuse into decode/decode_multi without a second kernel launch
or its own VMEM budget.  A Pallas variant only pays once the tile
reductions themselves bound the step; the knob ("off" | "fused") keeps
the jnp reference path as fallback and A/B row.

Callers pass the FINAL-NORM hidden state (models/llama.py decode_hidden)
plus the unembedding matrix (models/llama.py unembed_weight); each tile
computes `(h @ w[:, a:b]).astype(fp32)` — columnwise identical to the
reference `_logits` matmul, which is what the byte-identity contract
rides on (tests/test_fused_sampling.py, tests/test_engine_epilogue.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30

#: sampling candidate window — MUST equal engine/sampler.py CAP (the
#: reference this epilogue is byte/distribution-identical to); asserted
#: in tests/test_fused_sampling.py
CAP = 64

#: vocab columns per streamed tile: big enough that the tile matmul is
#: MXU-efficient, small enough that [B, tile] fp32 stays in registers /
#: VMEM-resident fusion instead of round-tripping HBM
DEFAULT_TILE = 2048

#: EngineConfig.sampling_epilogue vocabulary (validated in
#: engine/core.py, advertised by the worker MDC)
EPILOGUE_MODES = ("off", "fused")


def _tile_plan(V: int, tile: int):
    """Clamped tile width and count.  The last tile's start is clamped
    to V - tile (dynamic_slice semantics), so its leading columns
    overlap the previous tile; per-tile `fresh` masks re-hide them."""
    tile = max(1, min(tile, V))
    return tile, -(-V // tile)


def _tile_logits(h, w, i, tile, V):
    """One streamed tile: fp32 logits [B, tile], global column ids
    [tile], and the fresh-mask hiding the clamped last tile's overlap
    with its predecessor."""
    D = h.shape[1]
    start = jnp.minimum(i * tile, V - tile)
    wt = jax.lax.dynamic_slice(w, (0, start), (D, tile))
    lg = (h @ wt).astype(jnp.float32)
    cols = start + jnp.arange(tile, dtype=jnp.int32)
    fresh = cols >= i * tile
    return lg, cols, fresh


def fused_greedy_tokens(h: jax.Array,   # [B, d] final-norm hidden
                        w: jax.Array,   # [d, vocab] unembedding matrix
                        *, tile: int = DEFAULT_TILE) -> jax.Array:
    """Streaming argmax of the final projection: byte-identical to
    sampler.greedy_tokens(_logits(...)) — strict `>` keeps the first
    maximum, tiles ascend, so ties resolve to the lowest vocab id
    exactly like jnp.argmax.  Returns token ids [B] int32."""
    B = h.shape[0]
    V = w.shape[1]
    tile, n_t = _tile_plan(V, tile)

    def body(i, carry):
        bv, bi = carry
        lg, cols, fresh = _tile_logits(h, w, i, tile, V)
        lg = jnp.where(fresh[None, :], lg, -jnp.inf)
        tv = jnp.max(lg, axis=-1)
        ta = cols[jnp.argmax(lg, axis=-1)]
        upd = tv > bv
        return jnp.where(upd, tv, bv), jnp.where(upd, ta, bi)

    _, bi = jax.lax.fori_loop(
        0, n_t, body,
        (jnp.full((B,), -jnp.inf, jnp.float32),
         jnp.zeros((B,), jnp.int32)))
    return bi


def fused_sample_tokens(
    h: jax.Array,            # [B, d] final-norm hidden
    w: jax.Array,            # [d, vocab] unembedding matrix
    seeds: jax.Array,        # [B] int32 per-request seed
    steps: jax.Array,        # [B] int32 decode step counter (rng stream)
    temperature: jax.Array,  # [B] fp32; <=0 means greedy
    top_k: jax.Array,        # [B] int32; 0 disables
    top_p: jax.Array,        # [B] fp32; >=1 disables
    *, tile: int = DEFAULT_TILE,
) -> jax.Array:
    """Streaming sample_tokens: one pass over the projection tiles
    accumulates (argmax, top-CAP window, logsumexp), then the sampler's
    masked-window categorical replays verbatim on the window.  Requires
    vocab >= CAP — the same bound lax.top_k imposes on the reference."""
    B = h.shape[0]
    V = w.shape[1]
    tile, n_t = _tile_plan(V, max(tile, CAP))
    denom = jnp.maximum(temperature, 1e-6)  # sampler.py's scaled = lg/..

    def body(i, carry):
        bv, bi, rv, ri, m, s = carry
        lg, cols, fresh = _tile_logits(h, w, i, tile, V)
        # greedy stream over RAW logits (the temp<=0 per-slot fallback)
        lgm = jnp.where(fresh[None, :], lg, -jnp.inf)
        tv = jnp.max(lgm, axis=-1)
        ta = cols[jnp.argmax(lgm, axis=-1)]
        upd = tv > bv
        bv = jnp.where(upd, tv, bv)
        bi = jnp.where(upd, ta, bi)
        # temperature-scaled stream (division, matching the reference's
        # rounding exactly); overlap columns hide at -inf: exp -> 0 in
        # the normalizer, never a candidate
        sc = jnp.where(fresh[None, :], lg / denom[:, None], -jnp.inf)
        # online logsumexp
        mn = jnp.maximum(m, jnp.max(sc, axis=-1))
        s = s * jnp.exp(m - mn) \
            + jnp.sum(jnp.exp(sc - mn[:, None]), axis=-1)
        # top-CAP merge: running window FIRST so lax.top_k's stable
        # tie-break prefers earlier (lower-id) candidates, matching the
        # full-vocab call's ascending-index tie order
        tvk, tik = jax.lax.top_k(sc, CAP)
        cat_v = jnp.concatenate([rv, tvk], axis=-1)
        cat_i = jnp.concatenate([ri, cols[tik]], axis=-1)
        rv, sel = jax.lax.top_k(cat_v, CAP)
        ri = jnp.take_along_axis(cat_i, sel, axis=-1)
        return bv, bi, rv, ri, mn, s

    bv, bi, rv, ri, m, s = jax.lax.fori_loop(
        0, n_t, body,
        (jnp.full((B,), -jnp.inf, jnp.float32),
         jnp.zeros((B,), jnp.int32),
         jnp.full((B, CAP), -jnp.inf, jnp.float32),
         jnp.zeros((B, CAP), jnp.int32),
         jnp.full((B,), -jnp.inf, jnp.float32),
         jnp.zeros((B,), jnp.float32)))
    lse = m + jnp.log(s)

    # engine/sampler.py sample_tokens' window math, verbatim, on the
    # streamed (vals, idx, lse) instead of a full-vocab top_k
    def one(gidx, vals, idx, lse1, seed, step, temp, tk, tp):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        k_eff = jnp.clip(jnp.where(tk > 0, tk, CAP), 1, CAP)
        keep_k = jnp.arange(CAP) < k_eff
        probs = jnp.exp(vals - lse1)
        cum = jnp.cumsum(probs)
        keep_p = jnp.concatenate([jnp.array([True]), cum[:-1] < tp])
        masked = jnp.where(keep_k & keep_p, vals, NEG_INF)
        sampled = idx[jax.random.categorical(key, masked)]
        return jnp.where(temp <= 0.0, gidx, sampled)

    return jax.vmap(one)(bi, rv, ri, lse, seeds, steps, temperature,
                         top_k, top_p)
