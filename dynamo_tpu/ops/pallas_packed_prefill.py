"""Pallas TPU packed-prefill kernel: segment-aware causal flash attention
over the packed token stream.

The hand-tiled fast path filling the `impl="pallas"` slot
ops/packed_prefill.py reserved.  The XLA reference there runs one flash
pass PER SEGMENT ROW over the WHOLE packed stream and masks foreign
tokens out — an S-fold attention-FLOP overhead (S = co-scheduled
segment rows), plus a gathered-context round trip through HBM.  This
kernel removes both:

  * **Tile-skip iteration.**  The grid walks the packed stream in
    TOKEN BLOCKS.  For each (token block, segment) pair the wrapper
    precomputes how many context CHUNKS the pair actually needs —
    zero when the segment owns no token in the block (the skip), and
    otherwise only up to the block's own causal frontier
    ``ceil((max position in block)/chunk)`` rather than the full table
    width.  The packed stream is segment-contiguous (engine/prefill.py
    packs each slot's chunk back to back), so almost every token block
    intersects exactly ONE segment: total attention work is ~1x the
    stream's own context instead of S x, and the *causal* half of each
    segment's score rectangle is skipped at chunk granularity too.

  * **In-VMEM context.**  Each chunk's KV blocks are DMA'd from HBM by
    physical block id into double-buffered VMEM chunk buffers (the
    layout conventions of pallas_paged_attention.py: head-major
    TRANSPOSED blocks, [nkv, hd, bs] per-block strided descriptors,
    lane-aligned for block_size multiples of 128) and consumed by an
    online-softmax accumulation — no gathered [S, ctx, hd] tensor ever
    materializes in HBM.

Int8 KV caches (quant/kv.py) are first-class: pass the per-position
fp32 scale planes and the kernel DMAs int8 blocks + their scale rows
into VMEM and fuses the dequantizing multiply into the chunk consume
(operands in the query dtype — bf16 on the serving path — with fp32
softmax/accumulation), so quantization's halved HBM traffic lands
inside the fast path instead of routing around it.

The chunk DMA chain CROSSES tile and segment boundaries (the decode
kernel's never-drain scheme, generalized): the wrapper derives two more
scalar-prefetch planes from `nchunks` — a global slot PHASE (exclusive
tile-major cumulative sum: how many chunks all earlier (tile, segment)
pairs consume) and each pair's successor row (the next active pair in
tile-major order, -1 at the end) — and every pair's last chunk
prefetches its successor's chunk 0 into the opposite double-buffer
slot (pallas_paged_attention.make_chunk_chain, one definition site
with the decode kernel).  Only the launch's globally first fetch is
un-overlapped; no per-(tile, segment) chunk-0 latency is exposed.

Numerics: fp32 online softmax and accumulation, operands in the query
dtype.  One shared running (m, l, acc) per token row accumulates across
segments; masked positions contribute exp=0 explicitly (not just
NEG_INF scores), so a token's accumulator is untouched while foreign
segments stream past — the property that lets all S segment passes
share one carry without the reference's per-pass output select.
Matches packed_prefill_attention's XLA path to bf16 matmul tolerance;
interpret mode keeps the kernel runnable on CPU for tier-1
(tests/test_packed_pallas.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_paged_attention import (
    make_chunk_chain,
    make_chunk_dma,
    tpu_compiler_params,
)

NEG_INF = -1e30


def _next_pow2(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


def _packed_kernel(
    # scalar prefetch
    tables_ref,    # [S, n_chunks * bpc] int32 physical block ids
    nchunks_ref,   # [n_tiles, S] int32 context chunks per (tile, segment)
    base_ref,      # [n_tiles, S] int32 global slot phase per pair
    nseg_ref,      # [n_tiles, S] int32 successor segment row (-1 = none)
    # inputs
    seg_ref,       # [1, TB] int32 segment row per token (-1 = padded)
    pos_ref,       # [1, TB] int32 absolute position per token
    q_ref,         # [nkv, TB, g, hd] VMEM (this tile's queries, pre-scaled)
    k_hbm,         # [nkv, num_blocks, hd, bs] ANY (stays in HBM)
    v_hbm,
    *rest,         # (+ks_hbm, vs_hbm when quantized) o_ref, scratch...
    S: int,
    bpc: int,
    bs: int,
    quantized: bool,
):
    if quantized:
        (ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf, vs_buf, sem) = rest
    else:
        (o_ref, k_buf, v_buf, sem) = rest
        ks_hbm = vs_hbm = ks_buf = vs_buf = None
    t = pl.program_id(0)
    C = bpc * bs  # context positions per chunk
    q = q_ref[...]            # [nkv, TB, g, hd]
    seg = seg_ref[0]          # [TB]
    pos = pos_ref[0]
    nkv, TB, g, hd = q.shape

    # the chunk DMA contract (descriptor shapes, semaphore pairing, int8
    # scale lanes) is shared with the decode kernel; `row` here is the
    # segment index into the per-segment block tables
    start_chunk, wait_chunk = make_chunk_dma(
        tables_ref, k_hbm, v_hbm, k_buf, v_buf, sem, bpc=bpc, bs=bs,
        ks_hbm=ks_hbm, vs_hbm=vs_hbm, ks_buf=ks_buf, vs_buf=vs_buf)
    prime, chain_step = make_chunk_chain(start_chunk, wait_chunk)

    carry = (
        jnp.full((nkv, TB, g), NEG_INF, jnp.float32),
        jnp.zeros((nkv, TB, g), jnp.float32),
        jnp.zeros((nkv, TB, g, hd), jnp.float32),
    )
    # static unroll over segment rows (S is small — max_prefill_seqs
    # pow2); the chunk count is 0 for every segment with no token in
    # this tile, so the fori_loop below skips foreign (tile, segment)
    # pairs entirely — the tile-skip that removes the S-fold overhead
    for s in range(S):
        nch = nchunks_ref[t, s]
        base = base_ref[t, s]
        nseg = nseg_ref[t, s]

        # only the launch's globally first active pair primes chunk 0;
        # every other pair's chunk 0 was prefetched by its predecessor's
        # last chunk (cross-tile/segment never-drain chain)
        prime(s, nch, base)

        owned = seg == s  # [TB]

        def body(c, carry, s=s, owned=owned, nch=nch, base=base,
                 nseg=nseg):
            m, l, acc = carry
            slot = chain_step(s, c, nch, base, nseg)
            k = k_buf[slot]  # [nkv, hd, C]
            v = v_buf[slot]
            if quantized:
                # fused dequant on the chunk consume: int8 streamed from
                # HBM, multiplied by the per-position fp32 scale row,
                # cast to the query dtype for the MXU (bf16 operands,
                # fp32 accumulation on the serving path)
                k = (k.astype(jnp.float32)
                     * ks_buf[slot][:, None, :]).astype(q.dtype)
                v = (v.astype(jnp.float32)
                     * vs_buf[slot][:, None, :]).astype(q.dtype)
            # scores [nkv, TB, g, C]: one batched matmul for the tile
            sc = jax.lax.dot_general(
                q, k, (((3,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            span = c * C + jax.lax.broadcasted_iota(jnp.int32, (TB, C), 1)
            mask = owned[:, None] & (span <= pos[:, None])  # [TB, C]
            m4 = mask[None, :, None, :]
            sc = jnp.where(m4, sc, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(sc, axis=3))
            alpha = jnp.exp(m - m_new)
            # explicit zero outside the mask: a fully-masked row leaves
            # (m, l, acc) untouched, so the shared carry never mixes
            # foreign segments' junk into a real token's accumulation
            p = jnp.where(m4, jnp.exp(sc - m_new[..., None]), 0.0)
            l = l * alpha + jnp.sum(p, axis=3)
            pv = jax.lax.dot_general(
                p.astype(v.dtype), v, (((3,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            acc = acc * alpha[..., None] + pv
            return m_new, l, acc

        carry = jax.lax.fori_loop(0, nch, body, carry)
    m, l, acc = carry
    # tokens no segment owns (padded tail) have l == 0 -> output 0,
    # matching the XLA reference's untouched zero-init output rows
    o_ref[...] = (acc / jnp.maximum(l, 1e-20)[..., None]).astype(
        o_ref.dtype)


@functools.partial(
    # dynlint: disable=DYN001 kernel-level jit: engine dispatch reaches this inside already-watched programs (prefill_packed/spec_verify); direct calls are bench/test-only
    jax.jit,
    static_argnames=("layer", "chunk_cols", "token_block", "interpret"),
)
def packed_prefill_attention_pallas(
    q: jax.Array,             # [T, nh, hd] packed-stream queries (rope'd)
    k_cache: jax.Array,       # [L, nkv, num_blocks, hd, bs]
    v_cache: jax.Array,
    layer: int,
    block_tables: jax.Array,  # [S, mb] int32 per-segment block tables
    seg_ids: jax.Array,       # [T] int32 segment row per token
    positions: jax.Array,     # [T] int32 absolute position per token
    valid: jax.Array,         # [T] bool (False = padded tail)
    *,
    chunk_cols: int = 8,      # block columns per context chunk
    token_block: int = 0,     # query tokens per tile (0 = auto)
    interpret: bool = False,
    k_scale: jax.Array = None,  # [L, nkv, num_blocks, bs] fp32 (int8)
    v_scale: jax.Array = None,
) -> jax.Array:
    """Drop-in fast path for packed_prefill.packed_prefill_attention
    (impl="pallas"/"pallas_interpret").  Returns [T, nh, hd] in q's
    dtype; tokens outside every segment (the padded tail) return 0."""
    T, nh, hd = q.shape
    kc, vc = k_cache[layer], v_cache[layer]
    nkv, _, _, bs = kc.shape
    group = nh // nkv
    S, mb = block_tables.shape
    quantized = k_scale is not None

    TB = token_block or min(128, _next_pow2(T))
    n_tiles = -(-T // TB)
    Tp = n_tiles * TB

    bpc = max(1, min(mb, chunk_cols))
    n_chunks = -(-mb // bpc)
    pad_cols = n_chunks * bpc - mb
    if pad_cols:
        # padded table entries point at the garbage block (0); the span
        # mask keeps them out of every real token's window
        block_tables = jnp.pad(block_tables, ((0, 0), (0, pad_cols)))
    C = bpc * bs

    # padded-tail / invalid tokens get segment -1: they match no
    # segment row, so no mask ever selects them and no chunk count
    # grows on their behalf
    seg_eff = jnp.where(valid, seg_ids, -1).astype(jnp.int32)
    pad_t = Tp - T
    if pad_t:
        seg_eff = jnp.pad(seg_eff, (0, pad_t), constant_values=-1)
        positions = jnp.pad(positions, (0, pad_t))
        q = jnp.pad(q, ((0, pad_t), (0, 0), (0, 0)))

    # per-(tile, segment) causal chunk frontier: 0 chunks when the
    # segment owns no token in the tile (the skip), else enough chunks
    # to cover the tile's farthest owned position — the wrapper-side
    # half of the tile-skip scheme
    seg2d = seg_eff.reshape(n_tiles, TB)
    pos2d = positions.reshape(n_tiles, TB).astype(jnp.int32)
    owned = seg2d[None, :, :] == jnp.arange(S, dtype=jnp.int32)[:, None,
                                                                None]
    maxpos = jnp.max(jnp.where(owned, pos2d[None, :, :], -1), axis=2)
    nch = jnp.where(maxpos >= 0, maxpos // C + 1, 0)
    nchunks = jnp.minimum(nch, n_chunks).astype(jnp.int32).T  # [n_tiles, S]

    # cross-tile/segment DMA chain planes (make_chunk_chain): the global
    # slot PHASE of each (tile, segment) pair — exclusive tile-major
    # cumulative sum of nchunks, so slot(chunk c of pair) = (base+c)%2 —
    # and each pair's successor row: the segment index of the next
    # active pair in tile-major order (suffix-min over flat indices,
    # -1 past the last), whose chunk 0 the pair's last chunk prefetches
    flat = nchunks.reshape(-1)                    # tile-major [n_tiles*S]
    chunk_base = (jnp.cumsum(flat) - flat).astype(jnp.int32) \
        .reshape(n_tiles, S)
    npairs = flat.shape[0]
    fidx = jnp.arange(npairs, dtype=jnp.int32)
    cand = jnp.where(flat > 0, fidx, npairs)      # inactive -> sentinel
    suf = jax.lax.cummin(cand[::-1])[::-1]        # min over cand[i:]
    suf_excl = jnp.concatenate(
        [suf[1:], jnp.full((1,), npairs, jnp.int32)])
    next_seg = jnp.where(suf_excl < npairs, suf_excl % S, -1) \
        .astype(jnp.int32).reshape(n_tiles, S)

    scale = 1.0 / jnp.sqrt(jnp.float32(hd))
    qg = (q.astype(jnp.float32) * scale).astype(q.dtype)
    qg = qg.reshape(Tp, nkv, group, hd).transpose(1, 0, 2, 3)

    inputs = [seg2d, pos2d, qg, kc, vc]
    in_specs = [
        pl.BlockSpec((1, TB), lambda t, *refs: (t, 0)),
        pl.BlockSpec((1, TB), lambda t, *refs: (t, 0)),
        pl.BlockSpec((nkv, TB, group, hd),
                     lambda t, *refs: (0, t, 0, 0)),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    scratch = [
        pltpu.VMEM((2, nkv, hd, C), kc.dtype),
        pltpu.VMEM((2, nkv, hd, C), vc.dtype),
    ]
    if quantized:
        inputs += [k_scale[layer], v_scale[layer]]
        in_specs += [pl.BlockSpec(memory_space=pl.ANY),
                     pl.BlockSpec(memory_space=pl.ANY)]
        scratch += [pltpu.VMEM((2, nkv, C), jnp.float32),
                    pltpu.VMEM((2, nkv, C), jnp.float32)]
    scratch.append(pltpu.SemaphoreType.DMA((2, 4 if quantized else 2)))

    # bytes per context position per head: the int8 path streams 1-byte
    # elements plus one fp32 scale per (head, position)
    pos_bytes = hd * jnp.dtype(kc.dtype).itemsize + (4 if quantized else 0)
    out = pl.pallas_call(
        functools.partial(_packed_kernel, S=S, bpc=bpc, bs=bs,
                          quantized=quantized),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(n_tiles,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((nkv, TB, group, hd),
                                   lambda t, *refs: (0, t, 0, 0)),
            scratch_shapes=scratch,
        ),
        out_shape=jax.ShapeDtypeStruct((nkv, Tp, group, hd), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        # 1x the stream's own context, NOT the reference's S-fold: each
        # tile visits at most its own segment's table (upper bound —
        # the causal frontier skips chunks beyond a tile's last token)
        cost_estimate=pl.CostEstimate(
            flops=2 * 2 * Tp * nh * hd * n_chunks * C,
            bytes_accessed=2 * n_tiles * nkv * n_chunks * C * pos_bytes,
            transcendentals=Tp * nh * n_chunks * C,
        ),
        interpret=interpret,
    )(block_tables, nchunks, chunk_base, next_seg, *inputs)
    out = out.transpose(1, 0, 2, 3).reshape(Tp, nh, hd)
    return out[:T].astype(q.dtype)
