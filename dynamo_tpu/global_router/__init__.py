"""Global router: the pool-level request plane above the frontends.

Ref: the reference's hierarchical `global_router` across pool namespaces
(SURVEY.md:111).  A pool is one namespace running its own workers +
frontend tier (agg or disagg); the global router discovers pools from
the same discovery plane everything else uses, classifies each request
by (ISL, predicted TTFT) / (context length, ITL load) with the
conditional-disagg thresholds, and forwards to the chosen pool's
frontend tier.  See pools.py (discovery), policy.py (classification),
service.py (the HTTP proxy process).
"""

from .policy import Decision, GlobalRouterConfig, PoolClassifier
from .pools import FrontendView, PoolDirectory, PoolView
from .service import GlobalRouterService

__all__ = [
    "Decision", "GlobalRouterConfig", "PoolClassifier",
    "FrontendView", "PoolDirectory", "PoolView",
    "GlobalRouterService",
]
