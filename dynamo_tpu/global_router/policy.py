"""Pool classification policy.

Ref: the reference's global router routes on (ISL, predicted TTFT) for
prefill-bound work and (context length, ITL headroom) for decode-bound
work, with the conditional-disagg thresholds (eff-ISL >= 2048 AND
prefill ratio >= 0.7 — conditional_disagg.rs:11-18) deciding which
CLASS of pool a request wants before latency picks the pool within the
class:

    request class          preferred pool class   tie-break within class
    ---------------------  ---------------------  ----------------------
    long prompt, short     disagg (dedicated      lowest predicted TTFT
    completion (prefill-   prefill tier)          (per-token EWMA * ISL)
    bound)
    everything else        agg (no prefill hop    lowest inflight per
    (decode/ITL-bound)     to pay for)            frontend, then TTFT

A preferred class with no live pool falls back to the other class —
degraded placement beats a 503.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from .pools import PoolView


@dataclass
class GlobalRouterConfig:
    # conditional-disagg thresholds (ref conditional_disagg.rs:11-18)
    disagg_min_isl: int = 2048
    disagg_ratio: float = 0.7
    # seconds of penalty per in-flight request per frontend: the ITL
    # proxy — a loaded pool predicts slower tokens even if its TTFT
    # history looks good
    load_penalty_s: float = 0.010
    # assumed completion length when the request doesn't say
    default_max_tokens: int = 256


@dataclass
class Decision:
    pool: str
    reason: str
    isl: int
    prefill_ratio: float
    scores: Dict[str, float] = field(default_factory=dict)

    def to_attrs(self) -> dict:
        return {"pool": self.pool, "pool_reason": self.reason,
                "pool_scores": self.scores}


class PoolClassifier:
    def __init__(self, config: GlobalRouterConfig = None):
        self.config = config or GlobalRouterConfig()

    def classify(self, pools: List[PoolView], isl: int,
                 max_tokens: int = 0) -> Decision:
        """Pick a pool for (isl, max_tokens) among pools that serve the
        model (caller pre-filters).  Raises ValueError on empty input."""
        if not pools:
            raise ValueError("no candidate pools")
        cfg = self.config
        osl = max_tokens or cfg.default_max_tokens
        ratio = isl / max(isl + osl, 1)
        prefill_bound = (isl >= cfg.disagg_min_isl
                         and ratio >= cfg.disagg_ratio)
        want = [p for p in pools if p.is_disagg == prefill_bound]
        fell_back = not want
        if fell_back:
            want = pools
        scores = {p.namespace: self._score(p, isl) for p in want}
        best = min(want, key=lambda p: scores[p.namespace])
        reason = ("disagg" if prefill_bound else "agg") + (
            "_fallback" if fell_back else "")
        if len(pools) == 1:
            reason = "only_pool"
        return Decision(pool=best.namespace, reason=reason, isl=isl,
                        prefill_ratio=round(ratio, 3),
                        scores={k: round(v, 6)
                                for k, v in scores.items()})

    def _score(self, pool: PoolView, isl: int) -> float:
        """Predicted time-to-first-token if routed to `pool` now: the
        TTFT EWMA model plus a load penalty per in-flight request per
        frontend (the ITL-headroom proxy)."""
        ttft = pool.predict_ttft(isl) or 0.0
        per_fe = pool.inflight / max(len(pool.frontends), 1)
        return ttft + self.config.load_penalty_s * per_fe


def estimate_isl(body: dict) -> int:
    """Token-count estimate from an OpenAI request body: exact for
    token-list prompts, ~4 chars/token for text (matches the byte
    tokenizer's block math closely enough for threshold routing)."""
    prompt = body.get("prompt")
    if isinstance(prompt, list):
        return len(prompt)
    if isinstance(prompt, str):
        return max(len(prompt) // 4, 1)
    total = 0
    for m in body.get("messages", ()) or ():
        c = m.get("content")
        if isinstance(c, str):
            total += len(c)
    return max(total // 4, 1)
