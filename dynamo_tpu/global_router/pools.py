"""Pool discovery: namespaces as first-class routing targets.

A pool is a namespace with at least one advertised frontend.  The
directory watches the SAME two discovery prefixes the rest of the stack
already populates — `v1/instances/**` for frontend instances (HttpService
registers `{ns}/frontend/http` with an `http_addr` in its metadata) and
`v1/mdc/**` for model cards (whose `runtime_config.role` says whether
the namespace runs a disagg prefill tier) — so pools need no new
registration protocol: labeling a deployment's namespace IS joining a
pool.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..protocols.model_card import ModelDeploymentCard
from ..runtime.discovery import INSTANCE_PREFIX, MDC_PREFIX, Instance

logger = logging.getLogger(__name__)


@dataclass
class FrontendView:
    instance_id: int
    http_addr: str
    pool: str


@dataclass
class PoolView:
    """One pool namespace: its frontend tier + the models it serves,
    plus the load/latency signals the service feeds back per forward."""

    namespace: str
    frontends: Dict[int, FrontendView] = field(default_factory=dict)
    models: Dict[str, set] = field(default_factory=dict)  # name -> roles
    inflight: int = 0
    # TTFT model for (ISL, predicted TTFT) classification: a per-token
    # EWMA (prefill scales with ISL) plus a flat EWMA floor for short
    # prompts; None until the first completed forward
    ttft_per_token_ewma_s: Optional[float] = None
    ttft_ewma_s: Optional[float] = None

    @property
    def is_disagg(self) -> bool:
        return any("prefill" in roles for roles in self.models.values())

    def serves(self, model: str) -> bool:
        return model in self.models

    def observe_ttft(self, isl: int, ttft_s: float, alpha: float = 0.2):
        def ewma(cur, x):
            return x if cur is None else (1 - alpha) * cur + alpha * x

        self.ttft_ewma_s = ewma(self.ttft_ewma_s, ttft_s)
        if isl > 0:
            self.ttft_per_token_ewma_s = ewma(
                self.ttft_per_token_ewma_s, ttft_s / isl)

    def predict_ttft(self, isl: int) -> Optional[float]:
        if self.ttft_per_token_ewma_s is not None:
            return self.ttft_per_token_ewma_s * max(isl, 1)
        return self.ttft_ewma_s

    def to_dict(self) -> dict:
        return {
            "namespace": self.namespace,
            "kind": "disagg" if self.is_disagg else "agg",
            "frontends": sorted(f.http_addr
                                for f in self.frontends.values()),
            "models": {m: sorted(r) for m, r in self.models.items()},
            "inflight": self.inflight,
            "predicted_ttft_ms_at_1k": (
                round(self.predict_ttft(1024) * 1000.0, 3)
                if self.predict_ttft(1024) is not None else None),
        }


class PoolDirectory:
    """Watches discovery and maintains the namespace -> PoolView map."""

    def __init__(self, runtime):
        self.runtime = runtime
        self._pools: Dict[str, PoolView] = {}
        self._cancel = asyncio.Event()
        self._inst_task: Optional[asyncio.Task] = None
        self._mdc_task: Optional[asyncio.Task] = None
        # discovery key -> (namespace, instance_id) / (namespace, model)
        self._inst_keys: Dict[str, tuple] = {}
        self._mdc_keys: Dict[str, tuple] = {}
        self.last_change_unix = time.time()

    async def start(self) -> "PoolDirectory":
        self._inst_task = asyncio.create_task(self._watch_instances())
        self._mdc_task = asyncio.create_task(self._watch_mdc())
        return self

    async def close(self) -> None:
        self._cancel.set()
        for t in (self._inst_task, self._mdc_task):
            if t is not None:
                t.cancel()

    # -- views -------------------------------------------------------------
    def pools(self) -> Dict[str, PoolView]:
        return self._pools

    def pools_for_model(self, model: str) -> List[PoolView]:
        return [p for p in self._pools.values()
                if p.serves(model) and p.frontends]

    def models(self) -> List[str]:
        seen = set()
        for p in self._pools.values():
            if p.frontends:
                seen.update(p.models)
        return sorted(seen)

    def _pool(self, namespace: str) -> PoolView:
        return self._pools.setdefault(namespace, PoolView(namespace))

    def _gc(self, namespace: str) -> None:
        p = self._pools.get(namespace)
        if p is not None and not p.frontends and not p.models:
            del self._pools[namespace]

    # -- watches -----------------------------------------------------------
    async def _watch_instances(self) -> None:
        try:
            async for ev in self.runtime.discovery.watch(
                INSTANCE_PREFIX + "/", cancel=self._cancel
            ):
                try:
                    self._apply_instance(ev)
                except Exception:
                    logger.exception("pool directory failed applying %s",
                                     ev)
        except asyncio.CancelledError:
            pass

    def _apply_instance(self, ev) -> None:
        if ev.type == "put" and ev.value:
            inst = Instance.from_dict(ev.value)
            if inst.component != "frontend" or inst.endpoint != "http":
                return
            addr = inst.metadata.get("http_addr") or inst.address
            if not addr:
                return
            self._pool(inst.namespace).frontends[inst.instance_id] = (
                FrontendView(inst.instance_id, addr, inst.namespace))
            self._inst_keys[ev.key] = (inst.namespace, inst.instance_id)
            self.last_change_unix = time.time()
        elif ev.type == "delete" and ev.key in self._inst_keys:
            ns, iid = self._inst_keys.pop(ev.key)
            pool = self._pools.get(ns)
            if pool is not None:
                pool.frontends.pop(iid, None)
                self._gc(ns)
            self.last_change_unix = time.time()

    async def _watch_mdc(self) -> None:
        try:
            async for ev in self.runtime.discovery.watch(
                MDC_PREFIX + "/", cancel=self._cancel
            ):
                try:
                    self._apply_mdc(ev)
                except Exception:
                    logger.exception("pool directory failed applying %s",
                                     ev)
        except asyncio.CancelledError:
            pass

    def _apply_mdc(self, ev) -> None:
        if ev.type == "put" and ev.value:
            mdc = ModelDeploymentCard.from_dict(ev.value)
            role = mdc.runtime_config.get("role", "both")
            self._pool(mdc.namespace).models.setdefault(
                mdc.name, set()).add(role)
            self._mdc_keys[ev.key] = (mdc.namespace, mdc.name, role)
            self.last_change_unix = time.time()
        elif ev.type == "delete" and ev.key in self._mdc_keys:
            ns, name, role = self._mdc_keys.pop(ev.key)
            pool = self._pools.get(ns)
            if pool is not None:
                # only drop the role if no OTHER card still claims it
                still = {r for (n2, m2, r) in self._mdc_keys.values()
                         if n2 == ns and m2 == name}
                roles = pool.models.get(name)
                if roles is not None:
                    roles.intersection_update(still)
                    if not roles:
                        pool.models.pop(name, None)
                self._gc(ns)
            self.last_change_unix = time.time()
