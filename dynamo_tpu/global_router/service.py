"""The global-router HTTP process: classify, pick a pool, forward.

One aiohttp server exposing the same OpenAI surface the frontends do.
Per request it estimates ISL from the body, classifies against the
live pool set (policy.py), picks a frontend inside the chosen pool by
power-of-two-choices on local in-flight counts, and proxies the request
byte-for-byte — streaming responses pass through untouched, so token
streams are identical to hitting the pool frontend directly.  The
forward stamps `x-dyn-pool` so the frontend's request tracker (and
therefore the `routed` hop + request_end record) names the pool.

Failure posture: a frontend that refuses the connection goes on a short
cooldown and the request retries the pool's other frontends before
502ing; a classifier fault (chaos seam `grouter.classify`) degrades to
round-robin over the model's pools — a policy bug must never drop
traffic.

Observability: `dynamo_grouter_*` metrics (per-pool route counts by
reason, classification latency, pool/frontend gauges) plus a background
scrape of each frontend's /metrics that re-exports the cross-replica
spread of `dynamo_router_overlap_staleness_ratio` per pool — the
replica-sync health signal: replicas sharing one slot view should agree
on staleness, so a wide spread means a replica's view has drifted.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from collections import Counter, deque
from typing import Dict, Optional

import aiohttp
from aiohttp import web

from .. import chaos
from .policy import Decision, GlobalRouterConfig, PoolClassifier, \
    estimate_isl
from .pools import PoolDirectory, PoolView

logger = logging.getLogger(__name__)

# request headers never forwarded (hop-by-hop / recomputed)
_DROP_HEADERS = frozenset({
    "host", "content-length", "connection", "keep-alive",
    "transfer-encoding", "upgrade", "te", "trailer", "expect",
})
FRONTEND_COOLDOWN_S = 2.0


class GlobalRouterService:
    def __init__(self, runtime, host: str = "0.0.0.0", port: int = 8080,
                 config: Optional[GlobalRouterConfig] = None,
                 staleness_scrape_s: float = 2.0):
        self.runtime = runtime
        self.host = host
        self.port = port
        self.config = config or GlobalRouterConfig()
        self.directory = PoolDirectory(runtime)
        self.classifier = PoolClassifier(self.config)
        self.staleness_scrape_s = staleness_scrape_s
        self._runner: Optional[web.AppRunner] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._scrape_task: Optional[asyncio.Task] = None
        self._cancel = asyncio.Event()
        self._fe_inflight: Dict[str, int] = {}   # http_addr -> count
        self._fe_down: Dict[str, float] = {}     # http_addr -> down-at
        self._routed: Counter = Counter()        # (pool, reason) -> n
        self._route_lat_s: deque = deque(maxlen=4096)
        self._staleness: Dict[str, dict] = {}    # pool -> scrape rollup
        self._rr = 0

        m = runtime.metrics.scoped(component="grouter")
        self._m = m
        m.counter("dynamo_grouter_routed_total",
                  "requests forwarded, by pool and classification reason",
                  ("pool", "reason"))
        m.counter("dynamo_grouter_forward_errors_total",
                  "forward attempts that failed (per pool)", ("pool",))
        m.counter("dynamo_grouter_classify_errors_total",
                  "classifier faults degraded to round-robin")
        m.gauge("dynamo_grouter_pools", "pools currently discovered")
        m.gauge("dynamo_grouter_pool_frontends",
                "frontend replicas per pool", ("pool",))
        m.gauge("dynamo_grouter_pool_inflight",
                "in-flight forwarded requests per pool", ("pool",))
        m.histogram("dynamo_grouter_classify_seconds",
                    "pool classification latency",
                    buckets=(1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 1e-2))
        m.histogram("dynamo_grouter_route_seconds",
                    "receive -> forward-started latency (classify + "
                    "frontend pick)",
                    buckets=(1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1))
        m.gauge("dynamo_grouter_staleness_spread",
                "max-min dynamo_router_overlap_staleness_ratio across a "
                "pool's frontend replicas (0 = replicas agree)",
                ("pool",))

        self.app = web.Application()
        self.app.router.add_post("/v1/chat/completions", self._handle)
        self.app.router.add_post("/v1/completions", self._handle)
        self.app.router.add_get("/v1/models", self.h_models)
        self.app.router.add_get("/health", self.h_health)
        self.app.router.add_get("/metrics", self.h_metrics)

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> "GlobalRouterService":
        await self.directory.start()
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=None, sock_connect=5.0))
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = self._runner.addresses[0][1]
        self._scrape_task = asyncio.create_task(self._staleness_loop())
        self.runtime.register_debug_source("grouter", self.debug_state)
        logger.info("global router on %s:%d", self.host, self.port)
        return self

    async def close(self) -> None:
        self._cancel.set()
        if self._scrape_task is not None:
            self._scrape_task.cancel()
        await self.directory.close()
        if self._session is not None:
            await self._session.close()
        if self._runner is not None:
            await self._runner.cleanup()

    # -- routes ------------------------------------------------------------
    async def h_health(self, request: web.Request) -> web.Response:
        return web.json_response({
            "status": "ok", "pools": len(self.directory.pools())})

    async def h_metrics(self, request: web.Request) -> web.Response:
        pools = self.directory.pools()
        self._m.set("dynamo_grouter_pools", float(len(pools)))
        for ns, p in pools.items():
            self._m.set("dynamo_grouter_pool_frontends",
                        float(len(p.frontends)), pool=ns)
        return web.Response(body=self.runtime.metrics.render(),
                            content_type="text/plain")

    async def h_models(self, request: web.Request) -> web.Response:
        return web.json_response({
            "object": "list",
            "data": [{"id": m, "object": "model"}
                     for m in self.directory.models()]})

    async def _handle(self, request: web.Request) -> web.StreamResponse:
        t0 = time.monotonic()
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "invalid JSON body"},
                                     status=400)
        model = body.get("model")
        pools = self.directory.pools_for_model(model) if model else []
        if not pools:
            return web.json_response(
                {"error": f"model {model!r} not served by any pool"},
                status=404)
        isl = estimate_isl(body)
        max_tokens = int(body.get("max_tokens") or 0)
        tc = time.monotonic()
        try:
            await chaos.ahit("grouter.classify", key=model)
            decision = self.classifier.classify(pools, isl, max_tokens)
        except Exception:
            # a policy fault must degrade, not drop: round-robin over
            # the model's pools and keep serving
            self._m.inc("dynamo_grouter_classify_errors_total")
            self._rr += 1
            pool = pools[self._rr % len(pools)]
            decision = Decision(pool=pool.namespace,
                                reason="classify_error_rr", isl=isl,
                                prefill_ratio=0.0)
        self._m.observe("dynamo_grouter_classify_seconds",
                        time.monotonic() - tc)
        pool = self.directory.pools().get(decision.pool)
        if pool is None or not pool.frontends:
            return web.json_response(
                {"error": f"pool {decision.pool} lost its frontends"},
                status=503)
        return await self._forward(request, body, pool, decision, t0)

    # -- forwarding --------------------------------------------------------
    def _pick_frontend(self, pool: PoolView) -> Optional[str]:
        """P2C on local in-flight counts, skipping cooled-down addrs
        (all-down falls back to ignoring the cooldown)."""
        now = time.monotonic()
        addrs = [f.http_addr for f in pool.frontends.values()]
        live = [a for a in addrs
                if now - self._fe_down.get(a, -1e9) > FRONTEND_COOLDOWN_S]
        cand = live or addrs
        if not cand:
            return None
        # deterministic P2C: the two least-loaded of a rotating pair
        if len(cand) > 2:
            self._rr += 1
            i = self._rr % len(cand)
            cand = [cand[i], cand[(i + 1) % len(cand)]]
        return min(cand, key=lambda a: self._fe_inflight.get(a, 0))

    async def _forward(self, request: web.Request, body: dict,
                       pool: PoolView, decision: Decision,
                       t0: float) -> web.StreamResponse:
        assert self._session is not None
        headers = {k: v for k, v in request.headers.items()
                   if k.lower() not in _DROP_HEADERS}
        headers["x-dyn-pool"] = pool.namespace
        headers["Content-Type"] = "application/json"
        raw = json.dumps(body).encode()
        tried = set()
        pool.inflight += 1
        self._m.set("dynamo_grouter_pool_inflight", float(pool.inflight),
                    pool=pool.namespace)
        try:
            for _ in range(max(len(pool.frontends), 1)):
                addr = self._pick_frontend(pool)
                if addr is None or addr in tried:
                    break
                tried.add(addr)
                url = f"http://{addr}{request.rel_url.path}"
                self._fe_inflight[addr] = (
                    self._fe_inflight.get(addr, 0) + 1)
                try:
                    return await self._stream_through(
                        request, url, raw, headers, pool, decision,
                        t0)
                except (aiohttp.ClientConnectionError, OSError,
                        asyncio.TimeoutError):
                    # connection-level failure before any byte reached
                    # the client: cool the frontend down and try the
                    # pool's next replica
                    self._fe_down[addr] = time.monotonic()
                    self._m.inc("dynamo_grouter_forward_errors_total",
                                pool=pool.namespace)
                    logger.warning("frontend %s unreachable, retrying "
                                   "in pool %s", addr, pool.namespace)
                finally:
                    self._fe_inflight[addr] -= 1
            return web.json_response(
                {"error": f"no reachable frontend in pool "
                          f"{pool.namespace}"}, status=502)
        finally:
            pool.inflight -= 1
            self._m.set("dynamo_grouter_pool_inflight",
                        float(pool.inflight), pool=pool.namespace)

    async def _stream_through(self, request: web.Request, url: str,
                              raw: bytes, headers: dict, pool: PoolView,
                              decision: Decision,
                              t0: float) -> web.StreamResponse:
        assert self._session is not None
        t_send = time.monotonic()
        async with self._session.post(url, data=raw,
                                      headers=headers) as upstream:
            # forward started: route latency is classify + pick + connect
            self._route_lat_s.append(t_send - t0)
            self._m.observe("dynamo_grouter_route_seconds", t_send - t0)
            self._routed[(pool.namespace, decision.reason)] += 1
            self._m.inc("dynamo_grouter_routed_total",
                        pool=pool.namespace, reason=decision.reason)
            resp = web.StreamResponse(status=upstream.status)
            ct = upstream.headers.get("Content-Type")
            if ct:
                resp.headers["Content-Type"] = ct
            await resp.prepare(request)
            first = True
            try:
                async for chunk in upstream.content.iter_any():
                    if first:
                        pool.observe_ttft(decision.isl,
                                          time.monotonic() - t_send)
                        first = False
                    await resp.write(chunk)
            except (aiohttp.ClientConnectionError, OSError,
                    asyncio.TimeoutError):
                # once bytes reached the client a retry would corrupt
                # the stream: end it (the client sees a truncated SSE
                # stream — the same contract as a dying frontend)
                logger.warning("upstream died mid-stream (%s)", url)
            await resp.write_eof()
            return resp

    # -- replica-sync health scrape ---------------------------------------
    async def _staleness_loop(self) -> None:
        try:
            while not self._cancel.is_set():
                await asyncio.sleep(self.staleness_scrape_s)
                for ns, pool in list(self.directory.pools().items()):
                    await self._scrape_pool(ns, pool)
        except asyncio.CancelledError:
            pass

    async def _scrape_pool(self, ns: str, pool: PoolView) -> None:
        per_fe: Dict[str, float] = {}
        for fe in list(pool.frontends.values()):
            try:
                assert self._session is not None
                async with self._session.get(
                    f"http://{fe.http_addr}/metrics",
                    timeout=aiohttp.ClientTimeout(total=2.0),
                ) as r:
                    text = await r.text()
                val = _parse_staleness(text)
                if val is not None:
                    per_fe[fe.http_addr] = val
            except Exception:
                continue  # an unreachable replica just skips one sample
        if per_fe:
            spread = (max(per_fe.values()) - min(per_fe.values())
                      if len(per_fe) > 1 else 0.0)
            self._m.set("dynamo_grouter_staleness_spread", spread,
                        pool=ns)
            self._staleness[ns] = {
                "per_frontend": {a: round(v, 4)
                                 for a, v in per_fe.items()},
                "spread": round(spread, 4),
            }

    # -- introspection -----------------------------------------------------
    def route_latency_quantiles(self) -> dict:
        lat = sorted(self._route_lat_s)
        if not lat:
            return {"count": 0}

        def q(p):
            return round(lat[min(int(p * len(lat)), len(lat) - 1)] * 1e3,
                         3)

        return {"count": len(lat), "p50_ms": q(0.50), "p99_ms": q(0.99),
                "max_ms": round(lat[-1] * 1e3, 3)}

    def debug_state(self) -> dict:
        return {
            "kind": "global_router",
            "pools": {ns: p.to_dict()
                      for ns, p in self.directory.pools().items()},
            "routed": {f"{pool}/{reason}": n
                       for (pool, reason), n in self._routed.items()},
            "route_latency": self.route_latency_quantiles(),
            "staleness": self._staleness,
        }


def _parse_staleness(metrics_text: str) -> Optional[float]:
    """Pull dynamo_router_overlap_staleness_ratio out of a Prometheus
    text exposition; the max across label sets (one per served model)
    is the replica's staleness."""
    vals = []
    for line in metrics_text.splitlines():
        if (line.startswith("dynamo_router_overlap_staleness_ratio")
                and not line.startswith("#")):
            try:
                vals.append(float(line.rsplit(None, 1)[-1]))
            except ValueError:
                continue
    return max(vals) if vals else None
