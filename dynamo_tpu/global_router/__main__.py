"""`python -m dynamo_tpu.global_router` — the pool-level request plane.

Runs the global router as its own process: discovers pool namespaces
from the shared discovery plane, classifies requests on (ISL, predicted
TTFT) with the conditional-disagg thresholds, and proxies to the chosen
pool's frontend tier.  Deploy one (or a few, behind any TCP LB — the
process is stateless apart from latency EWMAs) per fleet.
"""

import argparse
import asyncio

from .. import obs
from ..runtime import DistributedRuntime
from ..runtime.logging import setup_logging
from .policy import GlobalRouterConfig
from .service import GlobalRouterService


def build_args() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("dynamo_tpu.global_router")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8080)
    # same conditional-disagg thresholds the frontends use
    # (conditional_disagg.rs:11-18), applied one level up: which CLASS
    # of pool a request wants
    p.add_argument("--disagg-min-isl", type=int, default=2048)
    p.add_argument("--disagg-ratio", type=float, default=0.7)
    p.add_argument("--load-penalty-ms", type=float, default=10.0,
                   help="predicted-TTFT penalty per in-flight request "
                        "per frontend (the ITL-headroom proxy)")
    p.add_argument("--staleness-scrape-s", type=float, default=2.0,
                   help="interval of the frontend /metrics scrape that "
                        "feeds dynamo_grouter_staleness_spread")
    return p


async def main() -> None:
    setup_logging()
    obs.install_from_env()
    args = build_args().parse_args()
    rt = await DistributedRuntime.detached().start()
    config = GlobalRouterConfig(
        disagg_min_isl=args.disagg_min_isl,
        disagg_ratio=args.disagg_ratio,
        load_penalty_s=args.load_penalty_ms / 1000.0,
    )
    service = await GlobalRouterService(
        rt, host=args.host, port=args.port, config=config,
        staleness_scrape_s=args.staleness_scrape_s).start()
    print(f"ready port={service.port}", flush=True)
    try:
        await rt.root_token.wait_killed()
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    await service.close()
    await rt.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
