"""`python -m dynamo_tpu.frontend` — OpenAI HTTP server + preprocessor +
router in one process (ref: components/src/dynamo/frontend/main.py)."""

import argparse
import asyncio
import os

from .. import obs
from ..runtime import DistributedRuntime, RouterMode
from ..runtime.logging import setup_logging
from .service import HttpService, ModelManager, ModelWatcher


def build_args() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("dynamo_tpu.frontend")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument(
        "--router-mode", default="round_robin",
        choices=["random", "round_robin", "least_loaded", "p2c", "kv"],
    )
    p.add_argument("--busy-threshold", type=int, default=None)
    p.add_argument("--kv-overlap-score-weight", type=float, default=1.0)
    p.add_argument("--router-temperature", type=float, default=0.0)
    # conditional disagg thresholds (ref: conditional_disagg.rs:11-18)
    p.add_argument("--disagg-min-isl", type=int, default=2048)
    p.add_argument("--disagg-ratio", type=float, default=0.7)
    p.add_argument("--always-disagg", action="store_true")
    p.add_argument("--grpc-port", type=int, default=0,
                   help="serve the KServe v2 gRPC inference protocol on "
                        "this port (0 = disabled)")
    p.add_argument(
        "--session-affinity-ttl", type=float,
        default=float(os.environ.get("DYN_SESSION_AFFINITY_TTL", 0)) or None,
        help="seconds an idle agent session stays pinned to its worker "
             "(0/unset disables sticky sessions)")
    # SLO plane (obs/slo.py): targets drive the goodput gauge,
    # multi-window burn rate, and the planner's slo_metrics feed
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="TTFT target in ms: a request is 'good' only if "
                        "its first token beat this (goodput/burn-rate "
                        "gauges light up when set)")
    p.add_argument("--slo-itl-ms", type=float, default=None,
                   help="per-request mean inter-token-latency target in "
                        "ms for the goodput check")
    p.add_argument("--slo-objective", type=float, default=0.99,
                   help="SLO objective (good-request fraction) the "
                        "burn-rate error budget derives from")
    # pool membership (global_router/): a pool frontend serves only its
    # own namespace and registers itself so the global router finds it
    p.add_argument("--pool-scoped", action="store_true",
                   help="serve only models in this process's namespace "
                        "(DYN_NAMESPACE) — the pool-frontend contract")
    p.add_argument("--advertise", action="store_true",
                   help="register this frontend in discovery even "
                        "without a system-status port, so the global "
                        "router can route to it")
    return p


async def main() -> None:
    setup_logging()
    # timeline tracing (obs/): DYN_TRACE=1 installs the process
    # tracer; DYN_TRACE_OUT gets a Chrome trace dump at exit
    obs.install_from_env()
    args = build_args().parse_args()
    rt = await DistributedRuntime.detached().start()
    manager = ModelManager()

    make_route = None
    mode = RouterMode(args.router_mode)
    if mode == RouterMode.KV:
        from ..router.kv_router import make_kv_route_factory

        make_route = make_kv_route_factory(
            rt,
            overlap_score_weight=args.kv_overlap_score_weight,
            temperature=args.router_temperature,
        )
    from ..disagg.prefill_router import ConditionalDisaggConfig

    disagg_config = ConditionalDisaggConfig(
        min_effective_isl=args.disagg_min_isl,
        min_effective_ratio=args.disagg_ratio,
        always_remote=args.always_disagg,
    )
    # "0 disables": normalize sub-second/zero TTLs to off here, where the
    # error is visible, instead of raising per-MDC inside the watcher loop
    affinity_ttl = args.session_affinity_ttl
    if affinity_ttl is not None and affinity_ttl < 1.0:
        affinity_ttl = None
    watcher = await ModelWatcher(
        rt, manager, router_mode=mode, make_route=make_route,
        disagg_config=disagg_config,
        session_affinity_ttl=affinity_ttl,
        namespaces={rt.config.namespace} if args.pool_scoped else None,
    ).start()
    from ..obs.slo import SloConfig

    service = await HttpService(
        rt, manager, host=args.host, port=args.port,
        busy_threshold=args.busy_threshold,
        slo=SloConfig(ttft_ms=args.slo_ttft_ms, itl_ms=args.slo_itl_ms,
                      objective=args.slo_objective),
        advertise=True if args.advertise else None,
    ).start()
    grpc_service = None
    if args.grpc_port:
        from .kserve import KserveGrpcService

        grpc_service = await KserveGrpcService(
            rt, manager, host=args.host, port=args.grpc_port,
            resolver=service._resolve_pipeline).start()
    print(f"ready port={args.port}", flush=True)
    try:
        await rt.root_token.wait_killed()
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    if grpc_service is not None:
        await grpc_service.close()
    await service.close()
    await watcher.close()
    await rt.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
